"""Index mappings: field types, dynamic mapping, document parsing.

Behavioral parity targets from the reference mapper layer
(reference: server/.../index/mapper/MapperService.java:52,
DocumentParser.java:50 — JSON -> typed fields; dynamic mapping rules in
DynamicFieldsBuilder). Supported types are the subset needed by the baseline
configs plus the common primitives; each maps to a columnar device layout:

  text         -> postings (blocked CSR) + norms; no docvalues
  keyword      -> postings (single token) + ordinal docvalues
  long/integer/short/byte -> int64 docvalues
  double/float/half_float -> float docvalues
  date         -> int64 epoch-millis docvalues
  boolean      -> int64 {0,1} docvalues
  dense_vector -> [N, dims] matrix for MXU scoring

Dynamic mapping mirrors ES defaults: JSON string -> `text` with a `.keyword`
sub-field (ignore_above 256), integral number -> `long`, float -> `float`,
bool -> `boolean`, ISO-8601-looking string -> `date`
(reference: index/mapper/DynamicFieldsBuilder.java).
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass, field

from ..analysis import get_analyzer, Analyzer
from ..utils.errors import MapperParsingError

TEXT_TYPES = {"text"}
# flattened is the whole-object keyword family: every leaf value indexes as
# an exact term under the root field, every leaf path as a dynamic keyword
# sub-field (reference behavior: x-pack flattened FlattenedFieldMapper)
KEYWORD_TYPES = {"keyword", "flattened"}
IP_TYPES = {"ip"}
INT_TYPES = {"long", "integer", "short", "byte"}
FLOAT_TYPES = {"double", "float", "half_float", "rank_feature"}
NUMERIC_TYPES = INT_TYPES | FLOAT_TYPES
DATE_TYPES = {"date"}
DATE_NANOS_TYPES = {"date_nanos"}
BOOL_TYPES = {"boolean"}
VECTOR_TYPES = {"dense_vector"}
COMPLETION_TYPES = {"completion"}
GEO_TYPES = {"geo_point"}
ALL_TYPES = (
    TEXT_TYPES | KEYWORD_TYPES | NUMERIC_TYPES | DATE_TYPES | DATE_NANOS_TYPES
    | BOOL_TYPES | VECTOR_TYPES | IP_TYPES
    | COMPLETION_TYPES | GEO_TYPES | {"object", "nested", "percolator"}
)

_INT_BOUNDS = {
    "long": (-(2**63), 2**63 - 1),
    "integer": (-(2**31), 2**31 - 1),
    "short": (-(2**15), 2**15 - 1),
    "byte": (-128, 127),
}

# strict_date_optional_time detection for dynamic date mapping
_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}(:\d{2}(\.\d+)?)?(Z|[+-]\d{2}:?\d{2})?)?$")


def parse_date_to_millis(value) -> int:
    """Parse ES default `strict_date_optional_time||epoch_millis` to epoch ms
    (reference: server/.../common/time/DateFormatters.java default format)."""
    if isinstance(value, bool):
        raise MapperParsingError(f"failed to parse date [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    if isinstance(value, str):
        s = value.strip()
        # date_optional_time admits year and year-month prefixes; try the
        # calendar interpretations before falling back to epoch_millis,
        # matching ES's left-to-right format list.
        if re.fullmatch(r"\d{4}", s):
            return int(_dt.datetime(int(s), 1, 1, tzinfo=_dt.timezone.utc).timestamp() * 1000)
        if re.fullmatch(r"\d{4}-\d{2}", s):
            y, mo = s.split("-")
            return int(_dt.datetime(int(y), int(mo), 1, tzinfo=_dt.timezone.utc).timestamp() * 1000)
        try:
            s2 = s.replace("Z", "+00:00")
            if " " in s2 and "T" not in s2:
                s2 = s2.replace(" ", "T", 1)
            # normalize no-colon utc offsets ("+0100" -> "+01:00")
            s2 = re.sub(r"([+-]\d{2})(\d{2})$", r"\1:\2", s2)
            dt = _dt.datetime.fromisoformat(s2)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            pass
        if re.fullmatch(r"-?\d+", s):
            return int(s)
    raise MapperParsingError(f"failed to parse date value [{value}]")


# java DateTimeFormatter tokens -> strptime, longest-first (case matters:
# MM = month, mm = minute). Covers the pattern vocabulary used by the
# reference's own test suites; unknown letters fail the pattern (and the
# next ||-alternative is tried).
_JAVA_TOKENS = [
    ("yyyy", "%Y"), ("uuuu", "%Y"), ("yy", "%y"),
    ("MM", "%m"), ("dd", "%d"), ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
    ("SSS", "%f"), ("epoch_millis", None), ("epoch_second", None),
]


def _java_to_strptime(pattern: str) -> str | None:
    out = []
    i = 0
    while i < len(pattern):
        for tok, py in _JAVA_TOKENS:
            if py and pattern.startswith(tok, i):
                out.append(py)
                i += len(tok)
                break
        else:
            c = pattern[i]
            if c.isalpha():
                return None  # unsupported token letter
            out.append("%%" if c == "%" else c)
            i += 1
    return "".join(out)


def parse_date_with_formats(value, formats: str) -> int:
    """Custom `format` mapping parameter: try each ||-alternative in order
    (reference: DateFieldMapper with a custom DateFormatter list)."""
    for fmt in formats.split("||"):
        fmt = fmt.strip()
        if fmt in ("epoch_millis",):
            try:
                return int(value)
            except (TypeError, ValueError):
                continue
        if fmt == "epoch_second":
            try:
                return int(value) * 1000
            except (TypeError, ValueError):
                continue
        if fmt in ("strict_date_optional_time", "date_optional_time",
                   "strict_date_optional_time_nanos", "basic_date_time",
                   "date_time", "strict_date_time"):
            try:
                return parse_date_to_millis(value)
            except MapperParsingError:
                continue
        py = _java_to_strptime(fmt)
        if py is None or not isinstance(value, str):
            continue
        try:
            dt = _dt.datetime.strptime(value, py)
            return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date value [{value}]")


def format_date_millis(ms: int, formats: str | None) -> str | int:
    """Render epoch millis in the mapping's (first) format."""
    fmt = (formats or "strict_date_optional_time").split("||")[0].strip()
    if fmt == "epoch_millis":
        return int(ms)
    if fmt == "epoch_second":
        return int(ms) // 1000
    dt = _dt.datetime.fromtimestamp(ms / 1000.0, tz=_dt.timezone.utc)
    py = _java_to_strptime(fmt)
    if py is not None and "date_optional_time" not in fmt:
        out = dt.strftime(py)
        if "%f" in py:  # java SSS is milliseconds, strftime %f is micros
            out = out.replace(dt.strftime("%f"), dt.strftime("%f")[:3])
        return out
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def parse_date_to_nanos(value) -> int:
    """date_nanos: epoch NANOseconds, preserving sub-millisecond digits
    (reference: DateFieldMapper.Resolution.NANOSECONDS)."""
    if isinstance(value, bool):
        raise MapperParsingError(f"failed to parse date [{value}]")
    if isinstance(value, (int, float)):
        # numeric input is epoch millis in the default format
        return int(value) * 1_000_000
    if isinstance(value, str):
        s = value.strip()
        m = re.fullmatch(
            r"(.*[T ]\d{2}:\d{2}:\d{2})\.(\d{4,9})(Z|[+-]\d{2}:?\d{2})?", s
        )
        if m:
            frac = m.group(2)
            nanos_frac = int(frac.ljust(9, "0"))
            base = m.group(1) + (m.group(3) or "")
            return parse_date_to_millis(base) * 1_000_000 + nanos_frac
        if re.fullmatch(r"-?\d+", s):
            return int(s) * 1_000_000
        return parse_date_to_millis(s) * 1_000_000
    raise MapperParsingError(f"failed to parse date value [{value}]")


def format_date_nanos(nanos: int) -> str:
    secs, frac_ns = divmod(int(nanos), 1_000_000_000)
    dt = _dt.datetime.fromtimestamp(secs, tz=_dt.timezone.utc)
    frac = f"{frac_ns:09d}".rstrip("0") or "0"
    return dt.strftime("%Y-%m-%dT%H:%M:%S") + f".{frac}Z"


def ip_sort_key(s: str) -> bytes:
    """Total order over mixed v4/v6: v4 compares as its v6-mapped form
    (reference: ES encodes every ip as a 16-byte InetAddress point)."""
    import ipaddress

    ip = ipaddress.ip_address(s)
    if ip.version == 4:
        ip = ipaddress.ip_address(f"::ffff:{s}")
    return ip.packed


@dataclass
class FieldType:
    name: str  # full dotted path
    type: str
    analyzer: str = "standard"
    search_analyzer: str | None = None
    index: bool = True
    doc_values: bool = True
    ignore_above: int | None = None  # keyword only
    dims: int | None = None  # dense_vector only
    similarity: str = "cosine"  # dense_vector: cosine|dot_product|l2_norm
    # ANN index options (dense_vector): partitions for the IVF index (the
    # TPU-native ANN; hnsw/int8_hnsw index_options map onto it)
    ann_nlist: int | None = None
    # selection-scan quantization tier: int8 (per-vector scale/offset)
    # or bf16 (split-bf16 pair) — ann/ tier selection
    ann_quant: str = "int8"
    # date/date_nanos "format" mapping parameter: ||-separated list of
    # java patterns / named formats (DateFieldMapper custom formats)
    format: str | None = None
    # skip (and record in _ignored) unparseable values instead of failing
    # the whole document (the ignore_malformed mapping parameter)
    ignore_malformed: bool = False
    fields: dict = field(default_factory=dict)  # sub-fields (e.g. .keyword)
    # retained mapping attributes with no behavior of their own at the
    # field level: time_series_dimension / time_series_metric (TSDB mode
    # reads them — index/tsdb.py; the reference stores them on the mapper)
    extra: dict = field(default_factory=dict)

    _analyzer_obj: Analyzer | None = None
    # memoized BatchedAnalyzer (analysis/batched.py); keyed to the
    # analyzer object's identity so an analysis-settings update that
    # resets _analyzer_obj invalidates this too
    _batched_obj: object | None = None

    def get_analyzer(self) -> Analyzer:
        if self._analyzer_obj is None:
            reg = getattr(self, "_registry", None) or {}
            self._analyzer_obj = reg.get(self.analyzer) or get_analyzer(self.analyzer)
        return self._analyzer_obj

    def get_batched_analyzer(self):
        """Vectorized counterpart of get_analyzer(), memoized the same
        way. The identity check (not just None) means even a stale memo
        that survived a direct _analyzer_obj reset rebuilds correctly."""
        from ..analysis.batched import BatchedAnalyzer

        an = self.get_analyzer()
        ba = self._batched_obj
        if ba is None or ba.analyzer is not an:
            ba = self._batched_obj = BatchedAnalyzer(an)
        return ba

    def get_search_analyzer(self) -> Analyzer:
        if self.search_analyzer:
            reg = getattr(self, "_registry", None) or {}
            return reg.get(self.search_analyzer) or get_analyzer(self.search_analyzer)
        return self.get_analyzer()

    def to_dict(self) -> dict:
        d: dict = {"type": self.type}
        if self.type in TEXT_TYPES and self.analyzer != "standard":
            d["analyzer"] = self.analyzer
        if self.type in VECTOR_TYPES:
            d["dims"] = self.dims
            d["similarity"] = self.similarity
        if self.ignore_above is not None:
            d["ignore_above"] = self.ignore_above
        d.update(self.extra)
        if self.fields:
            d["fields"] = {
                k: sub.to_dict() for k, sub in self.fields.items()
            }
        return d


class Mappings:
    """Mutable field-type registry for one index; merge-only like the
    reference (`MapperService.merge` — new fields may be added, existing
    types may not change)."""

    _TOP_LEVEL_KEYS = {"properties", "dynamic", "_source", "_meta",
                       "dynamic_templates", "_routing",
                       "_data_stream_timestamp"}

    def __init__(self, mapping_dict: dict | None = None, dynamic: str = "true"):
        self.fields: dict[str, FieldType] = {}
        # nested object paths (reference: ObjectMapper nested=true; fields
        # under these paths additionally index into the parent doc here —
        # the include_in_parent behavior — while `nested` queries match
        # per-object against the stored source)
        self.nested_paths: set[str] = set()
        # per-index custom analyzers (settings `analysis` section)
        self.analysis_registry: dict[str, Analyzer] = {}
        # bumped on every set_analysis: query-time analysis is part of a
        # parsed query's identity, so the shard request cache folds this
        # generation into its keys (a synonym-set reload changes results
        # with no index write — reference ReloadableCustomAnalyzer)
        self.analysis_generation = 0
        # "true" | "false" | "strict" (ES `dynamic` mapping parameter)
        self.dynamic = dynamic
        # `_routing: {required: true}` (RoutingFieldMapper): stored so the
        # TSDB mode check can forbid it (index/tsdb.py)
        self.routing_required = bool(
            ((mapping_dict or {}).get("_routing") or {}).get("required"))
        # `_data_stream_timestamp` meta field (DataStreamTimestampFieldMapper)
        # — raw config kept for TSDB validation; echo flag set by tsdb mode
        self.ds_timestamp = (mapping_dict or {}).get("_data_stream_timestamp")
        self._ds_timestamp_echo = False
        if mapping_dict:
            if mapping_dict.keys() & self._TOP_LEVEL_KEYS or not mapping_dict:
                props = mapping_dict.get("properties", {})
            else:
                props = mapping_dict  # bare properties map shorthand
            self._parse_properties(props, prefix="")
            dyn = mapping_dict.get("dynamic", dynamic)
            self.dynamic = {True: "true", False: "false"}.get(dyn, str(dyn))

    def set_analysis(self, registry: dict[str, Analyzer]) -> None:
        """Attach custom analyzers built from index settings; field types
        resolve names through this registry before the builtins."""
        self.analysis_generation += 1
        self.analysis_registry = registry or {}
        for ft in self.fields.values():
            ft._registry = self.analysis_registry
            ft._analyzer_obj = None
            ft._batched_obj = None
            for sub in ft.fields.values():
                sub._registry = self.analysis_registry
                sub._analyzer_obj = None
                sub._batched_obj = None

    # ---- mapping definition parsing -------------------------------------

    def _parse_properties(self, props: dict, prefix: str):
        for name, spec in props.items():
            full = f"{prefix}{name}"
            if not isinstance(spec, dict):
                raise MapperParsingError(f"invalid mapping for field [{full}]")
            ftype = spec.get("type")
            if ftype is None and "properties" in spec:
                self._parse_properties(spec["properties"], prefix=f"{full}.")
                continue
            if ftype not in ALL_TYPES:
                raise MapperParsingError(f"no handler for type [{ftype}] declared on field [{full}]")
            if ftype == "object":
                self._parse_properties(spec.get("properties", {}), prefix=f"{full}.")
                continue
            if ftype == "nested":
                self.nested_paths.add(full)
                self._parse_properties(spec.get("properties", {}), prefix=f"{full}.")
                continue
            ft = FieldType(
                name=full,
                type=ftype,
                analyzer=spec.get("analyzer", "standard"),
                search_analyzer=spec.get("search_analyzer"),
                index=spec.get("index", True),
                doc_values=spec.get("doc_values", ftype not in TEXT_TYPES),
                ignore_above=spec.get("ignore_above"),
                dims=spec.get("dims"),
                similarity=spec.get("similarity", "cosine"),
                format=spec.get("format"),
                ignore_malformed=bool(spec.get("ignore_malformed", False)),
                extra={k: spec[k] for k in
                       ("time_series_dimension", "time_series_metric")
                       if k in spec},
            )
            ft._registry = self.analysis_registry
            if ftype == "dense_vector" and not ft.dims:
                raise MapperParsingError(f"dense_vector field [{full}] requires [dims]")
            if ftype == "dense_vector":
                io = spec.get("index_options") or {}
                # hnsw/int8_hnsw request ANN; the TPU-native ANN is IVF
                # (nlist from m, or explicit "nlist" for type "ivf")
                if io.get("type") in ("hnsw", "int8_hnsw", "int4_hnsw", "ivf"):
                    # 0 = auto (sqrt(N) at pack-build time)
                    ft.ann_nlist = int(io.get("nlist", 0))
                    # scan tier: explicit "quantization" for type "ivf";
                    # hnsw maps to bf16 (full-ish precision selection),
                    # int8_hnsw/int4_hnsw to the int8 tier
                    quant = io.get("quantization") or (
                        "bf16" if io.get("type") == "hnsw" else "int8")
                    if quant not in ("int8", "bf16"):
                        raise MapperParsingError(
                            f"dense_vector [{full}] index_options "
                            f"quantization must be int8|bf16, got [{quant}]")
                    ft.ann_quant = quant
            for sub_name, sub_spec in spec.get("fields", {}).items():
                sub = FieldType(
                    name=f"{full}.{sub_name}",
                    type=sub_spec.get("type", "keyword"),
                    analyzer=sub_spec.get("analyzer", "standard"),
                    ignore_above=sub_spec.get("ignore_above"),
                )
                sub._registry = self.analysis_registry
                ft.fields[sub_name] = sub
                self.fields[sub.name] = sub
            self.fields[full] = ft

    def merge(self, mapping_dict: dict):
        other = Mappings(mapping_dict)
        for name, ft in other.fields.items():
            existing = self.fields.get(name)
            if existing is not None and existing.type != ft.type:
                raise MapperParsingError(
                    f"mapper [{name}] cannot be changed from type "
                    f"[{existing.type}] to [{ft.type}]"
                )
            if existing is None:
                self.fields[name] = ft
            else:
                # adopt new sub-fields onto the existing parent so document
                # parsing populates them (MapperService.merge adds new
                # multi-fields to existing mappers)
                for sub_name, sub in ft.fields.items():
                    if sub_name not in existing.fields:
                        existing.fields[sub_name] = sub
                        self.fields[sub.name] = sub

    # ---- dynamic mapping -------------------------------------------------

    def _dynamic_field(self, name: str, value) -> FieldType | None:
        if isinstance(value, bool):
            ft = FieldType(name, "boolean")
        elif isinstance(value, int):
            ft = FieldType(name, "long")
        elif isinstance(value, float):
            ft = FieldType(name, "float")
        elif isinstance(value, str):
            if _DATE_RE.match(value.strip()):
                ft = FieldType(name, "date")
            else:
                ft = FieldType(name, "text")
                kw = FieldType(f"{name}.keyword", "keyword", ignore_above=256)
                ft.fields["keyword"] = kw
                self.fields[kw.name] = kw
        else:
            return None
        self.fields[name] = ft
        return ft

    # ---- document parsing ------------------------------------------------

    def parse_document(self, source: dict) -> dict[str, list]:
        """Flatten a JSON document into {field_path: [values]} according to
        the mappings, adding dynamic mappings as needed. Arrays flatten into
        multiple values of the same field (ES semantics: an array is just a
        multi-valued field)."""
        out: dict[str, list] = {}
        self._parse_obj(source, "", out)
        return out

    def _parse_obj(self, obj: dict, prefix: str, out: dict):
        for key, value in obj.items():
            full = f"{prefix}{key}"
            self._parse_value(full, value, out)

    def _parse_value(self, full: str, value, out: dict):
        if value is None:
            return
        ft_pre = self.fields.get(full)
        if ft_pre is not None and ft_pre.type in ("completion", "percolator",
                                                  "geo_point"):
            # completion/percolator values keep their raw shape; the pack
            # builder stores them host-side
            out.setdefault(full, []).append(value)
            return
        if ft_pre is not None and ft_pre.type == "flattened" and isinstance(value, dict):
            self._flatten_leaves(ft_pre, full, "", value, out)
            return
        if isinstance(value, dict):
            self._parse_obj(value, f"{full}.", out)
            return
        if isinstance(value, list):
            for v in value:
                self._parse_value(full, v, out)
            return
        ft = self.fields.get(full)
        if ft is None:
            if self.dynamic == "strict":
                raise MapperParsingError(
                    f"mapping set to strict, dynamic introduction of [{full}] is not allowed"
                )
            if self.dynamic == "false":
                return
            ft = self._dynamic_field(full, value)
            if ft is None:
                return
        try:
            coerced = self._coerce(ft, value)
        except MapperParsingError:
            if not ft.ignore_malformed:
                raise
            # malformed value skipped; the doc records which fields were
            # ignored in the _ignored metadata field (reference behavior:
            # IgnoredFieldMapper + the ignore_malformed mapping parameter)
            ig = self.fields.get("_ignored")
            if ig is None:
                ig = self.fields["_ignored"] = FieldType(
                    "_ignored", "keyword", index=False
                )
            vals = out.setdefault("_ignored", [])
            if ft.name not in vals:
                vals.append(ft.name)
            return
        out.setdefault(full, []).append(coerced)
        for sub in ft.fields.values():
            out.setdefault(sub.name, []).append(self._coerce(sub, value))

    def _flatten_leaves(self, root: FieldType, full: str, sub: str, value, out):
        """flattened object: leaves index as keywords under the root field
        AND under per-key dynamic keyword sub-fields (keyed access)."""
        if isinstance(value, dict):
            for k, v in value.items():
                self._flatten_leaves(root, full, f"{sub}.{k}" if sub else k, v, out)
            return
        if isinstance(value, list):
            for v in value:
                self._flatten_leaves(root, full, sub, v, out)
            return
        if value is None:
            return
        sval = ("true" if value else "false") if isinstance(value, bool) else str(value)
        out.setdefault(full, []).append(sval)
        if sub:
            key_field = f"{full}.{sub}"
            if key_field not in self.fields:
                self.fields[key_field] = FieldType(
                    key_field, "keyword", index=root.index,
                    doc_values=root.doc_values,
                )
            out.setdefault(key_field, []).append(sval)

    @staticmethod
    def _coerce(ft: FieldType, value):
        t = ft.type
        if t in TEXT_TYPES or t in KEYWORD_TYPES:
            if isinstance(value, bool):
                return "true" if value else "false"
            return str(value)
        if t in IP_TYPES:
            import ipaddress

            try:
                return str(ipaddress.ip_address(str(value)))
            except ValueError:
                raise MapperParsingError(
                    f"failed to parse field [{ft.name}] of type [ip]: "
                    f"'{value}' is not an IP string literal."
                )
        if t in DATE_NANOS_TYPES:
            return parse_date_to_nanos(value)
        if t in INT_TYPES:
            try:
                iv = int(value)
            except (TypeError, ValueError):
                raise MapperParsingError(f"failed to parse field [{ft.name}] of type [{t}]: [{value}]")
            lo, hi = _INT_BOUNDS[t]
            if not (lo <= iv <= hi):
                raise MapperParsingError(f"value [{value}] out of range for type [{t}]")
            return iv
        if t in FLOAT_TYPES:
            try:
                return float(value)
            except (TypeError, ValueError):
                raise MapperParsingError(f"failed to parse field [{ft.name}] of type [{t}]: [{value}]")
        if t in DATE_TYPES:
            if ft.format:
                return parse_date_with_formats(value, ft.format)
            return parse_date_to_millis(value)
        if t in BOOL_TYPES:
            if isinstance(value, bool):
                return value
            if value in ("true", "false"):
                return value == "true"
            raise MapperParsingError(f"failed to parse boolean field [{ft.name}]: [{value}]")
        if t in VECTOR_TYPES:
            if not isinstance(value, (int, float)):
                raise MapperParsingError(f"dense_vector [{ft.name}] expects numbers")
            return float(value)
        raise MapperParsingError(f"unsupported type [{t}]")

    def to_dict(self) -> dict:
        props: dict = {}
        for name, ft in sorted(self.fields.items()):
            if name == "_ignored":  # internal metadata field
                continue
            if "." in name:
                parent = name.rsplit(".", 1)[0]
                pft = self.fields.get(parent)
                if pft is not None and name.split(".")[-1] in pft.fields:
                    continue  # rendered as sub-field of parent
            node = props
            parts = name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = ft.to_dict()
        out = {"properties": props}
        if self._ds_timestamp_echo:
            out["_data_stream_timestamp"] = {"enabled": True}
        return out
