"""HBM-resident index pack format: blocked-CSR postings + columnar DocValues.

This is the TPU replacement for Lucene's on-disk segment format (reference
behavior: Lucene 9 postings/doc-values read through ES's codec layer,
server/.../index/codec/PerFieldMapperCodec.java:37). Design drivers
(SURVEY.md §7 hard part #1 — XLA wants static shapes):

- Postings are ragged per term; we store them as fixed-size BLOCK=128 rows in
  two dense matrices `post_docids`/`post_tfs` of shape [num_blocks, BLOCK],
  with a CSR directory `term_block_start[T+1]` mapping term-id -> row range.
  Row 0 is reserved as an all-padding block so query-time block lists can be
  padded with 0. Padding doc slots hold `num_docs` (a sentinel that scatters
  into a dead accumulator slot).
- Per-block `block_max_tf` / `block_min_len` support block-max pruning
  (the TPU analog of Lucene's block-max WAND skipping: whole blocks are
  masked out by an upper-bound score test instead of branchy skipping).
- Norms store the *dequantized* Lucene 1-byte doc length (smallfloat.py) so
  BM25 matches a CPU Elasticsearch bit-for-bit.
- DocValues are plain columns: int64/float32 values + presence mask, or
  sorted-ordinal int32 + host-side term dictionary for keywords (the analog
  of Lucene sorted-set doc values feeding
  GlobalOrdinalsStringTermsAggregator.java:61).
- Dense vectors are a row-major [N, dims] float32 matrix; exact scoring is a
  single MXU matmul (reference analog: index/codec/vectors/ HNSW formats —
  on TPU, brute-force matmul + top_k beats graph walks for shard-sized N).

All arrays build host-side in numpy; `to_device()` ships them to HBM once.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from .mappings import (
    Mappings,
    TEXT_TYPES,
    KEYWORD_TYPES,
    INT_TYPES,
    FLOAT_TYPES,
    DATE_TYPES,
    BOOL_TYPES,
    VECTOR_TYPES,
)
from .smallfloat import quantize_lengths

BLOCK = 128  # TPU lane width; one postings block = one vector register row

# BM25 defaults baked into dense-tier tfn rows (reference behavior:
# index/similarity/SimilarityService.java:43-58 — BM25 k1=1.2, b=0.75)
BM25_K1 = 1.2
BM25_B = 0.75

# Position keys: docid * POS_L + position, in blocked sorted int64 arrays.
# POS_L is a GLOBAL constant (not per-pack) so one traced phrase program
# serves every shard of a mesh. 2^17 positions per doc ~ Lucene's practical
# token limit; key range fits int64 with room for the +INF padding sentinel.
POS_L = 1 << 17
POS_INF = np.int64(1) << 62


def default_dense_min_df(n_docs: int) -> int:
    """df threshold above which a term moves to the dense tier. ~1 posting
    per 2 doc-chunks: dense rows then cost at most ~2x their CSR form."""
    return max(64, n_docs // 256)


def compute_tfn(
    tfs: np.ndarray, dls: np.ndarray | None, avgdl: float, has_norms: bool
) -> np.ndarray:
    """Host-side tf/(tf + K): the doc-length-normalized BM25 tf saturation."""
    if has_norms:
        K = BM25_K1 * (1.0 - BM25_B + BM25_B * dls / avgdl)
    else:
        K = BM25_K1
    return (tfs / (tfs + K)).astype(np.float32)


@dataclass
class DocValuesColumn:
    kind: str  # "int" | "float" | "ord"
    values: np.ndarray  # [N] int64 | float32 | int32 ordinals (-1 = missing)
    has_value: np.ndarray  # [N] bool
    ord_terms: list[str] | None = None  # sorted terms for kind == "ord"
    # terms-agg support for numeric columns: sorted unique values + per-doc
    # ordinal (the analog of Lucene sorted-numeric global ordinals)
    uniq_values: np.ndarray | None = None  # [V] int64
    uniq_ords: np.ndarray | None = None  # [N] int32 (-1 = missing)
    # column min/max over present values (static histogram bucket planning)
    vmin: float | int = 0
    vmax: float | int = 0


@dataclass
class VectorColumn:
    values: np.ndarray  # [N, dims] float32
    has_value: np.ndarray  # [N] bool
    similarity: str  # cosine | dot_product | l2_norm
    dims: int


@dataclass
class ShardPack:
    """Immutable packed index for one shard (host-side numpy form)."""

    num_docs: int
    # postings
    post_docids: np.ndarray  # [num_blocks, BLOCK] int32; pad = num_docs
    post_tfs: np.ndarray  # [num_blocks, BLOCK] float32; pad = 0
    post_dls: np.ndarray  # [num_blocks, BLOCK] float32 doc length per posting; pad = 1
    term_block_start: np.ndarray  # [T+1] int32 (row ranges; row 0 reserved)
    term_df: np.ndarray  # [T] int32
    block_max_tf: np.ndarray  # [num_blocks] float32
    block_min_len: np.ndarray  # [num_blocks] float32 (min quantized dl in block)
    # term dictionary: (field, term) -> tid
    term_dict: dict[tuple[str, str], int]
    # norms per text field
    norms: dict[str, np.ndarray]  # field -> [N] float32 (dequantized lengths)
    # text-field presence (a value existed, even if it analyzed to 0 tokens)
    text_present: dict[str, np.ndarray]  # field -> [N] bool
    field_stats: dict[str, dict]  # field -> {sum_dl, doc_count} (exact, for avgdl)
    # columnar docvalues
    docvalues: dict[str, DocValuesColumn]
    vectors: dict[str, VectorColumn]
    live: np.ndarray  # [N] bool live-docs bitmap (deletes)
    # dense tier: terms with df >= dense_min_df stored as precomputed
    # tf/(tf+K) rows [V_dense, N] — scored on the MXU (matmul / elementwise)
    # with no gather or scatter. K bakes this pack's avgdl and BM25 defaults.
    dense_tfn: np.ndarray | None = None
    dense_dict: dict[tuple[str, str], int] = dc_field(default_factory=dict)
    # positions (phrase queries): blocked sorted int64 keys docid*POS_L+pos;
    # pad lanes = POS_INF; row 0 reserved all-padding (query lists 0-pad)
    pos_keys: np.ndarray | None = None  # [num_pos_blocks, BLOCK] int64
    term_pos_start: np.ndarray | None = None  # [T+1] int32 block row ranges
    term_pos_count: np.ndarray | None = None  # [T] int32 total positions

    def dense_row_of(self, fld: str, term: str) -> int | None:
        return self.dense_dict.get((fld, term))

    @property
    def num_blocks(self) -> int:
        return self.post_docids.shape[0]

    @property
    def num_terms(self) -> int:
        return len(self.term_df)

    def avgdl(self, fld: str) -> float:
        st = self.field_stats.get(fld)
        if not st or st["doc_count"] == 0:
            return 1.0
        return st["sum_dl"] / st["doc_count"]

    def term_id(self, fld: str, term: str) -> int | None:
        return self.term_dict.get((fld, term))

    def term_blocks(self, fld: str, term: str) -> tuple[int, int, int]:
        """-> (block_row_start, n_blocks, df); (0, 0, 0) when term absent."""
        tid = self.term_dict.get((fld, term))
        if tid is None:
            return 0, 0, 0
        s = int(self.term_block_start[tid])
        e = int(self.term_block_start[tid + 1])
        return s, e - s, int(self.term_df[tid])

    def term_pos_blocks(self, fld: str, term: str) -> tuple[int, int, int]:
        """-> (pos_block_row_start, n_blocks, n_positions); zeros if absent."""
        tid = self.term_dict.get((fld, term))
        if tid is None or self.term_pos_start is None:
            return 0, 0, 0
        s = int(self.term_pos_start[tid])
        e = int(self.term_pos_start[tid + 1])
        return s, e - s, int(self.term_pos_count[tid])

    def terms_for_field(self, fld: str) -> list[str]:
        """Sorted terms of one field (host-side term dictionary slice — the
        analog of Lucene's per-field FST enum, used by multi-term query
        expansion: prefix/wildcard/regexp/fuzzy). Cached per field."""
        cache = getattr(self, "_field_terms_cache", None)
        if cache is None:
            cache = self._field_terms_cache = {}
        terms = cache.get(fld)
        if terms is None:
            # term_dict iteration order is sorted (field, term): build() sorts
            terms = cache[fld] = [t for (f, t) in self.term_dict if f == fld]
        return terms


class PackBuilder:
    """Accumulates parsed documents for one shard, then packs.

    The mutable in-memory form here plays the role of Lucene's IndexWriter
    RAM buffer (reference: index/engine/InternalEngine.java:1387 feeding
    IndexWriter.addDocuments); `build()` is the "refresh" that produces an
    immutable searchable pack.
    """

    def __init__(self, mappings: Mappings):
        self.mappings = mappings
        # (field, term) -> {docid: tf}
        self.postings: dict[tuple[str, str], dict[int, int]] = {}
        # (field, term) -> {docid: [positions]} (phrase support)
        self.positions: dict[tuple[str, str], dict[int, list[int]]] = {}
        self.doc_field_lengths: dict[str, list[tuple[int, int]]] = {}
        # field -> (last_docid_seen, docs_with_field); docids arrive in order
        self.field_doc_counts: dict[str, list[int]] = {}
        self.docvalue_raw: dict[str, list[tuple[int, Any]]] = {}
        self.vector_raw: dict[str, list[tuple[int, list[float]]]] = {}
        self.num_docs = 0

    def add_document(self, parsed: dict[str, list], doc_id: str | None = None) -> int:
        """parsed = Mappings.parse_document output; returns local docid.
        doc_id, when given, is stored in the reserved `_id` ordinal column so
        ids queries/sorts run on device (the reference indexes _id as a
        keyword-like metadata field, index/mapper/IdFieldMapper.java)."""
        docid = self.num_docs
        self.num_docs += 1
        if doc_id is not None:
            self.docvalue_raw.setdefault("_id", []).append((docid, str(doc_id)))
        for fld, values in parsed.items():
            ft = self.mappings.fields.get(fld)
            if ft is None:
                continue
            t = ft.type
            if t in TEXT_TYPES:
                if not ft.index:
                    continue
                analyzer = ft.get_analyzer()
                length = 0
                counts: dict[str, int] = {}
                pos_lists: dict[str, list[int]] = {}
                pos_base = 0
                for v in values:
                    last_pos = -1
                    for tok in analyzer.analyze(v):
                        counts[tok.term] = counts.get(tok.term, 0) + 1
                        pos = pos_base + tok.position
                        # positions beyond the key range are dropped (the doc
                        # still matches term queries; phrases can't see its
                        # tail — the analog of Lucene's MAX_POSITION bound,
                        # made lossy instead of fatal so one oversized doc
                        # can't poison every later refresh)
                        if pos < POS_L - 64:
                            pos_lists.setdefault(tok.term, []).append(pos)
                        last_pos = max(last_pos, tok.position)
                        length += 1
                    # multi-valued text: position gap between values
                    # (reference behavior: TextFieldMapper position_increment_gap
                    # default 100)
                    pos_base += last_pos + 1 + 100
                for term, tf in counts.items():
                    self.postings.setdefault((fld, term), {})[docid] = tf
                    if term in pos_lists:
                        self.positions.setdefault((fld, term), {})[docid] = pos_lists[term]
                self.doc_field_lengths.setdefault(fld, []).append((docid, length))
            elif t in KEYWORD_TYPES:
                kept = []
                for v in values:
                    if ft.ignore_above is not None and len(v) > ft.ignore_above:
                        continue
                    kept.append(v)
                if ft.index and kept:
                    for v in set(kept):
                        p = self.postings.setdefault((fld, v), {})
                        p[docid] = p.get(docid, 0) + 1
                    fc = self.field_doc_counts.setdefault(fld, [-1, 0])
                    if fc[0] != docid:
                        fc[0] = docid
                        fc[1] += 1
                if ft.doc_values and kept:
                    # single-valued docvalues column; first value wins
                    # (multi-valued ordinal CSR is a later milestone)
                    self.docvalue_raw.setdefault(fld, []).append((docid, kept[0]))
            elif t in INT_TYPES or t in DATE_TYPES or t in BOOL_TYPES:
                if ft.doc_values and values:
                    self.docvalue_raw.setdefault(fld, []).append((docid, int(values[0])))
            elif t in FLOAT_TYPES:
                if ft.doc_values and values:
                    self.docvalue_raw.setdefault(fld, []).append((docid, float(values[0])))
            elif t in VECTOR_TYPES:
                if values:
                    if len(values) != ft.dims:
                        from ..utils.errors import MapperParsingError

                        raise MapperParsingError(
                            f"dense_vector [{fld}] has {len(values)} dims, mapping says {ft.dims}"
                        )
                    self.vector_raw.setdefault(fld, []).append((docid, [float(x) for x in values]))
        return docid

    def build(self, dense_min_df: int | None = None) -> ShardPack:
        N = self.num_docs
        mappings = self.mappings
        if dense_min_df is None:
            dense_min_df = default_dense_min_df(N)

        # ---- term dictionary: stable order = sorted by (field, term) ----
        keys = sorted(self.postings.keys())
        term_dict = {k: i for i, k in enumerate(keys)}
        T = len(keys)

        # ---- norms (quantized doc lengths) ------------------------------
        norms: dict[str, np.ndarray] = {}
        text_present: dict[str, np.ndarray] = {}
        field_stats: dict[str, dict] = {}
        for fld, pairs in self.doc_field_lengths.items():
            lengths = np.zeros(N, dtype=np.int64)
            present = np.zeros(N, dtype=bool)
            for docid, ln in pairs:
                lengths[docid] += ln
                present[docid] = True
            norms[fld] = quantize_lengths(lengths)
            text_present[fld] = present
            # Lucene avgdl = sumTotalTermFreq / docCount where docCount counts
            # docs with at least one term for the field (Terms.getDocCount)
            docs_with = len({docid for docid, ln in pairs if ln > 0})
            field_stats[fld] = {"sum_dl": float(lengths.sum()), "doc_count": docs_with}
        # norm-less indexed fields (keyword) still need per-field docCount
        # for idf (Lucene CollectionStatistics.docCount)
        for fld, (_, cnt) in self.field_doc_counts.items():
            if fld not in field_stats:
                field_stats[fld] = {"sum_dl": 0.0, "doc_count": cnt}
        # keyword fields used in scoring need norms too (constant length 1,
        # matching Lucene: keyword fields omit norms => norm = 1)
        # handled at query time by norm fallback.

        # ---- blocked postings -------------------------------------------
        n_blocks_per_term = []
        for k in keys:
            n_post = len(self.postings[k])
            n_blocks_per_term.append((n_post + BLOCK - 1) // BLOCK)
        total_blocks = 1 + int(sum(n_blocks_per_term))  # row 0 reserved padding

        post_docids = np.full((total_blocks, BLOCK), N, dtype=np.int32)
        post_tfs = np.zeros((total_blocks, BLOCK), dtype=np.float32)
        post_dls = np.ones((total_blocks, BLOCK), dtype=np.float32)
        term_block_start = np.zeros(T + 1, dtype=np.int32)
        term_df = np.zeros(T, dtype=np.int32)
        block_max_tf = np.zeros(total_blocks, dtype=np.float32)
        block_min_len = np.full(total_blocks, np.inf, dtype=np.float32)

        row = 1
        for tid, k in enumerate(keys):
            plist = self.postings[k]
            docs = np.fromiter(plist.keys(), dtype=np.int32, count=len(plist))
            tfs = np.fromiter(plist.values(), dtype=np.float32, count=len(plist))
            order = np.argsort(docs, kind="stable")
            docs, tfs = docs[order], tfs[order]
            term_df[tid] = len(docs)
            term_block_start[tid] = row
            fld = k[0]
            fld_norms = norms.get(fld)
            for off in range(0, len(docs), BLOCK):
                chunk_d = docs[off : off + BLOCK]
                chunk_t = tfs[off : off + BLOCK]
                post_docids[row, : len(chunk_d)] = chunk_d
                post_tfs[row, : len(chunk_t)] = chunk_t
                block_max_tf[row] = float(chunk_t.max())
                if fld_norms is not None:
                    post_dls[row, : len(chunk_d)] = fld_norms[chunk_d]
                    block_min_len[row] = float(fld_norms[chunk_d].min())
                else:
                    block_min_len[row] = 1.0
                row += 1
        term_block_start[T] = row
        # term_block_start[tid] for tid with 0 postings cannot occur (terms
        # only exist with >=1 posting), so CSR is well-formed.
        block_min_len[~np.isfinite(block_min_len)] = 1.0

        # ---- docvalues ---------------------------------------------------
        docvalues: dict[str, DocValuesColumn] = {}
        for fld, pairs in self.docvalue_raw.items():
            ftype = "keyword" if fld == "_id" else mappings.fields[fld].type
            has = np.zeros(N, dtype=bool)
            if ftype in KEYWORD_TYPES:
                terms_sorted = sorted({v for _, v in pairs})
                ord_of = {t: i for i, t in enumerate(terms_sorted)}
                vals = np.full(N, -1, dtype=np.int32)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = ord_of[v]
                        has[docid] = True
                docvalues[fld] = DocValuesColumn("ord", vals, has, terms_sorted)
            elif ftype in FLOAT_TYPES:
                vals = np.zeros(N, dtype=np.float32)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = v
                        has[docid] = True
                col = DocValuesColumn("float", vals, has)
                if has.any():
                    col.vmin = float(vals[has].min())
                    col.vmax = float(vals[has].max())
                docvalues[fld] = col
            else:  # int / date / boolean
                vals = np.zeros(N, dtype=np.int64)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = v
                        has[docid] = True
                col = DocValuesColumn("int", vals, has)
                if has.any():
                    present = vals[has]
                    col.vmin = int(present.min())
                    col.vmax = int(present.max())
                    uniq, inv = np.unique(present, return_inverse=True)
                    ords = np.full(N, -1, dtype=np.int32)
                    ords[has] = inv.astype(np.int32)
                    col.uniq_values = uniq
                    col.uniq_ords = ords
                docvalues[fld] = col

        # ---- vectors -----------------------------------------------------
        vectors: dict[str, VectorColumn] = {}
        for fld, pairs in self.vector_raw.items():
            ft = mappings.fields[fld]
            vals = np.zeros((N, ft.dims), dtype=np.float32)
            has = np.zeros(N, dtype=bool)
            for docid, vec in pairs:
                vals[docid] = vec
                has[docid] = True
            vectors[fld] = VectorColumn(vals, has, ft.similarity, ft.dims)

        # ---- position blocks (text terms only) ---------------------------
        pos_keys = None
        term_pos_start = None
        term_pos_count = None
        if self.positions:
            n_pos_blocks_per_term = []
            for k in keys:
                plists = self.positions.get(k)
                npos = sum(len(v) for v in plists.values()) if plists else 0
                n_pos_blocks_per_term.append((npos + BLOCK - 1) // BLOCK)
            total_pos_blocks = 1 + int(sum(n_pos_blocks_per_term))
            pos_keys = np.full((total_pos_blocks, BLOCK), POS_INF, dtype=np.int64)
            term_pos_start = np.zeros(T + 1, dtype=np.int32)
            term_pos_count = np.zeros(T, dtype=np.int32)
            prow = 1
            for tid, k in enumerate(keys):
                term_pos_start[tid] = prow
                plists = self.positions.get(k)
                if not plists:
                    continue
                flat = np.array(
                    [d * POS_L + p for d in sorted(plists) for p in plists[d]],
                    dtype=np.int64,
                )
                term_pos_count[tid] = len(flat)
                for off in range(0, len(flat), BLOCK):
                    chunk = flat[off : off + BLOCK]
                    pos_keys[prow, : len(chunk)] = chunk
                    prow += 1
            term_pos_start[T] = prow

        # ---- dense tier --------------------------------------------------
        dense_keys = [k for k in keys if len(self.postings[k]) >= dense_min_df]
        dense_dict = {k: i for i, k in enumerate(dense_keys)}
        dense_tfn = None
        if dense_keys:
            dense_tfn = np.zeros((len(dense_keys), N), dtype=np.float32)
            for i, k in enumerate(dense_keys):
                fld = k[0]
                plist = self.postings[k]
                docs = np.fromiter(plist.keys(), np.int32, count=len(plist))
                tfs = np.fromiter(plist.values(), np.float32, count=len(plist))
                fld_norms = norms.get(fld)
                st = field_stats.get(fld, {"sum_dl": 0.0, "doc_count": 0})
                avgdl = st["sum_dl"] / max(st["doc_count"], 1) or 1.0
                dense_tfn[i, docs] = compute_tfn(
                    tfs,
                    fld_norms[docs] if fld_norms is not None else None,
                    avgdl,
                    fld_norms is not None,
                )

        return ShardPack(
            num_docs=N,
            post_docids=post_docids,
            post_tfs=post_tfs,
            post_dls=post_dls,
            term_block_start=term_block_start,
            term_df=term_df,
            block_max_tf=block_max_tf,
            block_min_len=block_min_len,
            term_dict=term_dict,
            norms=norms,
            text_present=text_present,
            field_stats=field_stats,
            docvalues=docvalues,
            vectors=vectors,
            live=np.ones(N, dtype=bool),
            dense_tfn=dense_tfn,
            dense_dict=dense_dict,
            pos_keys=pos_keys,
            term_pos_start=term_pos_start,
            term_pos_count=term_pos_count,
        )
