"""HBM-resident index pack format: blocked-CSR postings + columnar DocValues.

This is the TPU replacement for Lucene's on-disk segment format (reference
behavior: Lucene 9 postings/doc-values read through ES's codec layer,
server/.../index/codec/PerFieldMapperCodec.java:37). Design drivers
(SURVEY.md §7 hard part #1 — XLA wants static shapes):

- Postings are ragged per term; we store them as fixed-size BLOCK=128 rows in
  two dense matrices `post_docids`/`post_tfs` of shape [num_blocks, BLOCK],
  with a CSR directory `term_block_start[T+1]` mapping term-id -> row range.
  Row 0 is reserved as an all-padding block so query-time block lists can be
  padded with 0. Padding doc slots hold `num_docs` (a sentinel that scatters
  into a dead accumulator slot).
- Per-block `block_max_tf` / `block_min_len` support block-max pruning
  (the TPU analog of Lucene's block-max WAND skipping: whole blocks are
  masked out by an upper-bound score test instead of branchy skipping).
- Norms store the *dequantized* Lucene 1-byte doc length (smallfloat.py) so
  BM25 matches a CPU Elasticsearch bit-for-bit.
- DocValues are plain columns: int64/float32 values + presence mask, or
  sorted-ordinal int32 + host-side term dictionary for keywords (the analog
  of Lucene sorted-set doc values feeding
  GlobalOrdinalsStringTermsAggregator.java:61).
- Dense vectors are a row-major [N, dims] float32 matrix; exact scoring is a
  single MXU matmul (reference analog: index/codec/vectors/ HNSW formats —
  on TPU, brute-force matmul + top_k beats graph walks for shard-sized N).

All arrays build host-side in numpy; `to_device()` ships them to HBM once.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from .mappings import (
    Mappings,
    TEXT_TYPES,
    KEYWORD_TYPES,
    IP_TYPES,
    INT_TYPES,
    FLOAT_TYPES,
    DATE_TYPES,
    DATE_NANOS_TYPES,
    BOOL_TYPES,
    VECTOR_TYPES,
    ip_sort_key,
)
from .smallfloat import quantize_lengths

BLOCK = 128  # TPU lane width; one postings block = one vector register row

# BM25 defaults baked into dense-tier tfn rows (reference behavior:
# index/similarity/SimilarityService.java:43-58 — BM25 k1=1.2, b=0.75)
BM25_K1 = 1.2
BM25_B = 0.75

# ---------------------------------------------------------------------------
# impact-scored sparse tier (BM25S, https://arxiv.org/pdf/2407.03618):
# per-(term, doc) BM25 contributions precomputed at index time and
# quantized to compact integer codes, so query time is a pure gather+sum
# over code blocks — no tf / doc-length / avgdl math in the hot path.
#
# Factorization (what lives where):
#   impact(t, d) = idf(t) · tfn(t, d),  tfn = tf / (tf + K(dl, avgdl))
#   code(t, d)   = round(tfn / ubf(t) · QMAX) ∈ [1, QMAX] for tf > 0
#   ubf(t)       = max_tf / (max_tf + k1·(1 − b))   — tfn's upper bound
#                  over ANY doc length (K ≥ k1·(1 − b)), so codes can
#                  never clip however avgdl drifts between refreshes
#   score(t, d)  = boost · idf(t) · ubf(t) / QMAX · code(t, d)
#
# idf stays a per-term query-time scalar (ONE host mul in prepare,
# sourced from ops/scoring.bm25_idf — the single idf implementation), so
# dfs-stats overrides flow into the impact weights with no rebuild; only
# avgdl drift requires re-deriving the codes (an elementwise device pass
# at refresh, parallel/sharded.StackedSearcher.refresh_impacts).
#
# Error model (documented, asserted in tests/test_impact.py): per query
# term the absolute score error is at most boost · idf · ubf / QMAX
# (codes round to the nearest level, half a level each way; the clamp to
# code ≥ 1 that preserves exact match/total semantics can round a
# sub-half-level impact up by at most one full level). Per-doc error is
# the sum over the query's impact-served terms. uint16 keeps this below
# f32 tie noise; int8 is the compact/coarse alternative.
# ---------------------------------------------------------------------------

IMPACT_QMAX = {"uint16": 65535, "int8": 127}
_IMPACT_NP_DTYPE = {"uint16": np.uint16, "int8": np.int8}


def impact_dtype_default() -> str:
    """Impact-code storage dtype: ES_TPU_IMPACT_DTYPE ∈ {uint16, int8}."""
    import os

    d = os.environ.get("ES_TPU_IMPACT_DTYPE", "uint16")
    return d if d in IMPACT_QMAX else "uint16"


def impact_term_ubf(term_block_start: np.ndarray, block_max_tf: np.ndarray,
                    k1: float = BM25_K1, b: float = BM25_B) -> np.ndarray:
    """[T] per-term tfn upper bound mtf/(mtf + k1·(1−b)) from the pack's
    block-max metadata — avgdl-independent, so the per-term code scale
    survives dfs-stats drift without clipping."""
    T = len(term_block_start) - 1
    if T <= 0:
        return np.zeros(0, np.float32)
    # every term owns >= 1 contiguous block row, so reduceat is exact
    mtf = np.maximum.reduceat(block_max_tf, term_block_start[:-1])
    return (mtf / np.maximum(mtf + k1 * (1.0 - b), 1e-9)).astype(np.float32)


def impact_row_terms(term_block_start: np.ndarray,
                     total_blocks: int) -> np.ndarray:
    """[total_blocks] term id of each postings block row (-1 for the
    reserved padding row 0 / rows past the directory)."""
    out = np.full(total_blocks, -1, np.int32)
    T = len(term_block_start) - 1
    if T > 0:
        counts = term_block_start[1:] - term_block_start[:-1]
        out[term_block_start[0]: term_block_start[T]] = np.repeat(
            np.arange(T, dtype=np.int32), counts)
    return out


def impact_row_params(
    row_terms: np.ndarray,          # [nb] int32 (-1 = padding)
    term_ubf: np.ndarray,           # [T] f32
    field_of_term: np.ndarray,      # [T] int
    avgdl_of_field: np.ndarray,     # [F] f64/f32 (effective stats)
    has_norms_of_field: np.ndarray,  # [F] bool
    qmax: int,
    k1: float = BM25_K1,
    b: float = BM25_B,
):
    """-> (k_base [nb], k_slope [nb], scale_inv [nb]) f32 per-row code
    parameters: K(dl) = k_base + k_slope·dl, code = tfn·scale_inv. The
    only stats-dependent piece is k_slope (k1·b/avgdl), recomputed from
    the EFFECTIVE field stats at every (re)derivation."""
    t = row_terms
    safe_t = np.maximum(t, 0)
    fcode = field_of_term[safe_t]
    hn = has_norms_of_field[fcode] & (t >= 0)
    k_base = np.where(hn, k1 * (1.0 - b), k1).astype(np.float32)
    k_slope = np.where(
        hn, k1 * b / np.maximum(avgdl_of_field[fcode], 1e-9), 0.0
    ).astype(np.float32)
    scale_inv = np.where(
        t >= 0, qmax / np.maximum(term_ubf[safe_t], 1e-9), 0.0
    ).astype(np.float32)
    return k_base, k_slope, scale_inv


def impact_codes_host(post_tfs: np.ndarray, post_dls: np.ndarray,
                      k_base: np.ndarray, k_slope: np.ndarray,
                      scale_inv: np.ndarray, qmax: int,
                      dtype: str) -> np.ndarray:
    """Quantized impact codes (numpy twin of the device derivation in
    parallel/sharded.StackedSearcher.refresh_impacts — the two are
    asserted equal by tests/test_impact.py). Shapes broadcast: per-row
    params [..., nb] against blocked lanes [..., nb, BLOCK]."""
    K = k_base[..., None] + k_slope[..., None] * post_dls
    tfn = post_tfs / (post_tfs + K)  # tf == 0 padding -> 0
    q = np.rint(tfn * scale_inv[..., None])
    q = np.clip(q, 1, qmax)  # tf > 0 must stay a match (code >= 1)
    q = np.where(post_tfs > 0, q, 0)
    return q.astype(_IMPACT_NP_DTYPE[dtype])

# Position keys: docid * POS_L + position, in blocked sorted int64 arrays.
# POS_L is a GLOBAL constant (not per-pack) so one traced phrase program
# serves every shard of a mesh. 2^17 positions per doc ~ Lucene's practical
# token limit; key range fits int64 with room for the +INF padding sentinel.
POS_L = 1 << 17
POS_INF = np.int64(1) << 62


def _parse_geo_point(v):
    """ES geo_point forms -> (lat, lon): {"lat","lon"} | "lat,lon" |
    [lon, lat] (GeoJSON order!) | {"type": "Point", "coordinates": [lon,lat]}
    (reference behavior: common/geo/GeoPoint.java parsing)."""
    try:
        if isinstance(v, dict):
            if "lat" in v and "lon" in v:
                return float(v["lat"]), float(v["lon"])
            if v.get("type", "").lower() == "point" and v.get("coordinates"):
                lon, lat = v["coordinates"][:2]
                return float(lat), float(lon)
            return None
        if isinstance(v, str):
            lat_s, lon_s = v.split(",", 1)
            return float(lat_s), float(lon_s)
        if isinstance(v, (list, tuple)) and len(v) >= 2:
            return float(v[1]), float(v[0])
    except (ValueError, TypeError):
        from ..utils.errors import MapperParsingError

        raise MapperParsingError(f"failed to parse geo_point value [{v!r}]")
    return None


def default_dense_min_df(n_docs: int) -> int:
    """df threshold above which a term moves to the dense tier. ~1 posting
    per 2 doc-chunks: dense rows then cost at most ~2x their CSR form."""
    return max(64, n_docs // 256)


def compute_tfn(
    tfs: np.ndarray, dls: np.ndarray | None, avgdl: float, has_norms: bool
) -> np.ndarray:
    """Host-side tf/(tf + K): the doc-length-normalized BM25 tf saturation."""
    if has_norms:
        K = BM25_K1 * (1.0 - BM25_B + BM25_B * dls / avgdl)
    else:
        K = BM25_K1
    return (tfs / (tfs + K)).astype(np.float32)


@dataclass
class DocValuesColumn:
    kind: str  # "int" | "float" | "ord"
    values: np.ndarray  # [N] int64 | float32 | int32 ordinals (-1 = missing)
    has_value: np.ndarray  # [N] bool
    ord_terms: list[str] | None = None  # sorted terms for kind == "ord"
    # terms-agg support for numeric columns: sorted unique values + per-doc
    # ordinal (the analog of Lucene sorted-numeric global ordinals)
    uniq_values: np.ndarray | None = None  # [V] int64
    uniq_ords: np.ndarray | None = None  # [N] int32 (-1 = missing)
    # column min/max over present values (static histogram bucket planning)
    vmin: float | int = 0
    vmax: float | int = 0
    # multi-valued keyword support: (doc, ordinal) pairs covering EVERY
    # value (the single-value arrays above keep first-value semantics for
    # sort/collapse); None when no doc has >1 value
    mv_pair_docs: np.ndarray | None = None  # [P] int32 sorted by doc
    mv_pair_ords: np.ndarray | None = None  # [P] int32


@dataclass
class VectorColumn:
    values: np.ndarray  # [N, dims] float32
    has_value: np.ndarray  # [N] bool
    similarity: str  # cosine | dot_product | l2_norm
    dims: int
    # optional device-resident ANN index (ann/index.build_ann output:
    # IVF partitions packed into padded cluster tiles + int8 tier)
    ann: dict | None = None
    # selection-scan tier for the ANN path (mapping index_options)
    ann_quant: str = "int8"


@dataclass
class ShardPack:
    """Immutable packed index for one shard (host-side numpy form)."""

    num_docs: int
    # postings
    post_docids: np.ndarray  # [num_blocks, BLOCK] int32; pad = num_docs
    post_tfs: np.ndarray  # [num_blocks, BLOCK] float32; pad = 0
    post_dls: np.ndarray  # [num_blocks, BLOCK] float32 doc length per posting; pad = 1
    term_block_start: np.ndarray  # [T+1] int32 (row ranges; row 0 reserved)
    term_df: np.ndarray  # [T] int32
    block_max_tf: np.ndarray  # [num_blocks] float32
    block_min_len: np.ndarray  # [num_blocks] float32 (min quantized dl in block)
    # term dictionary: (field, term) -> tid
    term_dict: dict[tuple[str, str], int]
    # norms per text field
    norms: dict[str, np.ndarray]  # field -> [N] float32 (dequantized lengths)
    # text-field presence (a value existed, even if it analyzed to 0 tokens)
    text_present: dict[str, np.ndarray]  # field -> [N] bool
    field_stats: dict[str, dict]  # field -> {sum_dl, doc_count} (exact, for avgdl)
    # columnar docvalues
    docvalues: dict[str, DocValuesColumn]
    vectors: dict[str, VectorColumn]
    live: np.ndarray  # [N] bool live-docs bitmap (deletes)
    # dense tier: terms with df >= dense_min_df stored as precomputed
    # tf/(tf+K) rows [V_dense, N] — scored on the MXU (matmul / elementwise)
    # with no gather or scatter. K bakes this pack's avgdl and BM25 defaults.
    dense_tfn: np.ndarray | None = None
    dense_dict: dict[tuple[str, str], int] = dc_field(default_factory=dict)
    # positions (phrase queries): blocked sorted int64 keys docid*POS_L+pos;
    # pad lanes = POS_INF; row 0 reserved all-padding (query lists 0-pad)
    pos_keys: np.ndarray | None = None  # [num_pos_blocks, BLOCK] int64
    term_pos_start: np.ndarray | None = None  # [T+1] int32 block row ranges
    term_pos_count: np.ndarray | None = None  # [T] int32 total positions
    # completion-suggester inputs, host-side only:
    # field -> sorted list of (input, weight, docid)
    completion: dict[str, list] = dc_field(default_factory=dict)
    # percolator queries, host-side only: field -> list of (docid, query_dict)
    percolator: dict[str, list] = dc_field(default_factory=dict)
    # impact-scored sparse tier (BM25S): quantized per-posting BM25
    # contributions aligned with post_docids, per-term tfn bounds, and the
    # quantization contract. None = tier absent (old manifests degrade to
    # the raw-postings scoring path).
    impact_codes: np.ndarray | None = None  # [num_blocks, BLOCK] u16|i8
    impact_ubf: np.ndarray | None = None  # [T] f32 per-term tfn bound
    impact_meta: dict | None = None  # {"dtype", "qmax", "k1", "b"}

    def dense_row_of(self, fld: str, term: str) -> int | None:
        return self.dense_dict.get((fld, term))

    @property
    def num_blocks(self) -> int:
        return self.post_docids.shape[0]

    @property
    def num_terms(self) -> int:
        return len(self.term_df)

    def avgdl(self, fld: str) -> float:
        st = self.field_stats.get(fld)
        if not st or st["doc_count"] == 0:
            return 1.0
        return st["sum_dl"] / st["doc_count"]

    def term_id(self, fld: str, term: str) -> int | None:
        return self.term_dict.get((fld, term))

    def term_blocks(self, fld: str, term: str) -> tuple[int, int, int]:
        """-> (block_row_start, n_blocks, df); (0, 0, 0) when term absent."""
        tid = self.term_dict.get((fld, term))
        if tid is None:
            return 0, 0, 0
        s = int(self.term_block_start[tid])
        e = int(self.term_block_start[tid + 1])
        return s, e - s, int(self.term_df[tid])

    def impact_wscale(self, fld: str, term: str) -> float | None:
        """ubf(t)/QMAX — the per-term dequantization scale of the impact
        tier; the query-time term weight is boost · idf · this. None when
        the tier is absent or the term unknown (caller falls back to the
        raw-postings path)."""
        if (self.impact_codes is None or self.impact_meta is None
                or self.impact_ubf is None):
            return None
        tid = self.term_dict.get((fld, term))
        if tid is None:
            return None
        return float(self.impact_ubf[tid]) / self.impact_meta["qmax"]

    def term_pos_blocks(self, fld: str, term: str) -> tuple[int, int, int]:
        """-> (pos_block_row_start, n_blocks, n_positions); zeros if absent."""
        tid = self.term_dict.get((fld, term))
        if tid is None or self.term_pos_start is None:
            return 0, 0, 0
        s = int(self.term_pos_start[tid])
        e = int(self.term_pos_start[tid + 1])
        return s, e - s, int(self.term_pos_count[tid])

    def terms_for_field(self, fld: str) -> list[str]:
        """Sorted terms of one field (host-side term dictionary slice — the
        analog of Lucene's per-field FST enum, used by multi-term query
        expansion: prefix/wildcard/regexp/fuzzy). Cached per field."""
        cache = getattr(self, "_field_terms_cache", None)
        if cache is None:
            cache = self._field_terms_cache = {}
        terms = cache.get(fld)
        if terms is None:
            # term_dict iteration order is sorted (field, term): build() sorts
            terms = cache[fld] = [t for (f, t) in self.term_dict if f == fld]
        return terms


class PackBuilder:
    """Accumulates parsed documents for one shard, then packs.

    The mutable in-memory form here plays the role of Lucene's IndexWriter
    RAM buffer (reference: index/engine/InternalEngine.java:1387 feeding
    IndexWriter.addDocuments); `build()` is the "refresh" that produces an
    immutable searchable pack.
    """

    def __init__(self, mappings: Mappings, use_native: bool | None = None):
        self.mappings = mappings
        # (field, term) -> {docid: tf}
        self.postings: dict[tuple[str, str], dict[int, int]] = {}
        # (field, term) -> {docid: [positions]} (phrase support)
        self.positions: dict[tuple[str, str], dict[int, list[int]]] = {}
        self.doc_field_lengths: dict[str, list[tuple[int, int]]] = {}
        # field -> (last_docid_seen, docs_with_field); docids arrive in order
        self.field_doc_counts: dict[str, list[int]] = {}
        self.docvalue_raw: dict[str, list[tuple[int, Any]]] = {}
        self.vector_raw: dict[str, list[tuple[int, list[float]]]] = {}
        self.completion_raw: dict[str, list[tuple[str, int, int]]] = {}
        self.percolator_raw: dict[str, list] = {}
        self.mv_extra_raw: dict[str, list] = {}  # extra keyword values beyond the first
        self.num_docs = 0
        # C++ accumulator owns the per-token hot loop when available
        # (native/packing.cpp); dict fallback otherwise. Packs are
        # bit-compatible either way (tests/test_native.py).
        self._native = None
        if use_native is not False:
            from .. import native as native_mod

            if native_mod.available():
                from ..native.accumulator import NativeAccumulator

                self._native = NativeAccumulator()
            elif use_native:
                raise RuntimeError("native packing requested but unavailable")

    def add_document(self, parsed: dict[str, list], doc_id: str | None = None,
                     skip_text: bool = False) -> int:
        """parsed = Mappings.parse_document output; returns local docid.
        doc_id, when given, is stored in the reserved `_id` ordinal column so
        ids queries/sorts run on device (the reference indexes _id as a
        keyword-like metadata field, index/mapper/IdFieldMapper.java).
        skip_text leaves indexed text fields to the caller — the
        batch-analysis path (add_documents_batch) routes them through
        one vectorized analyze dispatch per field instead."""
        docid = self.num_docs
        self.num_docs += 1
        if doc_id is not None:
            self.docvalue_raw.setdefault("_id", []).append((docid, str(doc_id)))
        for fld, values in parsed.items():
            ft = self.mappings.fields.get(fld)
            if ft is None:
                continue
            t = ft.type
            if t in TEXT_TYPES:
                if not ft.index or skip_text:
                    continue
                analyzer = ft.get_analyzer()
                if self._native is not None:
                    self._add_text_native(fld, docid, analyzer, values)
                    continue
                length = 0
                counts: dict[str, int] = {}
                pos_lists: dict[str, list[int]] = {}
                pos_base = 0
                for v in values:
                    last_pos = -1
                    for tok in analyzer.analyze(v):
                        counts[tok.term] = counts.get(tok.term, 0) + 1
                        pos = pos_base + tok.position
                        # positions beyond the key range are dropped (the doc
                        # still matches term queries; phrases can't see its
                        # tail — the analog of Lucene's MAX_POSITION bound,
                        # made lossy instead of fatal so one oversized doc
                        # can't poison every later refresh)
                        if pos < POS_L - 64:
                            pos_lists.setdefault(tok.term, []).append(pos)
                        last_pos = max(last_pos, tok.position)
                        length += 1
                    # multi-valued text: position gap between values
                    # (reference behavior: TextFieldMapper position_increment_gap
                    # default 100)
                    pos_base += last_pos + 1 + 100
                for term, tf in counts.items():
                    self.postings.setdefault((fld, term), {})[docid] = tf
                    if term in pos_lists:
                        self.positions.setdefault((fld, term), {})[docid] = pos_lists[term]
                self.doc_field_lengths.setdefault(fld, []).append((docid, length))
            elif t in KEYWORD_TYPES or t in IP_TYPES:
                kept = []
                for v in values:
                    if ft.ignore_above is not None and len(v) > ft.ignore_above:
                        continue
                    kept.append(v)
                if ft.index and kept:
                    if self._native is not None:
                        self._native.add_tokens(fld, docid, list(set(kept)), None)
                    else:
                        for v in set(kept):
                            p = self.postings.setdefault((fld, v), {})
                            p[docid] = p.get(docid, 0) + 1
                    fc = self.field_doc_counts.setdefault(fld, [-1, 0])
                    if fc[0] != docid:
                        fc[0] = docid
                        fc[1] += 1
                if ft.doc_values and kept:
                    # first value drives sort/collapse; ALL values feed the
                    # multi-value pair arrays for terms/cardinality aggs
                    self.docvalue_raw.setdefault(fld, []).append((docid, kept[0]))
                    if len(set(kept)) > 1:
                        self.mv_extra_raw.setdefault(fld, []).extend(
                            (docid, v) for v in sorted(set(kept))
                            if v != kept[0]
                        )
            elif (t in INT_TYPES or t in DATE_TYPES
                  or t in DATE_NANOS_TYPES or t in BOOL_TYPES):
                if ft.doc_values and values:
                    self.docvalue_raw.setdefault(fld, []).append((docid, int(values[0])))
            elif t in FLOAT_TYPES:
                if ft.doc_values and values:
                    self.docvalue_raw.setdefault(fld, []).append((docid, float(values[0])))
            elif t == "geo_point":
                for v in values:
                    latlon = _parse_geo_point(v)
                    if latlon is not None:
                        self.docvalue_raw.setdefault(f"{fld}#lat", []).append(
                            (docid, latlon[0]))
                        self.docvalue_raw.setdefault(f"{fld}#lon", []).append(
                            (docid, latlon[1]))
                        break  # single-valued column: first point wins
            elif t == "percolator":
                for v in values:
                    if not isinstance(v, dict):
                        from ..utils.errors import MapperParsingError

                        raise MapperParsingError(
                            f"percolator field [{fld}] requires a query object"
                        )
                    self.percolator_raw.setdefault(fld, []).append((docid, v))
            elif t == "completion":
                for v in values:
                    if isinstance(v, dict):
                        inputs = v.get("input") or []
                        if isinstance(inputs, str):
                            inputs = [inputs]
                        weight = int(v.get("weight", 1))
                    elif isinstance(v, list):
                        inputs, weight = v, 1
                    else:
                        inputs, weight = [v], 1
                    for inp in inputs:
                        self.completion_raw.setdefault(fld, []).append(
                            (str(inp), weight, docid)
                        )
            elif t in VECTOR_TYPES:
                if values:
                    if len(values) != ft.dims:
                        from ..utils.errors import MapperParsingError

                        raise MapperParsingError(
                            f"dense_vector [{fld}] has {len(values)} dims, mapping says {ft.dims}"
                        )
                    self.vector_raw.setdefault(fld, []).append((docid, [float(x) for x in values]))
        return docid

    def _add_text_native(self, fld: str, docid: int, analyzer, values):
        """Text-field token routing into the C++ accumulator. The ASCII fast
        path requires exact standard-analyzer semantics; anything else is
        Python-analyzed and fed as pre-tokenized terms."""
        from ..analysis.analyzers import StandardAnalyzer

        nat = self._native
        fast = (
            type(analyzer) is StandardAnalyzer
            and not analyzer.stopwords
            and analyzer.max_token_length == 255
        )
        length = 0
        pos_base = 0
        for v in values:
            ret = nat.add_text(fld, docid, v, pos_base) if fast else -1
            if ret < 0:
                toks = analyzer.analyze(v)
                nat.add_tokens(
                    fld, docid,
                    [tk.term for tk in toks],
                    [pos_base + tk.position for tk in toks],
                )
                last_pos = max((tk.position for tk in toks), default=-1)
                length += len(toks)
                pos_base += last_pos + 1 + 100
            else:
                length += ret
                pos_base += ret + 100
        self.doc_field_lengths.setdefault(fld, []).append((docid, length))

    def add_documents_batch(self, parsed_docs: list[dict],
                            doc_ids: list | None = None) -> list[int]:
        """Batch add: one vectorized analyze dispatch per text field
        across the whole burst (analysis/batched.py) feeding the same
        accumulator state as N add_document calls — asserted
        byte-identical by tests/test_batched_analysis.py. Non-text
        fields ride the per-doc path unchanged (they were never the
        wall). ES_TPU_ANALYZE=host degrades to the reference per-doc
        loop. Returns the local docids."""
        from ..analysis.batched import analyze_burst, analyze_mode

        if doc_ids is None:
            doc_ids = [None] * len(parsed_docs)
        mode = analyze_mode()
        if mode == "host":
            from ..monitoring.refresh_profile import refresh_stage

            with refresh_stage("analyze"):
                return [self.add_document(p, doc_id=d)
                        for p, d in zip(parsed_docs, doc_ids)]
        docids: list[int] = []
        # field -> (docids-with-field, flat values, value->doc ordinal)
        bursts: dict[str, tuple[list[int], list[str], list[int]]] = {}
        for parsed, doc_id in zip(parsed_docs, doc_ids):
            docid = self.add_document(parsed, doc_id=doc_id, skip_text=True)
            docids.append(docid)
            for fld, values in parsed.items():
                ft = self.mappings.fields.get(fld)
                if ft is None or ft.type not in TEXT_TYPES or not ft.index:
                    continue
                fdocs, vals, vdoc = bursts.setdefault(fld, ([], [], []))
                d_ord = len(fdocs)
                fdocs.append(docid)
                vals.extend(values)
                vdoc.extend([d_ord] * len(values))
        for fld, (fdocs, vals, vdoc) in bursts.items():
            ba = self.mappings.fields[fld].get_batched_analyzer()
            if self._native_burst_eligible(ba, vals, mode):
                self._ingest_text_burst_native(fld, fdocs, vals, vdoc, ba)
                continue
            burst = analyze_burst(
                ba, vals, np.asarray(vdoc, np.int64), len(fdocs), mode=mode)
            self._ingest_text_burst(fld, fdocs, burst)
        return docids

    def _native_burst_eligible(self, ba, vals: list[str], mode: str) -> bool:
        """auto + C accumulator + plain standard analyzer: the C
        tokenizer (builder_add_text) is the measured-fastest host
        analyze+insert route at every burst size (BENCH_NOTES round 20)
        and is byte-compatible with the oracle by the per-doc path's own
        contract, so auto prefers it — unless the device kernel claims
        the burst (accelerator backend, burst past ES_TPU_ANALYZE_MIN).
        Forced modes (host/batched/device) never take this route: their
        dispatch is the thing the parity tests pin down."""
        if mode != "auto" or self._native is None or not ba.device_eligible:
            return False
        import jax

        from ..analysis.batched import analyze_device_min
        from . import device_build as db

        return not (jax.default_backend() != "cpu"
                    and db.device_build_enabled()
                    and sum(map(len, vals)) >= analyze_device_min())

    def _ingest_text_burst_native(self, fld: str, fdocs: list[int],
                                  vals: list[str], vdoc: list[int],
                                  ba) -> None:
        """One field's whole burst through the C accumulator under a
        single costed `build.analyze` dispatch. Routing is per doc via
        _add_text_native (identical chaining, non-ASCII per-value
        fallback), so state parity with N add_document calls holds by
        construction; what the batch buys is one stage dispatch and no
        per-doc Python parse/setup between values."""
        from ..monitoring.refresh_profile import build_stage

        with build_stage("build.analyze", nbytes=sum(map(len, vals)),
                         values=len(vals), docs=len(fdocs)):
            i = 0
            n = len(vdoc)
            for d_ord, docid in enumerate(fdocs):
                j = i
                while j < n and vdoc[j] == d_ord:
                    j += 1
                self._add_text_native(fld, docid, ba.analyzer, vals[i:j])
                i = j

    def _ingest_text_burst(self, fld: str, docids: list[int], burst) -> None:
        """Route one analyzed burst into the accumulator — the batch
        twin of the per-doc text branch: same postings/positions/
        field-length state, same POS_L bound on stored positions (term
        frequencies and lengths still count past it)."""
        bounds = np.zeros(len(docids) + 1, np.int64)
        np.cumsum(burst.lengths, out=bounds[1:])
        if self._native is not None:
            terms = burst.terms.tolist()
            pos = burst.positions.tolist()
            for k, docid in enumerate(docids):
                s, e = int(bounds[k]), int(bounds[k + 1])
                # unfiltered positions, like _add_text_native: the C++
                # accumulator applies the position bound itself
                self._native.add_tokens(fld, docid, terms[s:e], pos[s:e])
                self.doc_field_lengths.setdefault(fld, []).append(
                    (docid, int(burst.lengths[k])))
            return
        T = int(burst.terms.size)
        if T:
            # intern terms -> codes, then group tokens by (term, doc) in
            # one stable sort; each segment is one posting
            vocab: dict[str, int] = {}
            terms = burst.terms.tolist()
            tcode = np.fromiter(
                (vocab.setdefault(t, len(vocab)) for t in terms),
                np.int64, count=T)
            uniq = list(vocab)
            D = len(docids)
            key = tcode * D + burst.doc_idx
            order = np.argsort(key, kind="stable")
            ks = key[order]
            seg = np.flatnonzero(
                np.concatenate([[True], ks[1:] != ks[:-1]]))
            seg_end = np.concatenate([seg[1:], [ks.size]])
            pos_sorted = burst.positions[order]
            for s, e in zip(seg.tolist(), seg_end.tolist()):
                k = int(ks[s])
                term = uniq[k // D]
                docid = docids[k % D]
                self.postings.setdefault((fld, term), {})[docid] = e - s
                pl = pos_sorted[s:e]
                pl = pl[pl < POS_L - 64]
                if pl.size:
                    self.positions.setdefault(
                        (fld, term), {})[docid] = pl.tolist()
        for k, docid in enumerate(docids):
            self.doc_field_lengths.setdefault(fld, []).append(
                (docid, int(burst.lengths[k])))

    def _flat_csr_from_dicts(self):
        """Convert the dict-form postings/positions to the flat-CSR form the
        vectorized packer consumes (same layout the native accumulator
        emits)."""
        keys = sorted(self.postings.keys())
        T = len(keys)
        df = np.fromiter(
            (len(self.postings[k]) for k in keys), np.int64, count=T
        )
        post_offsets = np.zeros(T + 1, np.int64)
        np.cumsum(df, out=post_offsets[1:])
        total = int(post_offsets[-1])
        flat_docs = np.empty(total, np.int32)
        flat_tfs = np.empty(total, np.float32)
        for i, k in enumerate(keys):
            plist = self.postings[k]
            docs = np.fromiter(plist.keys(), np.int32, count=len(plist))
            tfs = np.fromiter(plist.values(), np.float32, count=len(plist))
            order = np.argsort(docs, kind="stable")
            s, e = post_offsets[i], post_offsets[i + 1]
            flat_docs[s:e] = docs[order]
            flat_tfs[s:e] = tfs[order]
        pos_counts = np.zeros(T, np.int64)
        for i, k in enumerate(keys):
            plists = self.positions.get(k)
            if plists:
                pos_counts[i] = sum(len(v) for v in plists.values())
        pos_offsets = np.zeros(T + 1, np.int64)
        np.cumsum(pos_counts, out=pos_offsets[1:])
        flat_pos = np.empty(int(pos_offsets[-1]), np.int64)
        for i, k in enumerate(keys):
            plists = self.positions.get(k)
            if not plists:
                continue
            s = pos_offsets[i]
            for d in sorted(plists):
                for p in plists[d]:
                    flat_pos[s] = d * POS_L + p
                    s += 1
        return keys, post_offsets, flat_docs, flat_tfs, pos_offsets, flat_pos

    def build(self, dense_min_df: int | None = None) -> ShardPack:
        from ..monitoring.refresh_profile import build_stage, refresh_stage

        N = self.num_docs
        mappings = self.mappings
        if dense_min_df is None:
            dense_min_df = default_dense_min_df(N)

        # ---- flat CSR (native accumulator or dict fallback) --------------
        with refresh_stage("flat_csr"):
            if self._native is not None:
                keys, post_offsets, flat_docs, flat_tfs, pos_offsets, \
                    flat_pos = self._native.pack()
                self._native.close()
                self._native = None
            else:
                keys, post_offsets, flat_docs, flat_tfs, pos_offsets, \
                    flat_pos = self._flat_csr_from_dicts()
        # term dictionary: stable order = sorted by (field, term)
        term_dict = {k: i for i, k in enumerate(keys)}
        T = len(keys)

        # ---- norms (quantized doc lengths) ------------------------------
        norms: dict[str, np.ndarray] = {}
        text_present: dict[str, np.ndarray] = {}
        field_stats: dict[str, dict] = {}
        with build_stage("build.norms", num_docs=N,
                         nfields=len(self.doc_field_lengths)):
            for fld, pairs in self.doc_field_lengths.items():
                lengths = np.zeros(N, dtype=np.int64)
                present = np.zeros(N, dtype=bool)
                for docid, ln in pairs:
                    lengths[docid] += ln
                    present[docid] = True
                norms[fld] = quantize_lengths(lengths)
                text_present[fld] = present
                # Lucene avgdl = sumTotalTermFreq / docCount where docCount
                # counts docs with at least one term for the field
                # (Terms.getDocCount)
                docs_with = len({docid for docid, ln in pairs if ln > 0})
                field_stats[fld] = {"sum_dl": float(lengths.sum()),
                                    "doc_count": docs_with}
        # norm-less indexed fields (keyword) still need per-field docCount
        # for idf (Lucene CollectionStatistics.docCount)
        for fld, (_, cnt) in self.field_doc_counts.items():
            if fld not in field_stats:
                field_stats[fld] = {"sum_dl": 0.0, "doc_count": cnt}
        # keyword fields used in scoring need norms too (constant length 1,
        # matching Lucene: keyword fields omit norms => norm = 1)
        # handled at query time by norm fallback.

        # ---- blocked postings (segment scatter from flat CSR) ------------
        # PR 15: above the device-build floor the scatter + block-stat
        # derivation runs as one jitted segment-scatter kernel
        # (index/device_build.csr_blocked_scatter_device) — byte parity
        # with the host path asserted by tests/test_device_build.py
        from .device_build import (csr_blocked_scatter_device,
                                   use_device_build)

        NP = len(flat_docs) if T else 0
        csr_dev = use_device_build(NP)
        with build_stage("build.csr_assemble", postings=NP, num_docs=N,
                         terms=T, basis="device" if csr_dev else "host"):
            df = post_offsets[1:] - post_offsets[:-1]
            term_df = df.astype(np.int32)
            nblk = (df + BLOCK - 1) // BLOCK
            row_base = np.empty(T + 1, dtype=np.int64)
            row_base[0] = 1  # row 0 reserved all-padding
            row_base[1:] = 1 + np.cumsum(nblk)
            total_blocks = int(row_base[-1]) if T else 1
            term_block_start = row_base.astype(np.int32)

            field_names = sorted({k[0] for k in keys})
            fld_code = {f: i for i, f in enumerate(field_names)}
            field_of_term = np.fromiter(
                (fld_code[k[0]] for k in keys), np.int64, count=T
            )
            if NP:
                term_of_post = np.repeat(np.arange(T), df)
                local = np.arange(NP, dtype=np.int64) - np.repeat(
                    post_offsets[:-1], df
                )
                dest_row = row_base[:-1][term_of_post] + local // BLOCK
                dest_col = local % BLOCK
                # per-posting doc length (1.0 for norm-less fields)
                post_dl_flat = np.ones(NP, dtype=np.float32)
                fop = field_of_term[term_of_post]
                for f, nrm in norms.items():
                    code = fld_code.get(f)
                    if code is None:
                        continue
                    sel = fop == code
                    if sel.any():
                        post_dl_flat[sel] = nrm[flat_docs[sel]]
            if NP and csr_dev:
                (post_docids, post_tfs, post_dls, block_max_tf,
                 block_min_len) = csr_blocked_scatter_device(
                    flat_docs, flat_tfs, post_dl_flat, dest_row,
                    dest_col, total_blocks, BLOCK, N)
            else:
                post_docids = np.full((total_blocks, BLOCK), N,
                                      dtype=np.int32)
                post_tfs = np.zeros((total_blocks, BLOCK),
                                    dtype=np.float32)
                post_dls = np.ones((total_blocks, BLOCK),
                                   dtype=np.float32)
                block_max_tf = np.zeros(total_blocks, dtype=np.float32)
                block_min_len = np.full(total_blocks, np.inf,
                                        dtype=np.float32)
                if NP:
                    post_docids[dest_row, dest_col] = flat_docs
                    post_tfs[dest_row, dest_col] = flat_tfs
                    post_dls[dest_row, dest_col] = post_dl_flat
                    # per-block stats: flat order is block-contiguous, so
                    # reduceat over block starts gives segment max/min
                    starts = np.flatnonzero(
                        np.diff(dest_row, prepend=-1))
                    block_rows = dest_row[starts]
                    block_max_tf[block_rows] = np.maximum.reduceat(
                        flat_tfs, starts)
                    block_min_len[block_rows] = np.minimum.reduceat(
                        post_dl_flat, starts)
            block_min_len[~np.isfinite(block_min_len)] = 1.0

        # ---- docvalues ---------------------------------------------------
        docvalues: dict[str, DocValuesColumn] = {}
        for fld, pairs in self.docvalue_raw.items():
            if fld == "_id":
                ftype = "keyword"
            elif "#" in fld:
                ftype = "float"  # geo_point lat/lon sub-columns
            else:
                ftype = mappings.fields[fld].type
            has = np.zeros(N, dtype=bool)
            if ftype in KEYWORD_TYPES or ftype in IP_TYPES:
                extras = self.mv_extra_raw.get(fld, [])
                # ip ordinals sort by address value, not lexicographically,
                # so ord-range queries and sorts follow numeric ip order
                sort_key = ip_sort_key if ftype in IP_TYPES else None
                terms_sorted = sorted({v for _, v in pairs}
                                      | {v for _, v in extras}, key=sort_key)
                ord_of = {t: i for i, t in enumerate(terms_sorted)}
                vals = np.full(N, -1, dtype=np.int32)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = ord_of[v]
                        has[docid] = True
                col = DocValuesColumn("ord", vals, has, terms_sorted)
                if extras:
                    all_pairs = sorted(
                        {(docid, ord_of[v]) for docid, v in pairs if v in ord_of}
                        | {(docid, ord_of[v]) for docid, v in extras}
                    )
                    col.mv_pair_docs = np.array([d for d, _ in all_pairs], np.int32)
                    col.mv_pair_ords = np.array([o for _, o in all_pairs], np.int32)
                docvalues[fld] = col
            elif ftype in FLOAT_TYPES:
                vals = np.zeros(N, dtype=np.float32)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = v
                        has[docid] = True
                col = DocValuesColumn("float", vals, has)
                if has.any():
                    col.vmin = float(vals[has].min())
                    col.vmax = float(vals[has].max())
                docvalues[fld] = col
            else:  # int / date / boolean
                vals = np.zeros(N, dtype=np.int64)
                for docid, v in pairs:
                    if not has[docid]:
                        vals[docid] = v
                        has[docid] = True
                col = DocValuesColumn("int", vals, has)
                if has.any():
                    present = vals[has]
                    col.vmin = int(present.min())
                    col.vmax = int(present.max())
                    uniq, inv = np.unique(present, return_inverse=True)
                    ords = np.full(N, -1, dtype=np.int32)
                    ords[has] = inv.astype(np.int32)
                    col.uniq_values = uniq
                    col.uniq_ords = ords
                docvalues[fld] = col

        # ---- vectors -----------------------------------------------------
        vectors: dict[str, VectorColumn] = {}
        with refresh_stage("vectors"):
            for fld, pairs in self.vector_raw.items():
                ft = mappings.fields[fld]
                vals = np.zeros((N, ft.dims), dtype=np.float32)
                has = np.zeros(N, dtype=bool)
                for docid, vec in pairs:
                    vals[docid] = vec
                    has[docid] = True
                vc = VectorColumn(vals, has, ft.similarity, ft.dims,
                                  ann_quant=getattr(ft, "ann_quant", "int8"))
                if ft.ann_nlist is not None:
                    from ..ann import build_ann

                    nlist = ft.ann_nlist or max(1, int(has.sum() ** 0.5))
                    vc.ann = build_ann(vals, has, nlist)
                vectors[fld] = vc

        # ---- position blocks (vectorized scatter from flat CSR) ----------
        pos_keys = None
        term_pos_start = None
        term_pos_count = None
        n_positions = int(pos_offsets[-1]) if T else 0
        if n_positions:
            # position keys stay a host scatter for now: tiny next to the
            # postings volume, and phrase-heavy corpora are not the C7
            # write path (documented in BENCH_NOTES round 19)
            with build_stage("build.csr_assemble", postings=n_positions,
                             num_docs=N, terms=T, basis="host"):
                pos_df = pos_offsets[1:] - pos_offsets[:-1]
                pnblk = (pos_df + BLOCK - 1) // BLOCK
                prow_base = np.empty(T + 1, dtype=np.int64)
                prow_base[0] = 1
                prow_base[1:] = 1 + np.cumsum(pnblk)
                total_pos_blocks = int(prow_base[-1])
                pos_keys = np.full((total_pos_blocks, BLOCK), POS_INF,
                                   dtype=np.int64)
                term_pos_start = prow_base.astype(np.int32)
                term_pos_count = pos_df.astype(np.int32)
                pterm = np.repeat(np.arange(T), pos_df)
                plocal = np.arange(n_positions, dtype=np.int64) - np.repeat(
                    pos_offsets[:-1], pos_df
                )
                pos_keys[
                    prow_base[:-1][pterm] + plocal // BLOCK, plocal % BLOCK
                ] = flat_pos

        # per-field scoring constants, indexed by field code (dense tier +
        # impact tier share them)
        avgdl_of_field = np.ones(len(field_names), dtype=np.float64)
        has_norms_of_field = np.zeros(len(field_names), dtype=bool)
        for f, code in fld_code.items():
            st = field_stats.get(f, {"sum_dl": 0.0, "doc_count": 0})
            avgdl_of_field[code] = (
                st["sum_dl"] / max(st["doc_count"], 1)
            ) or 1.0
            has_norms_of_field[code] = f in norms

        # ---- impact tier (BM25S): quantized per-posting contributions ----
        impact_codes = impact_ubf = impact_meta = None
        if T:
            dtype = impact_dtype_default()
            qmax = IMPACT_QMAX[dtype]
            imp_dev = use_device_build(total_blocks * BLOCK)
            with build_stage("build.impact_quantize", rows=total_blocks,
                             code_bytes=2 if dtype == "uint16" else 1,
                             basis="device" if imp_dev else "host"):
                impact_ubf = impact_term_ubf(term_block_start, block_max_tf)
                row_terms = impact_row_terms(term_block_start, total_blocks)
                k_base, k_slope, scale_inv = impact_row_params(
                    row_terms, impact_ubf, field_of_term,
                    avgdl_of_field, has_norms_of_field, qmax)
                if imp_dev:
                    # PR 15: the quantization is a pure elementwise pass
                    # over the blocked CSR values — run it on device (the
                    # refresh_impacts shape, applied at build)
                    from .device_build import impact_codes_device

                    impact_codes = np.array(impact_codes_device(
                        post_tfs, post_dls, k_base, k_slope, scale_inv,
                        qmax=qmax, dtype=dtype))
                else:
                    impact_codes = impact_codes_host(
                        post_tfs, post_dls, k_base, k_slope, scale_inv,
                        qmax, dtype)
            impact_meta = {"dtype": dtype, "qmax": qmax,
                           "k1": BM25_K1, "b": BM25_B}

        # ---- dense tier (vectorized over all dense postings) -------------
        dense_ids = np.flatnonzero(df >= dense_min_df) if T else np.array([], np.int64)
        dense_keys = [keys[i] for i in dense_ids]
        dense_dict = {k: i for i, k in enumerate(dense_keys)}
        dense_tfn = None
        if dense_keys:
            # row count padded to a multiple of 128: per-shard vocabularies
            # differ slightly, and a lane-aligned row axis lets every shard
            # of an index share one compiled batched-query executable
            # (ops/batched.py W is [Q, V]); padding rows stay all-zero so
            # they never score or match
            with refresh_stage("dense_tier"):
                v_pad = -len(dense_keys) % 128
                dense_tfn = np.zeros((len(dense_keys) + v_pad, N),
                                     dtype=np.float32)
                dense_rank = np.full(T, -1, dtype=np.int64)
                dense_rank[dense_ids] = np.arange(len(dense_ids))
                dmask = dense_rank[term_of_post] >= 0
                rows = dense_rank[term_of_post[dmask]]
                cols = flat_docs[dmask]
                tfs_d = flat_tfs[dmask]
                dls_d = post_dl_flat[dmask]
                fcode = field_of_term[term_of_post[dmask]]
                K = np.where(
                    has_norms_of_field[fcode],
                    BM25_K1
                    * (1.0 - BM25_B + BM25_B * dls_d
                       / avgdl_of_field[fcode]),
                    BM25_K1,
                )
                dense_tfn[rows, cols] = (
                    tfs_d / (tfs_d + K)).astype(np.float32)

        completion = {
            fld: sorted(entries) for fld, entries in self.completion_raw.items()
        }
        percolator = dict(self.percolator_raw)
        return ShardPack(
            num_docs=N,
            post_docids=post_docids,
            post_tfs=post_tfs,
            post_dls=post_dls,
            term_block_start=term_block_start,
            term_df=term_df,
            block_max_tf=block_max_tf,
            block_min_len=block_min_len,
            term_dict=term_dict,
            norms=norms,
            text_present=text_present,
            field_stats=field_stats,
            docvalues=docvalues,
            vectors=vectors,
            live=np.ones(N, dtype=bool),
            dense_tfn=dense_tfn,
            dense_dict=dense_dict,
            pos_keys=pos_keys,
            term_pos_start=term_pos_start,
            term_pos_count=term_pos_count,
            completion=completion,
            percolator=percolator,
            impact_codes=impact_codes,
            impact_ubf=impact_ubf,
            impact_meta=impact_meta,
        )
