"""ShardPack <-> content-addressed component blobs (searchable snapshots).

The reference's frozen tier mounts Lucene files straight from the
repository, caching file REGIONS locally
(x-pack/plugin/blob-cache/src/main/java/org/elasticsearch/blobcache/shared/SharedBlobCacheService.java:68).
This framework's on-device representation is the ShardPack's numpy
arrays, so the unit of storage is the pack COMPONENT: every large array
(postings, norms, docvalues, vectors, dense tier, positions) becomes its
own content-addressed .npy blob — unchanged components of a re-snapshot
deduplicate to zero new bytes — and the small host-side state
(term dictionary, stats, completion/percolator lists) is one JSON meta
blob. No component is ever deserialized through pickle: a snapshot
repository is shared, possibly-untrusted storage, and `np.load` runs
with allow_pickle=False (tampered bytes fail, they cannot execute).
Mounting an index fetches these through the shared blob cache and
rebuilds the ShardPack directly: no per-document re-indexing, so a cold
search costs blob fetch + HBM upload, scaling with pack bytes rather
than doc count (VERDICT r4 #7).
"""

from __future__ import annotations

import io
import json

import numpy as np

from .pack import DocValuesColumn, ShardPack, VectorColumn

FORMAT = 2


def pack_layout_token() -> str:
    """Short digest of the pack's serialized layout: FORMAT plus the
    component-array inventory. Any pack-format/schema change (a new
    component, a renamed array, a FORMAT bump) changes the token, so
    caches of SERIALIZED packs keyed on it (bench.py's C5 corpus cache,
    ES_BENCH_C5_CACHE) can never silently feed a stale layout to a
    record run — the cache simply misses and rebuilds."""
    import hashlib

    basis = json.dumps({"format": FORMAT, "arrays": _ARRAYS},
                       sort_keys=True).encode()
    return hashlib.sha256(basis).hexdigest()[:12]

# top-level ndarray fields serialized as one component blob each.
# impact_codes/impact_ubf (the BM25S impact tier, PR 8) are OPTIONAL
# components: manifests written before the tier existed simply lack the
# keys, and deserialization degrades to impact_codes=None — the mounted
# pack scores through the raw-postings path until the next refresh
# rebuilds the tier (the ann_arrays compatibility discipline).
_ARRAYS = [
    "post_docids", "post_tfs", "post_dls", "term_block_start", "term_df",
    "block_max_tf", "block_min_len", "live", "dense_tfn", "pos_keys",
    "term_pos_start", "term_pos_count", "impact_codes", "impact_ubf",
]


def _np_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _np_load(payload: bytes) -> np.ndarray:
    return np.load(io.BytesIO(payload), allow_pickle=False)


def _json_bytes(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()


def serialize_pack(pack: ShardPack, put_blob) -> dict:
    """-> JSON-safe manifest; every component stored via put_blob(bytes)
    -> digest. Array components are .npy; `meta`/`ord_terms` are JSON."""
    man: dict = {"format": FORMAT, "num_docs": pack.num_docs,
                 "arrays": {}, "norms": {}, "text_present": {},
                 "docvalues": {}, "vectors": {}}
    for name in _ARRAYS:
        arr = getattr(pack, name)
        if arr is not None:
            man["arrays"][name] = put_blob(_np_bytes(arr))
    for fld, arr in pack.norms.items():
        man["norms"][fld] = put_blob(_np_bytes(arr))
    for fld, arr in pack.text_present.items():
        man["text_present"][fld] = put_blob(_np_bytes(arr))
    for fld, col in pack.docvalues.items():
        ent = {"kind": col.kind, "vmin": col.vmin, "vmax": col.vmax,
               "values": put_blob(_np_bytes(col.values)),
               "has_value": put_blob(_np_bytes(col.has_value))}
        for opt in ("uniq_values", "uniq_ords", "mv_pair_docs",
                    "mv_pair_ords"):
            arr = getattr(col, opt)
            if arr is not None:
                ent[opt] = put_blob(_np_bytes(arr))
        if col.ord_terms is not None:
            ent["ord_terms"] = put_blob(_json_bytes(list(col.ord_terms)))
        man["docvalues"][fld] = ent
    for fld, vc in pack.vectors.items():
        ent = {"similarity": vc.similarity, "dims": vc.dims,
               "values": put_blob(_np_bytes(vc.values)),
               "has_value": put_blob(_np_bytes(vc.has_value))}
        if vc.ann is not None:
            ent["ann_arrays"] = {k: put_blob(_np_bytes(np.asarray(v)))
                                 for k, v in vc.ann.items()
                                 if isinstance(v, np.ndarray)}
            ent["ann_scalars"] = {k: v for k, v in vc.ann.items()
                                  if not isinstance(v, np.ndarray)}
        if vc.ann_quant != "int8":
            ent["ann_quant"] = vc.ann_quant
        man["vectors"][fld] = ent
    meta = {
        "term_dict": [[f, t, tid]
                      for (f, t), tid in sorted(pack.term_dict.items(),
                                                key=lambda kv: kv[1])],
        "dense_dict": [[f, t, tid]
                       for (f, t), tid in sorted(pack.dense_dict.items(),
                                                 key=lambda kv: kv[1])],
        "field_stats": pack.field_stats,
        "completion": {f: [list(x) for x in lst]
                       for f, lst in pack.completion.items()},
        "percolator": {f: [list(x) for x in lst]
                       for f, lst in pack.percolator.items()},
    }
    if pack.impact_meta is not None:
        meta["impact_meta"] = pack.impact_meta
    man["meta"] = put_blob(_json_bytes(meta))
    return man


def deserialize_pack(man: dict, get_blob) -> ShardPack:
    """Rebuild a ShardPack from a serialize_pack manifest; get_blob is
    digest -> bytes (routed through the shared blob cache by mount)."""
    if man.get("format") != FORMAT:
        raise ValueError(f"unknown pack manifest format [{man.get('format')}]")
    arrays = {name: _np_load(get_blob(d))
              for name, d in man["arrays"].items()}
    meta = json.loads(get_blob(man["meta"]))
    docvalues = {}
    for fld, ent in man["docvalues"].items():
        docvalues[fld] = DocValuesColumn(
            kind=ent["kind"],
            values=_np_load(get_blob(ent["values"])),
            has_value=_np_load(get_blob(ent["has_value"])),
            ord_terms=(json.loads(get_blob(ent["ord_terms"]))
                       if "ord_terms" in ent else None),
            uniq_values=(_np_load(get_blob(ent["uniq_values"]))
                         if "uniq_values" in ent else None),
            uniq_ords=(_np_load(get_blob(ent["uniq_ords"]))
                       if "uniq_ords" in ent else None),
            vmin=ent["vmin"], vmax=ent["vmax"],
            mv_pair_docs=(_np_load(get_blob(ent["mv_pair_docs"]))
                          if "mv_pair_docs" in ent else None),
            mv_pair_ords=(_np_load(get_blob(ent["mv_pair_ords"]))
                          if "mv_pair_ords" in ent else None),
        )
    vectors = {}
    for fld, ent in man["vectors"].items():
        ann = None
        if "ann_arrays" in ent:
            ann = dict(ent.get("ann_scalars") or {})
            for k, d in ent["ann_arrays"].items():
                ann[k] = _np_load(get_blob(d))
        # manifests from before PR 7 carry "ivf_arrays": the host-side
        # probe layout the ann/ subsystem replaced — dropped on load
        # (the mounted index falls back to the exact scan; a refresh
        # rebuilds the ANN tiles)
        vectors[fld] = VectorColumn(
            values=_np_load(get_blob(ent["values"])),
            has_value=_np_load(get_blob(ent["has_value"])),
            similarity=ent["similarity"], dims=ent["dims"],
            ann=ann, ann_quant=ent.get("ann_quant", "int8"),
        )
    return ShardPack(
        num_docs=man["num_docs"],
        post_docids=arrays["post_docids"],
        post_tfs=arrays["post_tfs"],
        post_dls=arrays["post_dls"],
        term_block_start=arrays["term_block_start"],
        term_df=arrays["term_df"],
        block_max_tf=arrays["block_max_tf"],
        block_min_len=arrays["block_min_len"],
        term_dict={(f, t): tid for f, t, tid in meta["term_dict"]},
        norms={f: _np_load(get_blob(d)) for f, d in man["norms"].items()},
        text_present={f: _np_load(get_blob(d))
                      for f, d in man["text_present"].items()},
        field_stats=meta["field_stats"],
        docvalues=docvalues,
        vectors=vectors,
        live=arrays["live"],
        dense_tfn=arrays.get("dense_tfn"),
        dense_dict={(f, t): tid for f, t, tid in meta["dense_dict"]},
        pos_keys=arrays.get("pos_keys"),
        term_pos_start=arrays.get("term_pos_start"),
        term_pos_count=arrays.get("term_pos_count"),
        completion={f: [tuple(x) for x in lst]
                    for f, lst in meta["completion"].items()},
        percolator={f: [tuple(x) for x in lst]
                    for f, lst in meta["percolator"].items()},
        # optional impact tier: all three pieces or none (a partial
        # manifest — hand-edited or truncated — degrades whole)
        impact_codes=arrays.get("impact_codes"),
        impact_ubf=arrays.get("impact_ubf"),
        impact_meta=(meta.get("impact_meta")
                     if "impact_codes" in arrays else None),
    )


def manifest_digests(man: dict) -> list[str]:
    """Every blob digest a pack manifest references (GC accounting)."""
    out = list(man["arrays"].values()) + [man["meta"]]
    out += list(man["norms"].values()) + list(man["text_present"].values())
    for ent in man["docvalues"].values():
        out += [ent[k] for k in ("values", "has_value", "uniq_values",
                                 "uniq_ords", "mv_pair_docs",
                                 "mv_pair_ords", "ord_terms") if k in ent]
    for ent in man["vectors"].values():
        out += [ent["values"], ent["has_value"]]
        out += list((ent.get("ann_arrays") or {}).values())
        out += list((ent.get("ivf_arrays") or {}).values())  # pre-PR-7
    return out
