"""Lucene SmallFloat norm quantization (exact re-implementation).

BM25 parity requires reproducing how Lucene stores document length in a
single byte: values < 24 are exact, larger values keep a 4-bit mantissa
(reference behavior: Lucene 9 `SmallFloat.intToByte4`/`byte4ToInt`, used by
`BM25Similarity` — ES wires BM25 as the default at
server/.../index/similarity/SimilarityService.java:43-58). The scoring kernel
uses the *dequantized* length, so quantization here is what makes scores
bit-match a CPU Elasticsearch (SURVEY.md hard part #5).
"""

from __future__ import annotations

import numpy as np

# longToInt4(Integer.MAX_VALUE): numBits=31, shift=27, mantissa=(2^31-1)>>>27 & 7 = 7,
# encoded = 7 | (28<<3) = 231 -> NUM_FREE_VALUES = 255 - 231 = 24.
NUM_FREE_VALUES = 24


def long_to_int4(i: int) -> int:
    if i < 0:
        raise ValueError("only supports positive values")
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    encoded = (i >> shift) & 0x07
    encoded |= (shift + 1) << 3
    return encoded


def int4_to_long(i: int) -> int:
    bits = i & 0x07
    shift = (i >> 3) - 1
    if shift == -1:
        return bits
    return (bits | 0x08) << shift


def int_to_byte4(i: int) -> int:
    """Encode doc length -> unsigned byte (0..255)."""
    if i < 0:
        raise ValueError("only supports positive values")
    if i < NUM_FREE_VALUES:
        return i
    return NUM_FREE_VALUES + long_to_int4(i - NUM_FREE_VALUES)


def byte4_to_int(b: int) -> int:
    """Decode unsigned byte -> effective doc length used in scoring."""
    if b < NUM_FREE_VALUES:
        return b
    return NUM_FREE_VALUES + int4_to_long(b - NUM_FREE_VALUES)


# Decode table for all 256 byte values; device-side norm arrays store the
# already-dequantized float so kernels never branch.
DECODE_TABLE = np.array([byte4_to_int(b) for b in range(256)], dtype=np.float32)


def quantize_lengths(lengths: np.ndarray) -> np.ndarray:
    """Vectorized encode->decode: effective lengths after the 1-byte round
    trip. Encoding truncates, so the round trip maps x to the largest
    representable value <= x; DECODE_TABLE is monotone, so a searchsorted
    against it is exact."""
    idx = np.searchsorted(DECODE_TABLE, np.asarray(lengths, dtype=np.int64), side="right") - 1
    idx = np.clip(idx, 0, 255)
    return DECODE_TABLE[idx].astype(np.float32)
