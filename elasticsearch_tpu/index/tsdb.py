"""index.mode=time_series: dimension routing, _tsid synthesis, time
bounds, and the write-path restrictions of a TSDB index (VERDICT r4 #8).

Reference behavior: index/IndexMode.java:1 (TIME_SERIES validation:
routing_path required, index sorting forbidden, @timestamp mapping
enforced), index/routing/TsidBuilder + TimeSeriesIdFieldMapper (_tsid =
ordered encoding of every `time_series_dimension: true` field),
index/codec/tsdb/ (timestamp-ordered doc layout), and
cluster/routing/IndexRouting.ExtractFromSource (shard routing by hash of
the routing_path values, NOT the document id).

Documented divergence: the reference's _tsid/_id are base64 of a
murmur/sha composite (TimeSeriesIdFieldMapper.java 8.13 hashing); this
framework uses its own deterministic encoding (sha256-based), so the
VALUES differ while every behavioral property holds — same dimensions
=> same _tsid => same shard; (same _tsid, same @timestamp) => same _id
=> an exact duplicate overwrites (version 2) instead of duplicating.
"""

from __future__ import annotations

import base64
import hashlib

from ..utils.errors import IllegalArgumentError


def _parse_ts(v) -> int:
    """@timestamp -> epoch millis (int millis or ISO-8601 string)."""
    from .mappings import parse_date_to_millis

    if isinstance(v, str) and not v.strip():
        raise IllegalArgumentError("cannot parse empty datetime")
    # the reference's unbounded sentinels (IndexSettings TIME_SERIES
    # defaults) fall outside the parseable year range
    if v == "-9999-01-01T00:00:00Z":
        return -(1 << 60)
    if v == "9999-12-31T23:59:59.999Z":
        return 1 << 60
    return parse_date_to_millis(v)


def _fmt_millis(millis: int) -> str:
    """Bound echo format in error messages: ISO-8601 Z, seconds precision
    when the millis part is zero (the reference's date_optional_time)."""
    import datetime as _dt

    d = _dt.datetime.fromtimestamp(millis / 1000.0, _dt.timezone.utc)
    if millis % 1000:
        return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{millis % 1000:03d}Z"
    return d.strftime("%Y-%m-%dT%H:%M:%SZ")


class TimeSeriesMode:
    """Validated config of one time-series index."""

    def __init__(self, settings: dict, mappings):
        for bad in ("sort.field", "sort.order", "sort.mode", "sort.missing",
                    "routing_partition_size"):
            if settings.get(bad) is not None:
                raise IllegalArgumentError(
                    f"[index.mode=time_series] is incompatible with "
                    f"[index.{bad}]")
        # time-bound parse errors surface before the routing_path check
        # (tsdb/10_settings.yml "empty start end times" has both problems
        # and expects the date error)
        start, end = _time_bounds(settings)
        self.start_millis = _parse_ts(start) if start is not None else None
        self.end_millis = _parse_ts(end) if end is not None else None
        rp = settings.get("routing_path")
        if not rp:
            raise IllegalArgumentError(
                "[index.mode=time_series] requires a non-empty "
                "[index.routing_path]")
        if getattr(mappings, "routing_required", False):
            raise IllegalArgumentError(
                "routing is forbidden on CRUD operations that target "
                "indices in [index.mode=time_series]")
        self.routing_path = [rp] if isinstance(rp, str) else list(rp)
        # every mapped field a routing_path pattern matches must be a
        # dimension (IndexMode.validateRoutingPath)
        import fnmatch

        for pat in self.routing_path:
            for name, ft in mappings.fields.items():
                if (fnmatch.fnmatchcase(name, pat)
                        and not ft.extra.get("time_series_dimension")):
                    raise IllegalArgumentError(
                        f"All fields that match routing_path must be "
                        f"configured with [time_series_dimension: true] or "
                        f"flattened fields with a list of dimensions in "
                        f"[time_series_dimensions] and without the [script] "
                        f"parameter. [{name}] was [{ft.type}].")
        if (self.start_millis is not None and self.end_millis is not None
                and self.end_millis < self.start_millis):
            raise IllegalArgumentError(
                "[index.time_series.end_time] must be larger than "
                "[index.time_series.start_time]")
        self.mappings = mappings
        # _data_stream_timestamp meta field: always enabled on a TSDB
        # index; @timestamp is auto-mapped as date when absent and must
        # be date/date_nanos (DataStreamTimestampFieldMapper)
        dst = getattr(mappings, "ds_timestamp", None)
        if dst is not None and not isinstance(dst, dict):
            raise IllegalArgumentError(
                "[_data_stream_timestamp] config must be an object "
                f"[{dst}]")
        if isinstance(dst, dict) and dst.get("enabled") is False:
            raise IllegalArgumentError(
                "[_data_stream_timestamp] meta field has been disabled")
        ts_ft = mappings.fields.get("@timestamp")
        if ts_ft is None:
            from .mappings import FieldType

            mappings.fields["@timestamp"] = FieldType(
                name="@timestamp", type="date")
        elif ts_ft.type not in ("date", "date_nanos"):
            raise IllegalArgumentError(
                f"data stream timestamp field [@timestamp] is of type "
                f"[{ts_ft.type}], but [date,date_nanos] is expected")
        mappings._ds_timestamp_echo = True

    # ---- dimensions ------------------------------------------------------

    def _routing_fields(self) -> list[str]:
        """routing_path entries resolved against the mapped field names:
        a wildcard pattern (e.g. `k8s.pod.*`) expands to every mapped
        field it matches (IndexRouting.ExtractFromSource does the same
        via its pattern list); a literal entry resolves to itself, so
        dynamic/unmapped literal paths keep working."""
        import fnmatch

        out: set[str] = set()
        for pat in self.routing_path:
            if any(ch in pat for ch in "*?["):
                out.update(
                    name for name in self.mappings.fields
                    if fnmatch.fnmatchcase(name, pat)
                )
            else:
                out.add(pat)
        return sorted(out)

    def _dimension_fields(self) -> list[str]:
        dims = [
            name for name, ft in self.mappings.fields.items()
            if getattr(ft, "extra", {}).get("time_series_dimension")
        ]
        return sorted(set(dims) | set(self._routing_fields()))

    @staticmethod
    def _get_path(source: dict, path: str):
        cur = source
        for part in path.split("."):
            if not isinstance(cur, dict):
                return None
            cur = cur.get(part)
        return cur

    def dimensions_of(self, source: dict) -> list[tuple[str, str]]:
        out = []
        for f in self._dimension_fields():
            v = self._get_path(source, f)
            if v is not None:
                out.append((f, str(v)))
        return out

    def tsid_of(self, source: dict) -> str:
        """Deterministic _tsid: url-safe base64 of a sha256 over the
        ordered (dimension, value) pairs (divergence note above)."""
        dims = self.dimensions_of(source)
        if not dims:
            raise IllegalArgumentError(
                "a document must contain at least one dimension")
        h = hashlib.sha256()
        for k, v in dims:
            h.update(k.encode())
            h.update(b"\x00")
            h.update(v.encode())
            h.update(b"\x00")
        return base64.urlsafe_b64encode(h.digest()[:27]).decode().rstrip("=")

    # ---- write-path checks ----------------------------------------------

    def check_timestamp(self, source: dict) -> int:
        ts = source.get("@timestamp")
        if ts is None:
            raise IllegalArgumentError(
                "data stream timestamp field [@timestamp] is missing")
        millis = _parse_ts(ts)
        if self.start_millis is not None and millis < self.start_millis:
            raise IllegalArgumentError(
                f"time series index @timestamp value [{ts}] must be larger "
                f"than {_fmt_millis(self.start_millis)}")
        if self.end_millis is not None and millis >= self.end_millis:
            raise IllegalArgumentError(
                f"time series index @timestamp value [{ts}] must be smaller "
                f"than {_fmt_millis(self.end_millis)}")
        return millis

    def doc_id_of(self, source: dict) -> str:
        """_id = f(tsid, @timestamp): indexing the same point twice is an
        overwrite, never a duplicate (reference TsidExtractingIdFieldMapper)."""
        millis = self.check_timestamp(source)
        tsid = self.tsid_of(source)
        raw = hashlib.sha256(f"{tsid}\x00{millis}".encode()).digest()[:15]
        return (base64.urlsafe_b64encode(raw).decode().rstrip("=")
                + f"{millis & 0xFFFFFF:06x}")

    def shard_of(self, source: dict, num_shards: int) -> int:
        """Routing by the routing_path dimension values: every doc of one
        time series lands on one shard (IndexRouting.ExtractFromSource).
        Wildcard routing_path entries hash the mapped fields they expand
        to (_routing_fields) — hashing the literal pattern would extract
        nothing and make the index unwritable."""
        h = hashlib.sha256()
        found = False
        for f in self._routing_fields():
            v = self._get_path(source, f)
            if v is not None:
                found = True
                h.update(f.encode())
                h.update(b"\x00")
                h.update(str(v).encode())
                h.update(b"\x00")
        if not found:
            raise IllegalArgumentError(
                "Error extracting routing: source didn't contain any "
                "routing fields")
        return int.from_bytes(h.digest()[:4], "big") % max(num_shards, 1)


def _time_bounds(settings: dict):
    ts = settings.get("time_series") or {}
    start = ts.get("start_time") if isinstance(ts, dict) else None
    end = ts.get("end_time") if isinstance(ts, dict) else None
    start = settings.get("time_series.start_time", start)
    end = settings.get("time_series.end_time", end)
    return start, end


def time_series_mode(settings: dict, mappings) -> TimeSeriesMode | None:
    """-> the validated mode object when settings enable it, else None.
    Standard mode REJECTS the time-series-only settings instead of
    carrying them inert (tsdb/10_settings.yml; VERDICT r4 weak #7)."""
    mode = settings.get("mode")
    if mode in (None, "standard", "null"):
        if settings.get("routing_path"):
            raise IllegalArgumentError(
                "[index.routing_path] requires [index.mode=time_series]")
        start, end = _time_bounds(settings)
        if start is not None:
            raise IllegalArgumentError(
                "[index.time_series.start_time] requires "
                "[index.mode=time_series]")
        if end is not None:
            raise IllegalArgumentError(
                "[index.time_series.end_time] requires "
                "[index.mode=time_series]")
        return None
    if mode != "time_series":
        raise IllegalArgumentError(f"[{mode}] is an invalid index mode")
    return TimeSeriesMode(settings, mappings)
