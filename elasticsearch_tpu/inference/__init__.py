"""Inference API: embedding models running natively on the TPU.

The reference's inference plugin exposes `_inference/{task_type}/{id}`
endpoints that route to configured services and wire into ingest (the
`inference` processor) and search (knn `query_vector_builder`) — reference
behavior: x-pack/plugin/inference InferenceBaseRestHandler + service
registry; TransportInferenceAction. This is the one x-pack surface where a
TPU-native stack has a structural advantage: embedding is a batched
matmul pipeline, so it shares the device and the batching machinery with
scoring.

The built-in service here is `tpu_embedding`: a deterministic hashed
bag-of-tokens encoder — token hashes index a seeded embedding table, mean
pool, project, L2-normalize — the shape (not the quality) of a sentence
encoder, compiled once per (batch, dims) and entirely on-device. Real
checkpoints would slot into the same Service interface; the API surface,
ingest wiring, and query-time embedding are what parity is about.

Task types follow the reference: text_embedding (dense), sparse_embedding
(token -> weight maps, the ELSER shape), rerank, completion (stubbed to
similarity ranking — no generative model ships in-tree).
"""

from __future__ import annotations

import hashlib
import re

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.errors import (
    IllegalArgumentError,
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
)

_TOKEN_RE = re.compile(r"[a-z0-9]+")

VOCAB_BUCKETS = 1 << 15


def _hash_tokens(text: str) -> np.ndarray:
    toks = _TOKEN_RE.findall(text.lower())
    if not toks:
        return np.zeros(0, np.int32)
    return np.array(
        [int.from_bytes(hashlib.blake2b(t.encode(), digest_size=4).digest(),
                        "little") % VOCAB_BUCKETS
         for t in toks],
        np.int32,
    )


class TpuEmbeddingModel:
    """Hashed bag-of-tokens dense encoder, parameters derived from the
    model seed so results are reproducible across nodes."""

    def __init__(self, inference_id: str, dims: int = 384, seed: int | None = None):
        self.inference_id = inference_id
        self.dims = dims
        if seed is None:
            seed = int.from_bytes(
                hashlib.blake2b(inference_id.encode(), digest_size=4).digest(),
                "little",
            )
        # table in bf16: 32k x dims, the embedding analog of the bf16 dense
        # scoring tier; accumulation in f32. Drawn with numpy's seeded
        # generator, whose bit-exact output is part of its API contract —
        # jax.random's sampling is an implementation detail that has
        # changed across releases, and "reproducible across nodes" must
        # also mean across runtime versions
        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(
            rng.standard_normal((VOCAB_BUCKETS, self.dims), dtype=np.float32),
            jnp.bfloat16,
        )
        self._embed = jax.jit(self._embed_fn)

    def _embed_fn(self, ids, mask):
        vecs = self.table[ids].astype(jnp.float32)  # [B, L, D]
        summed = (vecs * mask[:, :, None]).sum(axis=1)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
        mean = summed / denom
        norm = jnp.linalg.norm(mean, axis=1, keepdims=True)
        return mean / jnp.maximum(norm, 1e-6)

    def embed(self, texts: list[str]) -> np.ndarray:
        tok = [_hash_tokens(t) for t in texts]
        L = max((len(t) for t in tok), default=1) or 1
        L = 1 << (L - 1).bit_length()  # pow2 pad: bounded compiled shapes
        ids = np.zeros((len(texts), L), np.int32)
        mask = np.zeros((len(texts), L), np.float32)
        for i, t in enumerate(tok):
            ids[i, : len(t)] = t
            mask[i, : len(t)] = 1.0
        return np.asarray(self._embed(jnp.asarray(ids), jnp.asarray(mask)))

    def sparse_embed(self, texts: list[str]) -> list[dict[str, float]]:
        """sparse_embedding task shape: token -> weight (tf-saturated)."""
        out = []
        for t in texts:
            toks = _TOKEN_RE.findall(t.lower())
            counts: dict[str, int] = {}
            for tk in toks:
                counts[tk] = counts.get(tk, 0) + 1
            out.append({tk: round(c / (c + 1.0), 6) for tk, c in counts.items()})
        return out


class InferenceService:
    """Model registry + execution (TransportPutInferenceModelAction /
    TransportInferenceAction analogs)."""

    TASK_TYPES = ("text_embedding", "sparse_embedding", "rerank", "completion")

    def __init__(self):
        self.models: dict[str, dict] = {}
        self._loaded: dict[str, TpuEmbeddingModel] = {}

    def put(self, inference_id: str, task_type: str, body: dict) -> dict:
        if task_type not in self.TASK_TYPES:
            raise IllegalArgumentError(f"unknown task_type [{task_type}]")
        if inference_id in self.models:
            raise ResourceAlreadyExistsError(
                f"inference endpoint [{inference_id}] already exists")
        service = (body or {}).get("service", "tpu_embedding")
        settings = dict((body or {}).get("service_settings") or {})
        dims = int(settings.get("dimensions", 384))
        cfg = {
            "inference_id": inference_id,
            "task_type": task_type,
            "service": service,
            "service_settings": {**settings, "dimensions": dims,
                                 "similarity": settings.get("similarity", "cosine")},
        }
        self.models[inference_id] = cfg
        return cfg

    def get(self, inference_id: str | None = None) -> dict:
        if inference_id in (None, "_all"):
            return {"endpoints": sorted(self.models.values(),
                                        key=lambda c: c["inference_id"])}
        cfg = self.models.get(inference_id)
        if cfg is None:
            raise ResourceNotFoundError(
                f"Inference endpoint not found [{inference_id}]")
        return {"endpoints": [cfg]}

    def delete(self, inference_id: str) -> dict:
        if inference_id not in self.models:
            raise ResourceNotFoundError(
                f"Inference endpoint not found [{inference_id}]")
        del self.models[inference_id]
        self._loaded.pop(inference_id, None)
        return {"acknowledged": True}

    def _model(self, inference_id: str) -> TpuEmbeddingModel:
        cfg = self.models.get(inference_id)
        if cfg is None:
            raise ResourceNotFoundError(
                f"Inference endpoint not found [{inference_id}]")
        m = self._loaded.get(inference_id)
        if m is None:
            ss = cfg["service_settings"]
            m = TpuEmbeddingModel(inference_id, dims=ss["dimensions"],
                                  seed=ss.get("seed"))
            self._loaded[inference_id] = m
        return m

    def infer(self, inference_id: str, inputs, task_type: str | None = None,
              query: str | None = None) -> dict:
        cfg = self.models.get(inference_id)
        if cfg is None:
            raise ResourceNotFoundError(
                f"Inference endpoint not found [{inference_id}]")
        if task_type is not None and task_type != cfg["task_type"]:
            raise IllegalArgumentError(
                f"endpoint [{inference_id}] is of task_type "
                f"[{cfg['task_type']}], requested [{task_type}]")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not isinstance(inputs, list) or not all(isinstance(x, str) for x in inputs):
            raise IllegalArgumentError("[input] must be a string or string array")
        tt = cfg["task_type"]
        model = self._model(inference_id)
        if tt == "text_embedding":
            vecs = model.embed(inputs)
            return {"text_embedding": [
                {"embedding": [float(x) for x in v]} for v in vecs
            ]}
        if tt == "sparse_embedding":
            return {"sparse_embedding": [
                {"is_truncated": False, "embedding": e}
                for e in model.sparse_embed(inputs)
            ]}
        if tt == "rerank":
            if query is None:
                raise IllegalArgumentError("rerank requires [query]")
            qv = model.embed([query])[0]
            dv = model.embed(inputs)
            scores = dv @ qv
            order = np.argsort(-scores, kind="stable")
            return {"rerank": [
                {"index": int(i), "relevance_score": float(scores[i]),
                 "text": inputs[int(i)]}
                for i in order
            ]}
        # completion: no generative model in-tree; nearest-tokens echo keeps
        # the API contract exercisable (documented divergence)
        return {"completion": [{"result": inp} for inp in inputs]}

    def embed_one(self, inference_id: str, text: str) -> list[float]:
        """Query-time embedding for knn query_vector_builder."""
        return [float(x) for x in self._model(inference_id).embed([text])[0]]


def resolve_query_vector_builders(obj, service: InferenceService):
    """Replace every knn `query_vector_builder` in a query/knn body with the
    embedded `query_vector` (reference behavior: KnnSearchBuilder rewrite +
    TextEmbeddingQueryVectorBuilder). Walks the whole tree so the builder
    works in the top-level knn section AND in knn queries nested in bool."""
    if isinstance(obj, dict):
        if "query_vector_builder" in obj:
            b = obj["query_vector_builder"]
            te = b.get("text_embedding") if isinstance(b, dict) else None
            if (not isinstance(te, dict) or "model_id" not in te
                    or "model_text" not in te):
                raise IllegalArgumentError(
                    "[query_vector_builder] supports [text_embedding] with "
                    "[model_id] and [model_text]")
            out = {k: resolve_query_vector_builders(v, service)
                   for k, v in obj.items() if k != "query_vector_builder"}
            out["query_vector"] = service.embed_one(
                te["model_id"], str(te["model_text"]))
            return out
        return {k: resolve_query_vector_builders(v, service)
                for k, v in obj.items()}
    if isinstance(obj, list):
        return [resolve_query_vector_builders(v, service) for v in obj]
    return obj
