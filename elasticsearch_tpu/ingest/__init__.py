"""IngestService: pipeline registry + execution on the bulk path.

Reference behavior: ingest/IngestService.java:98 (pipeline CRUD via cluster
state, execution hook in the bulk path :701), Pipeline/CompoundProcessor
(on_failure semantics), _ingest/pipeline REST APIs + _simulate."""

from __future__ import annotations

import time

from ..utils.errors import IllegalArgumentError
from .processors import (
    PROCESSOR_TYPES,
    DropDocument,
    ForeachProcessor,
    IngestProcessorError,
    PipelineProcessor,
    Processor,
)


class Pipeline:
    def __init__(self, name: str, config: dict, service: "IngestService"):
        self.name = name
        self.config = config
        self.description = config.get("description")
        self.version = config.get("version")
        self.service = service
        self.processors = [self._build(p) for p in config.get("processors") or []]
        self.on_failure = [self._build(p) for p in config.get("on_failure") or []]

    def _build(self, spec: dict) -> Processor:
        if not isinstance(spec, dict) or len(spec) != 1:
            raise IllegalArgumentError(
                f"processor must be an object with exactly one key, got {spec!r}"
            )
        (ptype, config), = spec.items()
        config = dict(config or {})
        on_failure = config.pop("on_failure", None)
        if ptype == "pipeline":
            proc = PipelineProcessor(config, ingest_service=self.service)
        elif ptype == "foreach":
            proc = ForeachProcessor(config, build_processor=self._build)
        elif ptype in PROCESSOR_TYPES:
            proc = PROCESSOR_TYPES[ptype](config)
            if ptype in ("enrich", "inference"):
                proc.engine = getattr(self.service, "engine", None)
        else:
            from ..plugins import registry

            cls = registry.processors.get(ptype)
            if cls is None:
                raise IllegalArgumentError(f"No processor type exists with name [{ptype}]")
            proc = cls(config)
            proc.engine = getattr(self.service, "engine", None)
        if on_failure:
            proc.on_failure = [self._build(p) for p in on_failure]
        else:
            proc.on_failure = None
        return proc

    def run(self, ctx: dict) -> dict | None:
        """Returns the transformed ctx, or None if the document was dropped."""
        try:
            for proc in self.processors:
                try:
                    if not proc.should_run(ctx):
                        continue
                    proc.process(ctx)
                except DropDocument:
                    raise
                except Exception as ex:
                    if proc.ignore_failure:
                        continue
                    if proc.on_failure:
                        self._run_failure_chain(proc.on_failure, ctx, ex)
                        continue
                    raise
        except DropDocument:
            return None
        except Exception as ex:
            if self.on_failure:
                try:
                    self._run_failure_chain(self.on_failure, ctx, ex)
                    return ctx
                except DropDocument:
                    return None
            raise
        return ctx

    @staticmethod
    def _run_failure_chain(processors, ctx, ex):
        meta = ctx.setdefault("_ingest", {})
        meta["on_failure_message"] = str(ex)
        meta["on_failure_processor_type"] = getattr(ex, "processor_type", None)
        for proc in processors:
            if proc.should_run(ctx):
                proc.process(ctx)


class IngestService:
    def __init__(self):
        self.pipelines: dict[str, dict] = {}
        self._compiled: dict[str, Pipeline] = {}

    # -- CRUD --------------------------------------------------------------

    def put_pipeline(self, name: str, config: dict):
        # compile eagerly: invalid configs are rejected at PUT time, as the
        # reference validates on put (IngestService.validatePipeline)
        pipe = Pipeline(name, config, self)
        self.pipelines[name] = config
        self._compiled[name] = pipe
        return {"acknowledged": True}

    def get_pipeline(self, name: str) -> Pipeline | None:
        return self._compiled.get(name)

    def get_pipeline_config(self, name: str) -> dict | None:
        return self.pipelines.get(name)

    def delete_pipeline(self, name: str) -> bool:
        self._compiled.pop(name, None)
        return self.pipelines.pop(name, None) is not None

    # -- execution ---------------------------------------------------------

    def execute(self, pipeline_name: str, source: dict, index: str | None = None,
                doc_id: str | None = None) -> dict | None:
        """Run a document through a pipeline. Returns the new source, or None
        if dropped. Raises on missing pipeline or unhandled processor error."""
        pipe = self._compiled.get(pipeline_name)
        if pipe is None:
            raise IllegalArgumentError(f"pipeline with id [{pipeline_name}] does not exist")
        ctx = dict(source)
        ctx["_ingest"] = {"timestamp": _iso_now(), "pipeline": pipeline_name}
        if index is not None:
            ctx["_index"] = index
        if doc_id is not None:
            ctx["_id"] = doc_id
        out = pipe.run(ctx)
        if out is None:
            return None
        out.pop("_ingest", None)
        out.pop("_index", None)
        out.pop("_id", None)
        return out

    def execute_batch(self, pipeline_names, sources: list,
                      index: str | None = None,
                      doc_ids: list | None = None) -> list:
        """Run a batch of documents through an already-resolved pipeline
        chain (PR 16 bulk front door). Registry lookups and the ingest
        timestamp are hoisted once per batch instead of per doc. Returns
        per-doc outcomes aligned with `sources`: the new source dict,
        None if a drop processor fired, or the captured Exception when
        that doc's chain failed — the caller owns the per-item error
        envelope, so this method itself never raises for a bad doc.

        A missing pipeline is raised lazily per doc (not validated up
        front) so a doc dropped by the first pipeline still reports a
        drop, never a missing-final-pipeline error — byte-identical to
        the per-doc execute() path."""
        pipes = [(name, self._compiled.get(name))
                 for name in pipeline_names if name]
        ts = _iso_now()
        if doc_ids is None:
            doc_ids = [None] * len(sources)
        outs: list = []
        for source, doc_id in zip(sources, doc_ids):
            try:
                for name, pipe in pipes:
                    if pipe is None:
                        raise IllegalArgumentError(
                            f"pipeline with id [{name}] does not exist")
                    ctx = dict(source)
                    ctx["_ingest"] = {"timestamp": ts, "pipeline": name}
                    if index is not None:
                        ctx["_index"] = index
                    if doc_id is not None:
                        ctx["_id"] = doc_id
                    out = pipe.run(ctx)
                    if out is None:
                        source = None
                        break
                    out.pop("_ingest", None)
                    out.pop("_index", None)
                    out.pop("_id", None)
                    source = out
                outs.append(source)
            except Exception as ex:  # noqa: BLE001 - per-doc outcome
                outs.append(ex)
        return outs

    def simulate(self, config_or_name, docs: list[dict], verbose: bool = False) -> dict:
        """_ingest/pipeline/_simulate."""
        if isinstance(config_or_name, str):
            pipe = self._compiled.get(config_or_name)
            if pipe is None:
                raise IllegalArgumentError(
                    f"pipeline with id [{config_or_name}] does not exist"
                )
        else:
            pipe = Pipeline("_simulate_pipeline", config_or_name, self)
        results = []
        for d in docs:
            src = dict(d.get("_source") or {})
            ctx = dict(src)
            ctx["_ingest"] = {"timestamp": _iso_now()}
            for k in ("_index", "_id"):
                if k in d:
                    ctx[k] = d[k]
            try:
                out = pipe.run(ctx)
                if out is None:
                    results.append({"doc": None})
                else:
                    meta = out.pop("_ingest", None)
                    results.append({"doc": {
                        "_index": out.pop("_index", d.get("_index", "_index")),
                        "_id": out.pop("_id", d.get("_id", "_id")),
                        "_source": out,
                        "_ingest": meta,
                    }})
            except Exception as ex:
                results.append({"error": {
                    "type": getattr(ex, "type", "exception"),
                    "reason": str(ex),
                }})
        return {"docs": results}


def _iso_now() -> str:
    from datetime import datetime, timezone

    return datetime.now(timezone.utc).isoformat()
