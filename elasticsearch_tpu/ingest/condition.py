"""Host-side condition/script evaluation over an ingest document context.

The reference evaluates processor `if` conditions and `script` processors as
Painless against a ctx map (reference behavior: ingest/ConditionalProcessor.java,
modules/ingest-common ScriptProcessor). This module reuses the expression
parser (script/expression.py) with a host resolver that adds strings, null,
ctx.path access, and string methods — the imperative host-side subset, kept
separate from the device compiler on purpose: device scripts must be pure
array math; ingest runs on the host mutation path where strings are fine.
"""

from __future__ import annotations

from ..script.expression import ScriptError, _Parser, _tokenize


def _lookup(ctx: dict, path: list[str]):
    cur = ctx
    for p in path:
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
        else:
            return None
    return cur


def _resolve_path(ast) -> list[str] | None:
    """('name','ctx') / attr/index chains -> field path list, else None."""
    parts: list[str] = []
    while True:
        if ast[0] == "attr":
            parts.append(ast[2])
            ast = ast[1]
        elif ast[0] == "index":
            parts.append(ast[2])
            ast = ast[1]
        elif ast == ("name", "ctx"):
            return list(reversed(parts))
        else:
            return None


class HostExpr:
    """Evaluate a parsed expression against a ctx dict (returns python
    scalars/strings/lists)."""

    def __init__(self, source: str):
        self.source = source
        self.ast = _Parser(_tokenize(source)).parse()

    def eval(self, ctx: dict):
        return self._eval(self.ast, ctx)

    def _eval(self, ast, ctx):
        kind = ast[0]
        if kind == "num":
            v = ast[1]
            return int(v) if float(v).is_integer() else v
        if kind == "strlit":
            return ast[1]
        if kind == "name":
            n = ast[1]
            if n == "ctx":
                return ctx
            if n == "null":
                return None
            if n in ("true", "false"):
                return n == "true"
            raise ScriptError(f"unknown identifier [{n}] (use ctx.field)")
        path = _resolve_path(ast)
        if path is not None:
            return _lookup(ctx, path)
        if kind in ("attr", "index"):
            base = self._eval(ast[1], ctx)
            key = ast[2]
            if isinstance(base, dict):
                return base.get(key)
            if key == "length" and isinstance(base, (str, list)):
                return len(base)
            return None
        if kind == "call":
            return self._call(ast, ctx)
        if kind == "un":
            v = self._eval(ast[2], ctx)
            if ast[1] == "-":
                return -(v or 0)
            return not self._truthy(v)
        if kind == "bin":
            a = self._eval(ast[2], ctx)
            b = self._eval(ast[3], ctx)
            op = ast[1]
            if op == "+":
                if isinstance(a, str) or isinstance(b, str):
                    return f"{'' if a is None else a}{'' if b is None else b}"
                return (a or 0) + (b or 0)
            a = a or 0
            b = b or 0
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b
            if op == "%":
                return a % b
            return a**b
        if kind == "cmp":
            a = self._eval(ast[2], ctx)
            b = self._eval(ast[3], ctx)
            op = ast[1]
            if op == "==":
                return a == b
            if op == "!=":
                return a != b
            if a is None or b is None:
                return False
            return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]
        if kind == "bool":
            a = self._truthy(self._eval(ast[2], ctx))
            if ast[1] == "or":
                return a or self._truthy(self._eval(ast[3], ctx))
            return a and self._truthy(self._eval(ast[3], ctx))
        if kind == "tern":
            return (
                self._eval(ast[2], ctx)
                if self._truthy(self._eval(ast[1], ctx))
                else self._eval(ast[3], ctx)
            )
        raise ScriptError(f"unsupported in ingest context: {kind}")

    def _call(self, ast, ctx):
        fn, args = ast[1], ast[2]
        vals = [self._eval(a, ctx) for a in args]
        if fn[0] == "attr":
            recv = self._eval(fn[1], ctx)
            method = fn[2]
            if method == "contains":
                return vals[0] in recv if recv is not None else False
            if method == "containsKey":
                return isinstance(recv, dict) and vals[0] in recv
            if method == "startsWith":
                return isinstance(recv, str) and recv.startswith(vals[0])
            if method == "endsWith":
                return isinstance(recv, str) and recv.endswith(vals[0])
            if method == "toLowerCase":
                return recv.lower() if isinstance(recv, str) else recv
            if method == "toUpperCase":
                return recv.upper() if isinstance(recv, str) else recv
            if method == "trim":
                return recv.strip() if isinstance(recv, str) else recv
            if method == "isEmpty":
                return recv is None or len(recv) == 0
            if method == "size" or method == "length":
                return len(recv) if recv is not None else 0
            raise ScriptError(f"unknown method [{method}]")
        if fn == ("name", "abs"):
            return abs(vals[0] or 0)
        if fn == ("name", "min"):
            return min(vals)
        if fn == ("name", "max"):
            return max(vals)
        raise ScriptError(f"unknown function {fn}")

    @staticmethod
    def _truthy(v) -> bool:
        return bool(v)


class Condition:
    """A processor `if` condition."""

    def __init__(self, source: str):
        self.expr = HostExpr(source)

    def matches(self, ctx: dict) -> bool:
        return HostExpr._truthy(self.expr.eval(ctx))


class HostScript:
    """`script` processor body: semicolon-separated `ctx.path = expr`
    assignments (plus bare expressions, ignored results)."""

    def __init__(self, source: str):
        self.source = source
        self.statements: list[tuple[list[str] | None, HostExpr]] = []
        for stmt in self._split(source):
            stmt = stmt.strip()
            if not stmt:
                continue
            target, expr = self._parse_assignment(stmt)
            self.statements.append((target, HostExpr(expr)))

    @staticmethod
    def _split(src: str) -> list[str]:
        out, cur, in_str, q = [], [], False, ""
        for ch in src:
            if in_str:
                cur.append(ch)
                if ch == q:
                    in_str = False
            elif ch in "'\"":
                in_str, q = True, ch
                cur.append(ch)
            elif ch == ";":
                out.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        if cur:
            out.append("".join(cur))
        return out

    @staticmethod
    def _parse_assignment(stmt: str):
        depth = 0
        in_str, q = False, ""
        for i, ch in enumerate(stmt):
            if in_str:
                if ch == q:
                    in_str = False
            elif ch in "'\"":
                in_str, q = True, ch
            elif ch in "([":
                depth += 1
            elif ch in ")]":
                depth -= 1
            elif ch == "=" and depth == 0:
                prev = stmt[i - 1] if i else ""
                nxt = stmt[i + 1] if i + 1 < len(stmt) else ""
                if prev not in "=!<>" and nxt != "=":
                    lhs = stmt[:i].strip()
                    ast = _Parser(_tokenize(lhs)).parse()
                    path = _resolve_path(ast)
                    if path is None:
                        raise ScriptError(f"assignment target must be ctx.path: [{lhs}]")
                    return path, stmt[i + 1 :].strip()
        return None, stmt

    def run(self, ctx: dict):
        for target, expr in self.statements:
            val = expr.eval(ctx)
            if target is None:
                continue
            cur = ctx
            for p in target[:-1]:
                nxt = cur.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    cur[p] = nxt
                cur = nxt
            cur[target[-1]] = val
