"""Ingest processors (reference behavior: ingest/Processor SPI +
modules/ingest-common/src/main/java/org/elasticsearch/ingest/common/*).

Each processor transforms a ctx dict (the document source plus _index/_id
metadata under reserved keys). Dotted field paths address nested objects, as
in the reference's IngestDocument."""

from __future__ import annotations

import json
import re
from typing import Any

from ..utils.errors import IllegalArgumentError
from .condition import Condition, HostScript


class IngestProcessorError(Exception):
    def __init__(self, message: str, processor_type: str):
        super().__init__(message)
        self.processor_type = processor_type


class DropDocument(Exception):
    """Raised by the drop processor: the document is discarded, not indexed."""


# -- field path helpers ----------------------------------------------------


def _split_path(path: str) -> list[str]:
    if not path:
        raise IllegalArgumentError("field path cannot be empty")
    return path.split(".")


def get_field(ctx: dict, path: str, default=None):
    cur: Any = ctx
    for p in _split_path(path):
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
        else:
            return default
    return cur


def has_field(ctx: dict, path: str) -> bool:
    sentinel = object()
    return get_field(ctx, path, sentinel) is not sentinel


def set_field(ctx: dict, path: str, value):
    parts = _split_path(path)
    cur = ctx
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value


def remove_field(ctx: dict, path: str) -> bool:
    parts = _split_path(path)
    cur = ctx
    for p in parts[:-1]:
        cur = cur.get(p)
        if not isinstance(cur, dict):
            return False
    return cur.pop(parts[-1], None) is not None


def render_template(tmpl: str, ctx: dict) -> str:
    """Mustache-style {{field}} substitution (the reference renders values
    through lang-mustache)."""

    def sub(m):
        v = get_field(ctx, m.group(1).strip())
        return "" if v is None else str(v)

    return re.sub(r"\{\{\{?([^}]+?)\}?\}\}", sub, tmpl)


# -- the processors --------------------------------------------------------


class Processor:
    type: str = "?"

    def __init__(self, config: dict):
        self.config = config
        self.if_cond = Condition(config["if"]) if config.get("if") else None
        self.ignore_failure = bool(config.get("ignore_failure", False))
        self.on_failure = config.get("on_failure")  # built by the pipeline
        self.tag = config.get("tag")
        self.description = config.get("description")

    def should_run(self, ctx: dict) -> bool:
        return self.if_cond is None or self.if_cond.matches(ctx)

    def process(self, ctx: dict) -> None:
        raise NotImplementedError

    def _fail(self, msg: str):
        raise IngestProcessorError(msg, self.type)

    def _field(self, key="field") -> str:
        v = self.config.get(key)
        if not v:
            self._fail(f"[{key}] required property is missing")
        return v


class SetProcessor(Processor):
    type = "set"

    def process(self, ctx):
        field = self._field()
        if self.config.get("override", True) is False and get_field(ctx, field) is not None:
            return
        if "copy_from" in self.config:
            val = get_field(ctx, self.config["copy_from"])
        else:
            val = self.config.get("value")
            if isinstance(val, str) and "{{" in val:
                val = render_template(val, ctx)
        set_field(ctx, field, val)


class RemoveProcessor(Processor):
    type = "remove"

    def process(self, ctx):
        fields = self.config.get("field")
        fields = fields if isinstance(fields, list) else [fields]
        for f in fields:
            found = remove_field(ctx, f)
            if not found and not self.config.get("ignore_missing", False):
                self._fail(f"field [{f}] not present as part of path [{f}]")


class RenameProcessor(Processor):
    type = "rename"

    def process(self, ctx):
        src, dst = self._field(), self._field("target_field")
        if not has_field(ctx, src):
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{src}] doesn't exist")
        if has_field(ctx, dst) and not self.config.get("override", False):
            self._fail(f"field [{dst}] already exists")
        val = get_field(ctx, src)
        remove_field(ctx, src)
        set_field(ctx, dst, val)


class ConvertProcessor(Processor):
    type = "convert"

    def process(self, ctx):
        field = self._field()
        target = self.config.get("target_field", field)
        typ = self.config.get("type")
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] not present")

        def conv1(v):
            try:
                if typ in ("integer", "long"):
                    return int(str(v), 0) if isinstance(v, str) else int(v)
                if typ in ("float", "double"):
                    return float(v)
                if typ == "string":
                    return str(v).lower() if isinstance(v, bool) else str(v)
                if typ == "boolean":
                    s = str(v).lower()
                    if s in ("true", "false"):
                        return s == "true"
                    raise ValueError(s)
                if typ == "auto":
                    s = str(v)
                    for f in (lambda: int(s), lambda: float(s)):
                        try:
                            return f()
                        except ValueError:
                            pass
                    if s.lower() in ("true", "false"):
                        return s.lower() == "true"
                    return v
                if typ == "ip":
                    import ipaddress

                    ipaddress.ip_address(str(v))
                    return str(v)
            except (ValueError, TypeError):
                self._fail(f"unable to convert [{v}] to {typ}")
            self._fail(f"type [{typ}] not supported")

        set_field(ctx, target, [conv1(v) for v in val] if isinstance(val, list) else conv1(val))


class _StringProcessor(Processor):
    def transform(self, s: str) -> str:
        raise NotImplementedError

    def process(self, ctx):
        field = self._field()
        target = self.config.get("target_field", field)
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        if isinstance(val, list):
            set_field(ctx, target, [self.transform(str(v)) for v in val])
        else:
            set_field(ctx, target, self.transform(str(val)))


class LowercaseProcessor(_StringProcessor):
    type = "lowercase"

    def transform(self, s):
        return s.lower()


class UppercaseProcessor(_StringProcessor):
    type = "uppercase"

    def transform(self, s):
        return s.upper()


class TrimProcessor(_StringProcessor):
    type = "trim"

    def transform(self, s):
        return s.strip()


class HtmlStripProcessor(_StringProcessor):
    type = "html_strip"

    def transform(self, s):
        return re.sub(r"<[^>]*>", "", s)


class UrldecodeProcessor(_StringProcessor):
    type = "urldecode"

    def transform(self, s):
        from urllib.parse import unquote_plus

        return unquote_plus(s)


class SplitProcessor(Processor):
    type = "split"

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        sep = self.config.get("separator")
        if sep is None:
            self._fail("[separator] required property is missing")
        parts = re.split(sep, str(val))
        if not self.config.get("preserve_trailing", False):
            while parts and parts[-1] == "":
                parts.pop()
        set_field(ctx, self.config.get("target_field", field), parts)


class JoinProcessor(Processor):
    type = "join"

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        if not isinstance(val, list):
            self._fail(f"field [{field}] of type [{type(val).__name__}] cannot be cast to a list")
        sep = self.config.get("separator", "")
        set_field(ctx, self.config.get("target_field", field),
                  sep.join(str(v) for v in val))


class AppendProcessor(Processor):
    type = "append"

    def process(self, ctx):
        field = self._field()
        value = self.config.get("value")
        values = value if isinstance(value, list) else [value]
        values = [render_template(v, ctx) if isinstance(v, str) and "{{" in v else v
                  for v in values]
        cur = get_field(ctx, field)
        if cur is None:
            cur = []
        elif not isinstance(cur, list):
            cur = [cur]
        if self.config.get("allow_duplicates", True):
            cur = cur + values
        else:
            cur = cur + [v for v in values if v not in cur]
        set_field(ctx, field, cur)


class GsubProcessor(Processor):
    type = "gsub"

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        out = re.sub(self.config.get("pattern", ""),
                     self.config.get("replacement", ""), str(val))
        set_field(ctx, self.config.get("target_field", field), out)


class DateProcessor(Processor):
    type = "date"

    def process(self, ctx):
        from ..index.mappings import parse_date_to_millis
        from datetime import datetime, timezone

        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            self._fail(f"field [{field}] is null or missing")
        formats = self.config.get("formats", ["ISO8601"])
        ms = None
        last = None
        for fmt in formats:
            try:
                if fmt in ("ISO8601", "strict_date_optional_time", "date_optional_time"):
                    ms = parse_date_to_millis(val)
                elif fmt == "UNIX":
                    ms = int(float(val) * 1000)
                elif fmt == "UNIX_MS":
                    ms = int(val)
                else:
                    # java date format subset -> python strptime
                    py = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
                          .replace("dd", "%d").replace("HH", "%H")
                          .replace("mm", "%M").replace("ss", "%S"))
                    dt = datetime.strptime(str(val), py).replace(tzinfo=timezone.utc)
                    ms = int(dt.timestamp() * 1000)
                break
            except Exception as ex:
                last = ex
        if ms is None:
            self._fail(f"unable to parse date [{val}]: {last}")
        dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
        out = dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"
        set_field(ctx, self.config.get("target_field", "@timestamp"), out)


class FailProcessor(Processor):
    type = "fail"

    def process(self, ctx):
        self._fail(render_template(self.config.get("message", "fail"), ctx))


class DropProcessor(Processor):
    type = "drop"

    def process(self, ctx):
        raise DropDocument()


class JsonProcessor(Processor):
    type = "json"

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        try:
            parsed = json.loads(val)
        except (TypeError, ValueError) as ex:
            self._fail(f"unable to parse JSON in field [{field}]: {ex}")
        if self.config.get("add_to_root", False):
            if not isinstance(parsed, dict):
                self._fail("cannot add non-object JSON to root")
            ctx.update(parsed)
        else:
            set_field(ctx, self.config.get("target_field", field), parsed)


class KvProcessor(Processor):
    type = "kv"

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        fs = self.config.get("field_split", " ")
        vs = self.config.get("value_split", "=")
        target = self.config.get("target_field")
        include = self.config.get("include_keys")
        exclude = set(self.config.get("exclude_keys") or [])
        out = {}
        for pair in re.split(fs, str(val)):
            if not pair:
                continue
            kv = re.split(vs, pair, maxsplit=1)
            if len(kv) != 2:
                continue
            k, v = kv
            if include is not None and k not in include:
                continue
            if k in exclude:
                continue
            out[k] = v
        for k, v in out.items():
            set_field(ctx, f"{target}.{k}" if target else k, v)


class CsvProcessor(Processor):
    type = "csv"

    def process(self, ctx):
        import csv as _csv
        import io

        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        targets = self.config.get("target_fields") or []
        sep = self.config.get("separator", ",")
        quote = self.config.get("quote", '"')
        row = next(_csv.reader(io.StringIO(str(val)), delimiter=sep, quotechar=quote))
        for name, v in zip(targets, row):
            set_field(ctx, name, v)


class DissectProcessor(Processor):
    """%{key} pattern splitter (libs/dissect DissectParser)."""

    type = "dissect"

    def process(self, ctx):
        field = self._field()
        pattern = self.config.get("pattern")
        if pattern is None:
            self._fail("[pattern] required property is missing")
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        sep = self.config.get("append_separator", "")
        keys = re.findall(r"%\{([^}]*)\}", pattern)
        rx_parts = re.split(r"%\{[^}]*\}", pattern)
        rx = "".join(
            re.escape(p) + ("(.*?)" if i < len(keys) else "")
            for i, p in enumerate(rx_parts)
        ) + "$"
        m = re.match(rx, str(val), re.DOTALL)
        if m is None:
            self._fail(f"Unable to find match for dissect pattern: {pattern} "
                       f"against source: {val}")
        appends: dict[str, list] = {}
        for key, g in zip(keys, m.groups()):
            if not key or key.startswith("?"):
                continue
            if key.startswith("+"):
                appends.setdefault(key[1:], []).append(g)
            else:
                set_field(ctx, key, g)
        for key, parts in appends.items():
            base = get_field(ctx, key)
            all_parts = ([base] if base is not None else []) + parts
            set_field(ctx, key, sep.join(str(p) for p in all_parts))


_GROK_PATTERNS = {
    "WORD": r"\w+",
    "NOTSPACE": r"\S+",
    "SPACE": r"\s*",
    "DATA": r".*?",
    "GREEDYDATA": r".*",
    "INT": r"[+-]?\d+",
    "NUMBER": r"[+-]?\d+(?:\.\d+)?",
    "BASE10NUM": r"[+-]?\d+(?:\.\d+)?",
    "POSINT": r"\d+",
    "IP": r"\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}",
    "IPORHOST": r"[\w.\-:]+",
    "HOSTNAME": r"[\w.\-]+",
    "USER": r"[\w.\-]+",
    "USERNAME": r"[\w.\-]+",
    "EMAILADDRESS": r"[\w.+\-]+@[\w.\-]+",
    "UUID": r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}",
    "TIMESTAMP_ISO8601": r"\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}:\d{2}(?:\.\d+)?(?:Z|[+-]\d{2}:?\d{2})?",
    "LOGLEVEL": r"(?:TRACE|DEBUG|INFO|NOTICE|WARN(?:ING)?|ERROR|SEVERE|CRIT(?:ICAL)?|FATAL)",
    "HTTPDATE": r"\d{2}/\w{3}/\d{4}:\d{2}:\d{2}:\d{2} [+-]\d{4}",
    "QS": r"\"[^\"]*\"",
    "QUOTEDSTRING": r"\"[^\"]*\"",
    "URIPATH": r"/[^\s?#]*",
    "URIPARAM": r"\?[^\s#]*",
}


class GrokProcessor(Processor):
    """Grok with the core built-in pattern set (the reference bundles the full
    pattern bank in libs/grok; this is the commonly-used subset)."""

    type = "grok"

    def __init__(self, config):
        super().__init__(config)
        self.patterns = config.get("patterns") or []
        if not self.patterns:
            self._fail("[patterns] required property is missing")
        bank = dict(_GROK_PATTERNS)
        bank.update(config.get("pattern_definitions") or {})
        self.compiled = []
        for p in self.patterns:
            self.compiled.append(re.compile(self._to_regex(p, bank)))

    def _to_regex(self, pattern: str, bank: dict, depth=0) -> str:
        if depth > 10:
            self._fail("circular grok pattern reference")

        def sub(m):
            name = m.group(1)
            field = m.group(3)
            typ = m.group(5)
            body = bank.get(name)
            if body is None:
                self._fail(f"Unable to find pattern [{name}]")
            body = self._to_regex(body, bank, depth + 1)
            if field:
                safe = field.replace(".", "__DOT__").replace("@", "__AT__")
                return f"(?P<{safe}>{body})"
            return f"(?:{body})"

        return re.sub(r"%\{(\w+)(:([\w.@]+)(:(int|long|float|double))?)?\}", sub, pattern)

    def process(self, ctx):
        field = self._field()
        val = get_field(ctx, field)
        if val is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        for pat_src, rx in zip(self.patterns, self.compiled):
            m = rx.search(str(val))
            if m is None:
                continue
            types = dict(re.findall(r"%\{\w+:([\w.@]+):(int|long|float|double)\}", pat_src))
            for k, v in m.groupdict().items():
                if v is None:
                    continue
                k = k.replace("__DOT__", ".").replace("__AT__", "@")
                t = types.get(k)
                if t in ("int", "long"):
                    v = int(v)
                elif t in ("float", "double"):
                    v = float(v)
                set_field(ctx, k, v)
            return
        self._fail(f"Provided Grok expressions do not match field value: [{val}]")


class ScriptProcessor(Processor):
    type = "script"

    def __init__(self, config):
        super().__init__(config)
        spec = config.get("source") or (config.get("script") or {})
        src = spec if isinstance(spec, str) else spec.get("source")
        if not src:
            self._fail("[source] required property is missing")
        self.script = HostScript(src)

    def process(self, ctx):
        self.script.run(ctx)


class PipelineProcessor(Processor):
    type = "pipeline"

    def __init__(self, config, ingest_service=None):
        super().__init__(config)
        self.ingest_service = ingest_service

    def process(self, ctx):
        name = self.config.get("name")
        pipeline = self.ingest_service.get_pipeline(name)
        if pipeline is None:
            if self.config.get("ignore_missing_pipeline", False):
                return
            self._fail(f"Pipeline processor configured for non-existent pipeline [{name}]")
        pipeline.run(ctx)


class ForeachProcessor(Processor):
    type = "foreach"

    def __init__(self, config, build_processor=None):
        super().__init__(config)
        spec = config.get("processor")
        if not spec or len(spec) != 1:
            self._fail("[processor] required property is missing")
        self.inner = build_processor(spec)

    def process(self, ctx):
        field = self._field()
        vals = get_field(ctx, field)
        if vals is None:
            if self.config.get("ignore_missing", False):
                return
            self._fail(f"field [{field}] is null or missing")
        if not isinstance(vals, list):
            self._fail(f"field [{field}] is not a list")
        out = []
        for v in vals:
            ctx["_ingest"] = {**ctx.get("_ingest", {}), "_value": v}
            self.inner.process(ctx)
            out.append(ctx["_ingest"]["_value"])
        set_field(ctx, field, out)


class EnrichProcessor(Processor):
    """enrich: add fields from an executed enrich policy's lookup table
    (reference behavior: x-pack/plugin/enrich MatchProcessor — exact-match
    lookup by the policy's match_field). The owning engine is attached by
    Pipeline._build (`self.engine`)."""

    type = "enrich"

    def __init__(self, config):
        super().__init__(config)
        self.policy_name = self._field("policy_name")
        self.fld = self._field("field")
        self.target = self._field("target_field")
        self.override = bool(self.config.get("override", True))
        self.ignore_missing = bool(self.config.get("ignore_missing", False))
        self.engine = None

    def process(self, ctx):
        from ..xpack import enrich_lookup

        if self.engine is None:
            self._fail("enrich processor has no engine attached")
        value = get_field(ctx, self.fld)
        if value is None:
            if self.ignore_missing:
                return
            self._fail(f"field [{self.fld}] is missing")
        row = enrich_lookup(self.engine, self.policy_name, value)
        if row is None:
            return
        if self.override or not has_field(ctx, self.target):
            set_field(ctx, self.target, dict(row))


class InferenceProcessor(Processor):
    """inference: run an inference endpoint over document fields at ingest
    (reference behavior: x-pack InferenceProcessor — the embedding path of
    semantic indexing). Config follows the modern `input_output` form:
    [{"input_field", "output_field"}]. The owning engine is attached by
    Pipeline._build (`self.engine`)."""

    type = "inference"

    def __init__(self, config):
        super().__init__(config)
        self.model_id = self._field("model_id")
        io = self.config.get("input_output")
        if not isinstance(io, list) or not io:
            self._fail("inference processor requires [input_output]")
        for entry in io:
            if (not isinstance(entry, dict) or "input_field" not in entry
                    or "output_field" not in entry):
                self._fail(
                    "[input_output] entries require [input_field] and "
                    "[output_field]")
        self.input_output = [
            (entry["input_field"], entry["output_field"]) for entry in io
        ]
        self.ignore_missing = bool(self.config.get("ignore_missing", False))
        self.engine = None

    def process(self, ctx):
        if self.engine is None:
            self._fail("inference processor has no engine attached")
        svc = self.engine.inference
        cfg = svc.models.get(self.model_id)
        if cfg is None:
            self._fail(f"Inference endpoint not found [{self.model_id}]")
        for in_f, out_f in self.input_output:
            value = get_field(ctx, in_f)
            if value is None:
                if self.ignore_missing:
                    continue
                self._fail(f"field [{in_f}] is missing")
            if cfg["task_type"] == "sparse_embedding":
                out = svc.infer(self.model_id, [str(value)])
                set_field(ctx, out_f, out["sparse_embedding"][0]["embedding"])
            else:
                set_field(ctx, out_f, svc.embed_one(self.model_id, str(value)))


PROCESSOR_TYPES = {
    cls.type: cls
    for cls in (
        SetProcessor, RemoveProcessor, RenameProcessor, ConvertProcessor,
        LowercaseProcessor, UppercaseProcessor, TrimProcessor,
        HtmlStripProcessor, UrldecodeProcessor, SplitProcessor, JoinProcessor,
        AppendProcessor, GsubProcessor, DateProcessor, FailProcessor,
        DropProcessor, JsonProcessor, KvProcessor, CsvProcessor,
        DissectProcessor, GrokProcessor, ScriptProcessor, EnrichProcessor,
        InferenceProcessor,
    )
}
