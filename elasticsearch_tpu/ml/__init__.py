"""Machine learning: anomaly-detection jobs with datafeeds, a native JAX
model, checkpointed model state, and the `_ml` REST surface.

The reference's x-pack ML plugin (1,085 files) runs anomaly detection in
sidecar C++ `autodetect` processes fed over named pipes; this framework
owns JAX on the accelerator, so the model (online seasonal-trend
decomposition + streaming robust scale estimation, ml/model.py) runs
in-process where the data already lives, scoring every bucket vectorized
across detectors and partitions in one device call. Jobs run on the
persistent-task framework; model state checkpoints through the
content-addressed blob layout so close/reopen, node restart, and
failover to another node all resume from learned state.
"""

from .config import DatafeedConfig, JobConfig, results_index_name
from .job import MlJobTaskExecutor, MlService

__all__ = [
    "DatafeedConfig",
    "JobConfig",
    "MlJobTaskExecutor",
    "MlService",
    "results_index_name",
]
