"""ML job / datafeed configuration: parsing + validation.

Parity target: the reference's job and datafeed configs
(x-pack/plugin/core/.../ml/job/config/Job.java — job_id, analysis_config
{bucket_span, detectors[{function, field_name, partition_field_name}]},
data_description {time_field}, analysis_limits {model_memory_limit};
.../datafeed/DatafeedConfig.java — datafeed_id, job_id, indices, query,
frequency). Only the config surface this framework's native JAX model
consumes is validated strictly; unknown keys are preserved opaquely the
way the reference tolerates forward-compatible fields.
"""

from __future__ import annotations

import re

from ..common.settings import parse_bytes
from ..utils.durations import parse_duration_seconds
from ..utils.errors import IllegalArgumentError

JOB_ID_RE = re.compile(r"^[a-z0-9](?:[a-z0-9_\-]{0,62}[a-z0-9])?$")

# detector functions the JAX model scores natively. `metric` is the
# reference's default (mean); low_/high_ variants are one-sided.
FUNCTIONS = {
    "count", "low_count", "high_count",
    "mean", "avg", "metric", "low_mean", "high_mean",
    "min", "max", "sum", "low_sum", "high_sum",
}
# functions that need no field (they score the bucket doc count)
COUNT_FUNCTIONS = {"count", "low_count", "high_count"}
# one-sided senses: -1 flags only drops, +1 only spikes, 0 both
FUNCTION_SIDE = {
    "low_count": -1, "high_count": 1,
    "low_mean": -1, "high_mean": 1,
    "low_sum": -1, "high_sum": 1,
}


def _agg_of(function: str) -> str:
    """Datafeed sub-aggregation serving a detector function."""
    base = function.removeprefix("low_").removeprefix("high_")
    if base in ("mean", "avg", "metric"):
        return "avg"
    return base  # min / max / sum (count uses doc_count)


class Detector:
    def __init__(self, index: int, spec: dict):
        fn = spec.get("function")
        if fn not in FUNCTIONS:
            raise IllegalArgumentError(f"Unknown function [{fn}]")
        self.index = index
        self.function = fn
        self.field_name = spec.get("field_name")
        if fn in COUNT_FUNCTIONS:
            if self.field_name:
                raise IllegalArgumentError(
                    f"field_name cannot be used with function [{fn}]")
        elif not self.field_name:
            raise IllegalArgumentError(
                f"Unless the function is 'count' one of field_name, "
                f"by_field_name or over_field_name must be set")
        self.partition_field_name = spec.get("partition_field_name")
        self.by_field_name = spec.get("by_field_name")
        if self.by_field_name and self.partition_field_name:
            raise IllegalArgumentError(
                "by_field_name and partition_field_name cannot both be set "
                "on one detector (native model splits one way)")
        # by_field splits series exactly like partition here (the reference
        # differs only in result aggregation weights)
        self.split_field = self.partition_field_name or self.by_field_name
        self.description = spec.get("detector_description") or fn
        self.side = FUNCTION_SIDE.get(fn, 0)

    @property
    def agg(self) -> str | None:
        return None if self.function in COUNT_FUNCTIONS else _agg_of(self.function)

    def to_dict(self) -> dict:
        out = {"detector_index": self.index, "function": self.function,
               "detector_description": self.description}
        if self.field_name:
            out["field_name"] = self.field_name
        if self.partition_field_name:
            out["partition_field_name"] = self.partition_field_name
        if self.by_field_name:
            out["by_field_name"] = self.by_field_name
        return out


class JobConfig:
    def __init__(self, job_id: str, body: dict):
        if not JOB_ID_RE.match(job_id or ""):
            raise IllegalArgumentError(
                f"Invalid job_id; '{job_id}' can contain lowercase "
                "alphanumeric (a-z and 0-9), hyphens or underscores; must "
                "start and end with alphanumeric")
        self.job_id = job_id
        ac = body.get("analysis_config")
        if not isinstance(ac, dict):
            raise IllegalArgumentError("[analysis_config] is required")
        span = parse_duration_seconds(ac.get("bucket_span", "5m"))
        if not span or span <= 0:
            raise IllegalArgumentError("[bucket_span] must be a positive time value")
        self.bucket_span = int(span)
        raw_detectors = ac.get("detectors")
        if not isinstance(raw_detectors, list) or not raw_detectors:
            raise IllegalArgumentError("No detectors configured")
        self.detectors = [Detector(i, d) for i, d in enumerate(raw_detectors)]
        # seasonal period in buckets: explicit, else daily when the span
        # divides a day into a modest number of buckets (the reference
        # learns periodicity; the native model fixes the candidate period)
        period = ac.get("period_buckets")
        if period is None:
            period = 86400 // self.bucket_span \
                if 86400 % self.bucket_span == 0 else 0
            if not (2 <= period <= 288):
                period = 0
        self.period_buckets = int(period)
        dd = body.get("data_description") or {}
        self.time_field = dd.get("time_field", "time")
        limits = body.get("analysis_limits") or {}
        self.model_memory_limit = parse_bytes(
            limits.get("model_memory_limit", "16mb"))
        self.description = body.get("description")
        self.raw = body

    def to_dict(self) -> dict:
        out = {
            "job_id": self.job_id,
            "job_type": "anomaly_detector",
            "analysis_config": {
                "bucket_span": f"{self.bucket_span}s",
                "detectors": [d.to_dict() for d in self.detectors],
            },
            "data_description": {"time_field": self.time_field},
            "analysis_limits": {
                "model_memory_limit": f"{self.model_memory_limit // (1 << 20)}mb"},
            "results_index_name": results_index_name(self.job_id),
        }
        if self.period_buckets:
            out["analysis_config"]["period_buckets"] = self.period_buckets
        if self.description:
            out["description"] = self.description
        return out


class DatafeedConfig:
    def __init__(self, datafeed_id: str, body: dict):
        if not JOB_ID_RE.match(datafeed_id or ""):
            raise IllegalArgumentError(f"Invalid datafeed_id [{datafeed_id}]")
        self.datafeed_id = datafeed_id
        self.job_id = body.get("job_id")
        if not self.job_id:
            raise IllegalArgumentError("[job_id] is required")
        indices = body.get("indices") or body.get("indexes")
        if isinstance(indices, str):
            indices = [indices]
        if not indices:
            raise IllegalArgumentError("[indices] is required")
        self.indices = list(indices)
        self.query = body.get("query") or {"match_all": {}}
        self.frequency = parse_duration_seconds(body.get("frequency"), None)
        self.raw = body

    def to_dict(self) -> dict:
        return {
            "datafeed_id": self.datafeed_id,
            "job_id": self.job_id,
            "indices": self.indices,
            "query": self.query,
        }


def results_index_name(job_id: str) -> str:
    # the reference writes to .ml-anomalies-shared by default; a per-job
    # hidden index keeps results deletable with the job
    return f".ml-anomalies-{job_id}"
