"""Datafeed extraction: bucketed model input pulled through the engine's
normal aggregation path.

Parity target: the reference's DatafeedJob + aggregation data extractor
(x-pack/plugin/ml/.../datafeed/extractor/aggregation/
AggregationDataExtractor.java — a date_histogram at bucket_span with one
sub-aggregation per detector, paged over [start, end)). Here the whole
window is one search: the date-histogram agg runs segmented on device,
and the response is reshaped host-side into dense [B, series] batches
(absent metric buckets keep a present=False mask; count detectors see an
explicit 0 — the reference's empty-bucket semantics).
"""

from __future__ import annotations

import numpy as np

from .config import DatafeedConfig, JobConfig

# terms-agg width for partition discovery; partitions beyond this are
# dropped with a telemetry counter (no silent truncation)
MAX_PARTITIONS = 1024


def bucket_floor(ts_ms: int, span_s: int) -> int:
    span_ms = span_s * 1000
    return (int(ts_ms) // span_ms) * span_ms


def build_aggs(job: JobConfig) -> dict:
    """The datafeed's aggregation body: date_histogram(bucket_span) with
    per-detector sub-aggs, split detectors nesting a terms agg."""
    sub: dict = {}
    for d in job.detectors:
        if d.split_field:
            inner = {}
            if d.agg:
                inner[f"d{d.index}"] = {d.agg: {"field": d.field_name}}
            sub[f"split{d.index}"] = {
                "terms": {"field": d.split_field, "size": MAX_PARTITIONS},
                **({"aggs": inner} if inner else {}),
            }
        elif d.agg:
            sub[f"d{d.index}"] = {d.agg: {"field": d.field_name}}
    return {
        "buckets": {
            "date_histogram": {"field": job.time_field,
                               "fixed_interval": f"{job.bucket_span}s"},
            **({"aggs": sub} if sub else {}),
        }
    }


def pull(engine, df: DatafeedConfig, job: JobConfig,
         start_ms: int, end_ms: int) -> dict:
    """Extract complete buckets in [start_ms, end_ms) -> {
        "bucket_starts": [B] ms (contiguous, span-aligned),
        "event_counts": [B] int,
        "series": {(detector_index, split_value|None):
                   (values [B] f64, present [B] bool)},
        "truncated_partitions": int,
    } — empty B when no complete bucket fits the window."""
    span_ms = job.bucket_span * 1000
    lo = bucket_floor(start_ms, job.bucket_span)
    if lo < start_ms:
        lo += span_ms  # only buckets fully inside the window
    hi = bucket_floor(end_ms, job.bucket_span)  # exclusive
    if hi <= lo:
        return {"bucket_starts": np.zeros(0, np.int64),
                "event_counts": np.zeros(0, np.int64),
                "series": {}, "truncated_partitions": 0}
    query = {"bool": {"filter": [
        df.query,
        {"range": {job.time_field: {"gte": lo, "lt": hi,
                                    "format": "epoch_millis"}}},
    ]}}
    expr = ",".join(df.indices)
    res = engine.search_multi(expr, query=query, size=0,
                              aggs=build_aggs(job))
    raw = (res.get("aggregations") or {}).get("buckets", {}).get("buckets", [])
    starts = np.arange(lo, hi, span_ms, dtype=np.int64)
    B = len(starts)
    pos = {int(s): i for i, s in enumerate(starts)}
    event_counts = np.zeros(B, np.int64)
    series: dict = {}
    truncated = 0

    def slot(key):
        if key not in series:
            series[key] = (np.zeros(B, np.float64), np.zeros(B, bool))
        return series[key]

    # count detectors exist even when the window is all-empty
    for d in job.detectors:
        if d.agg is None and not d.split_field:
            slot((d.index, None))
    for b in raw:
        i = pos.get(int(b["key"]))
        if i is None:
            continue  # partial edge bucket outside [lo, hi)
        event_counts[i] = b.get("doc_count", 0)
        for d in job.detectors:
            if d.split_field:
                sb = (b.get(f"split{d.index}") or {}).get("buckets") or []
                if len(sb) >= MAX_PARTITIONS:
                    truncated += 1
                for part in sb:
                    key = (d.index, str(part["key"]))
                    if d.agg is None:
                        v, m = slot(key)
                        v[i] = float(part.get("doc_count", 0))
                        m[i] = True
                    else:
                        got = (part.get(f"d{d.index}") or {}).get("value")
                        if got is not None:
                            v, m = slot(key)
                            v[i] = float(got)
                            m[i] = True
            elif d.agg is None:
                v, m = slot((d.index, None))
                v[i] = float(b.get("doc_count", 0))
            else:
                got = (b.get(f"d{d.index}") or {}).get("value")
                if got is not None:
                    v, m = slot((d.index, None))
                    v[i] = float(got)
                    m[i] = True
    # count detectors: every bucket in the window is an observation —
    # zero-count buckets are real zeros, not missing data
    for d in job.detectors:
        if d.agg is None:
            for (di, split), (v, m) in series.items():
                if di == d.index:
                    m[:] = True
    return {"bucket_starts": starts, "event_counts": event_counts,
            "series": series, "truncated_partitions": truncated}


def preview(engine, df: DatafeedConfig, job: JobConfig, limit: int = 100) -> list[dict]:
    """First `limit` flattened (time, detector inputs) rows — the
    reference's datafeed _preview shape (flat docs, not aggs)."""
    fields = sorted({d.field_name for d in job.detectors if d.field_name}
                    | {d.split_field for d in job.detectors if d.split_field})
    res = engine.search_multi(
        ",".join(df.indices), query=df.query, size=limit,
        sort=[{job.time_field: {"order": "asc"}}])
    out = []
    for h in res["hits"]["hits"]:
        src = h.get("_source") or {}
        row = {job.time_field: src.get(job.time_field)}
        for f in fields:
            if f in src:
                row[f] = src[f]
        out.append(row)
    return out
