"""ML job lifecycle: persistent-task-backed anomaly-detection jobs with
checkpointed model state.

Parity targets (reference): x-pack/plugin/ml/.../job/JobManager.java (job
CRUD + open/close through the persistent task framework,
OpenJobPersistentTasksExecutor), .../job/process/autodetect/
AutodetectProcessManager.java (one model per open job, results persisted
per bucket, model state checkpointed so close/reopen and node failover
resume seamlessly), and ModelSnapshot retention. The sidecar C++
autodetect process of the reference is replaced by the in-process JAX
model (ml/model.py); model state checkpoints ride the content-addressed
blob layout (snapshots/repository.py) instead of .ml-state documents, so
a job adopted by ANOTHER node (shared state repository) reopens from the
exact learned seasonality the failed node last persisted.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..snapshots.repository import FsRepository, InMemoryRepository
from ..telemetry import record_ml_event
from ..utils.errors import (
    IllegalArgumentError,
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
)
from . import datafeed as datafeed_mod
from . import model as model_mod
from . import results as results_mod
from .config import DatafeedConfig, JobConfig, results_index_name

PERSISTENT_TASK_NAME = "xpack/ml/job"
SNAPSHOT_RETENTION = 10


class JobRuntime:
    """Open-job state: the live model plus series registry and progress."""

    def __init__(self, cfg: JobConfig):
        self.cfg = cfg
        self.state = model_mod.init_state(model_mod.MIN_CAP, cfg.period_buckets)
        self.series: dict[tuple[int, str | None], int] = {}
        self.processed_end_ms: int | None = None
        self.allocation_id = 1
        self.memory_status = "ok"
        self.counts = {
            "processed_record_count": 0,
            "bucket_count": 0,
            "latest_record_timestamp": None,
            "latest_bucket_timestamp": None,
        }

    def nbytes(self) -> int:
        return model_mod.state_nbytes(self.state)

    def snapshot_meta(self) -> dict:
        return {
            "job_id": self.cfg.job_id,
            "series": [[di, split, slot]
                       for (di, split), slot in sorted(self.series.items(),
                                                       key=lambda kv: kv[1])],
            "processed_end_ms": self.processed_end_ms,
            "allocation_id": self.allocation_id,
            "counts": self.counts,
        }

    def restore_meta(self, meta: dict):
        self.series = {(int(di), split): int(slot)
                       for di, split, slot in meta.get("series", [])}
        self.processed_end_ms = meta.get("processed_end_ms")
        self.allocation_id = int(meta.get("allocation_id", 1))
        self.counts.update(meta.get("counts") or {})


class MlJobTaskExecutor:
    """Persistent-task executor: each scheduler tick advances every open
    job's started datafeed to the newest complete bucket (real-time mode;
    lookback-with-end runs synchronously in start_datafeed)."""

    def tick(self, engine, task):
        ml = engine.ml
        job_id = (task.get("params") or {}).get("job_id")
        if job_id not in ml.runtimes:
            # allocated task without a live model: this node restarted (or
            # the task failed over here) — reopen from the latest model
            # snapshot, exactly the reference's job-reallocation path
            try:
                ml.open_job(job_id)
            except ResourceNotFoundError:
                return  # config gone: orphaned task, nothing to run
        if job_id not in ml.runtimes:
            return
        for df_id, df_cfg in list(ml._datafeeds().items()):
            st = ml._datafeed_state().get(df_id) or {}
            if df_cfg.get("job_id") == job_id and st.get("state") == "started":
                ml._advance_datafeed(df_id, end_ms=int(time.time() * 1000))
        # periodic checkpoint: content addressing dedups unchanged state,
        # so an idle tick writes nothing; after progress the latest learned
        # state is always recoverable (node restart / failover)
        ml.checkpoint(job_id, reason="scheduled")
        task["state"]["last_tick_ms"] = int(time.time() * 1000)


class MlService:
    """Node-level ML: job/datafeed registries (cluster metadata), open-job
    runtimes, model-state repository, breaker-accounted model memory."""

    def __init__(self, engine):
        self.engine = engine
        self.runtimes: dict[str, JobRuntime] = {}
        self._mem_repo: InMemoryRepository | None = None
        self._repo_cache: tuple[str, FsRepository] | None = None
        engine.persistent.register_executor(
            PERSISTENT_TASK_NAME, MlJobTaskExecutor())

    # ---- stores ----------------------------------------------------------

    def _jobs(self) -> dict:
        return self.engine.meta.extras.setdefault("ml_jobs", {})

    def _datafeeds(self) -> dict:
        return self.engine.meta.extras.setdefault("ml_datafeeds", {})

    def _datafeed_state(self) -> dict:
        return self.engine.meta.extras.setdefault("ml_datafeed_state", {})

    def _check_enabled(self):
        if not self.engine.settings.get("xpack.ml.enabled"):
            raise IllegalArgumentError("machine learning is disabled "
                                       "(xpack.ml.enabled: false)")

    # ---- model-state repository (content-addressed blob layout) ----------

    def repo(self):
        import os

        path = self.engine.settings.get("xpack.ml.state_repository_path")
        if not path and self.engine.data_path:
            path = os.path.join(self.engine.data_path, "ml_state")
        if not path:
            if self._mem_repo is None:
                self._mem_repo = InMemoryRepository()
            return self._mem_repo
        if self._repo_cache is None or self._repo_cache[0] != path:
            self._repo_cache = (path, FsRepository(path))
        return self._repo_cache[1]

    def invalidate_repo_cache(self):
        self._repo_cache = None

    def _repo_meta(self, job_id: str) -> dict:
        repo = self.repo()
        name = f"ml/jobs/{job_id}.json"
        if repo.exists(name):
            return json.loads(repo.read(name))
        return {"config": None, "datafeeds": {}, "snapshots": [],
                "snapshot_seq": 0, "current_snapshot": None}

    def _save_repo_meta(self, job_id: str, meta: dict):
        self.repo().write(f"ml/jobs/{job_id}.json",
                          json.dumps(meta, sort_keys=True).encode())

    # ---- job CRUD --------------------------------------------------------

    def _cfg(self, job_id: str) -> JobConfig:
        stored = self._jobs().get(job_id)
        if stored is None:
            raise ResourceNotFoundError(
                f"No known job with id '{job_id}'")
        return JobConfig(job_id, stored["config"])

    def put_job(self, job_id: str, body: dict) -> dict:
        self._check_enabled()
        if job_id in self._jobs():
            raise ResourceAlreadyExistsError(
                f"The job cannot be created with the Id '{job_id}'. "
                "The Id is already used.")
        cfg = JobConfig(job_id, body or {})
        entry = {"config": body, "create_time": int(time.time() * 1000),
                 "state": "closed"}
        self._jobs()[job_id] = entry
        self.engine.meta.save()
        # publish the config to the shared state repository so another
        # node can adopt the job on failover
        meta = self._repo_meta(job_id)
        meta["config"] = body
        self._save_repo_meta(job_id, meta)
        record_ml_event("jobs_created")
        return {**cfg.to_dict(), "create_time": entry["create_time"]}

    def get_jobs(self, job_id: str | None) -> dict:
        jobs = self._jobs()
        if job_id and job_id not in ("_all", "*"):
            if job_id not in jobs:
                raise ResourceNotFoundError(f"No known job with id '{job_id}'")
            ids = [job_id]
        else:
            ids = sorted(jobs)
        out = []
        for jid in ids:
            cfg = JobConfig(jid, jobs[jid]["config"])
            out.append({**cfg.to_dict(),
                        "create_time": jobs[jid].get("create_time")})
        return {"count": len(out), "jobs": out}

    def delete_job(self, job_id: str, force: bool = False) -> dict:
        if job_id not in self._jobs():
            raise ResourceNotFoundError(f"No known job with id '{job_id}'")
        if job_id in self.runtimes:
            if not force:
                raise IllegalArgumentError(
                    f"Cannot delete job [{job_id}] because the job is opened")
            self.close_job(job_id)
        del self._jobs()[job_id]
        for df_id in [d for d, c in self._datafeeds().items()
                      if c.get("job_id") == job_id]:
            del self._datafeeds()[df_id]
            self._datafeed_state().pop(df_id, None)
        self.engine.meta.save()
        name = results_index_name(job_id)
        if name in self.engine.indices:
            self.engine.delete_index(name)
        repo = self.repo()
        if repo.exists(f"ml/jobs/{job_id}.json"):
            repo.delete(f"ml/jobs/{job_id}.json")
        record_ml_event("jobs_deleted")
        return {"acknowledged": True}

    # ---- open / close / flush -------------------------------------------

    def _adopt_from_repo(self, job_id: str) -> bool:
        """Failover path: a job created on another node exists only in the
        shared state repository; copy its config into this node's
        metadata so it can be opened here."""
        meta = self._repo_meta(job_id)
        if meta.get("config") is None:
            return False
        self._jobs()[job_id] = {"config": meta["config"],
                                "create_time": int(time.time() * 1000),
                                "state": "closed"}
        for df_id, df_body in (meta.get("datafeeds") or {}).items():
            self._datafeeds().setdefault(df_id, df_body)
        self.engine.meta.save()
        return True

    def open_job(self, job_id: str) -> dict:
        self._check_enabled()
        if job_id in self.runtimes:
            return {"opened": True, "node": self.engine.tasks.node}
        if job_id not in self._jobs() and not self._adopt_from_repo(job_id):
            raise ResourceNotFoundError(f"No known job with id '{job_id}'")
        max_open = self.engine.settings.get("xpack.ml.max_open_jobs")
        if len(self.runtimes) >= max_open:
            raise IllegalArgumentError(
                f"node is full: [{len(self.runtimes)}] opened jobs >= "
                f"xpack.ml.max_open_jobs [{max_open}]")
        cfg = self._cfg(job_id)
        rt = JobRuntime(cfg)
        meta = self._repo_meta(job_id)
        snap = self._pick_snapshot(meta)
        if snap is not None:
            payload = self.repo().get_blob(snap["digest"])
            state, smeta = model_mod.deserialize_state(payload)
            rt.state = state
            rt.restore_meta(smeta)
            rt.allocation_id += 1
            record_ml_event("jobs_restored_from_snapshot")
        self._account_memory(job_id, rt)
        self.runtimes[job_id] = rt
        self._jobs()[job_id]["state"] = "opened"
        self.engine.meta.save()
        results_mod.ensure_results_index(self.engine, cfg)
        task_id = f"job-{job_id}"
        try:
            self.engine.persistent.start(
                task_id, PERSISTENT_TASK_NAME,
                {"job_id": job_id, "node": self.engine.tasks.node})
        except ResourceAlreadyExistsError:
            self.engine.persistent.resume(task_id)
        record_ml_event("jobs_opened")
        return {"opened": True, "node": self.engine.tasks.node}

    def _pick_snapshot(self, meta: dict) -> dict | None:
        snaps = meta.get("snapshots") or []
        if not snaps:
            return None
        current = meta.get("current_snapshot")
        if current:
            for s in snaps:
                if s["snapshot_id"] == current:
                    return s
        return snaps[-1]

    def close_job(self, job_id: str, force: bool = False) -> dict:
        rt = self.runtimes.get(job_id)
        if rt is None:
            if job_id in self._jobs():
                return {"closed": True}
            raise ResourceNotFoundError(f"No known job with id '{job_id}'")
        self.checkpoint(job_id, reason="close")
        for df_id, c in self._datafeeds().items():
            if c.get("job_id") == job_id:
                st = self._datafeed_state().setdefault(df_id, {})
                st["state"] = "stopped"
        try:
            self.engine.persistent.remove(f"job-{job_id}")
        except ResourceNotFoundError:
            pass
        self.engine.breakers.set_steady("model_inference", f"ml:{job_id}", 0)
        del self.runtimes[job_id]
        self._jobs()[job_id]["state"] = "closed"
        self.engine.meta.save()
        record_ml_event("jobs_closed")
        return {"closed": True}

    def flush_job(self, job_id: str, body: dict | None = None) -> dict:
        rt = self.runtimes.get(job_id)
        if rt is None:
            raise IllegalArgumentError(
                f"Cannot flush because job [{job_id}] is not open")
        name = results_index_name(job_id)
        if name in self.engine.indices:
            self.engine.indices[name].refresh()
        out = {"flushed": True}
        if rt.processed_end_ms is not None:
            out["last_finalized_bucket_end"] = rt.processed_end_ms
        return out

    def job_stats(self, job_id: str | None) -> dict:
        jobs = self.get_jobs(job_id)["jobs"]
        out = []
        for j in jobs:
            jid = j["job_id"]
            rt = self.runtimes.get(jid)
            if rt is None:
                meta = self._repo_meta(jid)
                snap = self._pick_snapshot(meta)
                counts, mem, status = {}, 0, "ok"
                if snap is not None:
                    counts = snap.get("counts") or {}
                    mem = snap.get("model_bytes", 0)
                state = "closed"
            else:
                counts, mem, status = rt.counts, rt.nbytes(), rt.memory_status
                state = "opened"
            entry = {
                "job_id": jid,
                "state": state,
                "data_counts": {"job_id": jid, **counts},
                "model_size_stats": {
                    "job_id": jid,
                    "model_bytes": mem,
                    "memory_status": status,
                    "total_partition_field_count":
                        len(rt.series) if rt else 0,
                },
            }
            if rt is not None:
                entry["node"] = {"name": self.engine.tasks.node}
                entry["allocation_id"] = rt.allocation_id
            out.append(entry)
        return {"count": len(out), "jobs": out}

    # ---- model snapshots -------------------------------------------------

    def checkpoint(self, job_id: str, reason: str = "periodic") -> dict:
        rt = self.runtimes.get(job_id)
        if rt is None:
            raise IllegalArgumentError(f"job [{job_id}] is not open")
        payload = model_mod.serialize_state(rt.state, rt.snapshot_meta())
        repo = self.repo()
        digest = repo.put_blob(payload)
        meta = self._repo_meta(job_id)
        if meta.get("snapshots") and meta["snapshots"][-1]["digest"] == digest:
            return meta["snapshots"][-1]  # state unchanged: dedup
        meta["snapshot_seq"] = int(meta.get("snapshot_seq", 0)) + 1
        snap = {
            "job_id": job_id,
            "snapshot_id": f"{job_id}-{meta['snapshot_seq']}",
            "timestamp": int(time.time() * 1000),
            "digest": digest,
            "description": reason,
            "snapshot_doc_count": 1,
            "model_bytes": rt.nbytes(),
            "counts": dict(rt.counts),
            "latest_record_time_stamp":
                rt.counts.get("latest_record_timestamp"),
        }
        meta.setdefault("snapshots", []).append(snap)
        meta["snapshots"] = meta["snapshots"][-SNAPSHOT_RETENTION:]
        meta["current_snapshot"] = None  # new head supersedes any revert
        self._save_repo_meta(job_id, meta)
        record_ml_event("model_snapshots_written")
        return snap

    def get_model_snapshots(self, job_id: str) -> dict:
        self._cfg(job_id)  # 404 on unknown job
        snaps = self._repo_meta(job_id).get("snapshots") or []
        shaped = [{k: v for k, v in s.items() if k not in ("digest", "counts")}
                  for s in snaps]
        return {"count": len(shaped), "model_snapshots": shaped}

    def revert_model_snapshot(self, job_id: str, snapshot_id: str) -> dict:
        if job_id in self.runtimes:
            raise IllegalArgumentError(
                f"Cannot revert snapshot: job [{job_id}] is opened")
        meta = self._repo_meta(job_id)
        match = [s for s in meta.get("snapshots", [])
                 if s["snapshot_id"] == snapshot_id]
        if not match:
            raise ResourceNotFoundError(
                f"No model snapshot with id [{snapshot_id}] exists for job "
                f"[{job_id}]")
        meta["current_snapshot"] = snapshot_id
        self._save_repo_meta(job_id, meta)
        return {"model": {k: v for k, v in match[0].items()
                          if k not in ("digest", "counts")}}

    # ---- datafeeds -------------------------------------------------------

    def put_datafeed(self, df_id: str, body: dict) -> dict:
        self._check_enabled()
        if df_id in self._datafeeds():
            raise ResourceAlreadyExistsError(
                f"A datafeed with id [{df_id}] already exists")
        cfg = DatafeedConfig(df_id, body or {})
        if cfg.job_id not in self._jobs():
            raise ResourceNotFoundError(
                f"No known job with id '{cfg.job_id}'")
        if any(c.get("job_id") == cfg.job_id
               for c in self._datafeeds().values()):
            raise IllegalArgumentError(
                f"A datafeed already exists for job [{cfg.job_id}]")
        self._datafeeds()[df_id] = body
        self._datafeed_state()[df_id] = {"state": "stopped"}
        self.engine.meta.save()
        meta = self._repo_meta(cfg.job_id)
        meta.setdefault("datafeeds", {})[df_id] = body
        self._save_repo_meta(cfg.job_id, meta)
        return cfg.to_dict()

    def get_datafeeds(self, df_id: str | None) -> dict:
        feeds = self._datafeeds()
        if df_id and df_id not in ("_all", "*"):
            if df_id not in feeds:
                raise ResourceNotFoundError(
                    f"No datafeed with id [{df_id}] exists")
            ids = [df_id]
        else:
            ids = sorted(feeds)
        return {"count": len(ids), "datafeeds": [
            DatafeedConfig(i, feeds[i]).to_dict() for i in ids]}

    def delete_datafeed(self, df_id: str) -> dict:
        if df_id not in self._datafeeds():
            raise ResourceNotFoundError(f"No datafeed with id [{df_id}] exists")
        if (self._datafeed_state().get(df_id) or {}).get("state") == "started":
            raise IllegalArgumentError(
                f"Cannot delete datafeed [{df_id}] while its status is started")
        del self._datafeeds()[df_id]
        self._datafeed_state().pop(df_id, None)
        self.engine.meta.save()
        return {"acknowledged": True}

    def datafeed_stats(self, df_id: str | None) -> dict:
        got = self.get_datafeeds(df_id)
        out = []
        for d in got["datafeeds"]:
            st = self._datafeed_state().get(d["datafeed_id"]) or {}
            out.append({
                "datafeed_id": d["datafeed_id"],
                "state": st.get("state", "stopped"),
                "timing_stats": {
                    "job_id": d["job_id"],
                    "search_count": st.get("search_count", 0),
                    "total_search_time_ms": st.get("search_ms", 0.0),
                },
            })
        return {"count": len(out), "datafeeds": out}

    @staticmethod
    def _parse_time(v, default: int) -> int:
        if v is None:
            return default
        s = str(v)
        if s.lstrip("-").isdigit():
            return int(s)
        import datetime as _dt

        return int(_dt.datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp() * 1000)

    def start_datafeed(self, df_id: str, start=None, end=None) -> dict:
        self._check_enabled()
        if df_id not in self._datafeeds():
            raise ResourceNotFoundError(f"No datafeed with id [{df_id}] exists")
        df_cfg = DatafeedConfig(df_id, self._datafeeds()[df_id])
        if df_cfg.job_id not in self.runtimes:
            raise IllegalArgumentError(
                f"cannot start datafeed [{df_id}] because job "
                f"[{df_cfg.job_id}] is not open")
        st = self._datafeed_state().setdefault(df_id, {})
        start_ms = self._parse_time(start, 0)
        end_ms = self._parse_time(end, None) if end is not None else None
        rt = self.runtimes[df_cfg.job_id]
        if rt.processed_end_ms is None:
            rt.processed_end_ms = datafeed_mod.bucket_floor(
                start_ms, rt.cfg.bucket_span)
        st["state"] = "started"
        self.engine.meta.save()
        record_ml_event("datafeeds_started")
        if end_ms is not None:
            # lookback with a bound: run to completion now, then stop
            self._advance_datafeed(df_id, end_ms=end_ms)
            st["state"] = "stopped"
            self.engine.meta.save()
            self.checkpoint(df_cfg.job_id, reason="datafeed lookback complete")
        return {"started": True, "node": self.engine.tasks.node}

    def stop_datafeed(self, df_id: str) -> dict:
        if df_id not in self._datafeeds():
            raise ResourceNotFoundError(f"No datafeed with id [{df_id}] exists")
        st = self._datafeed_state().setdefault(df_id, {})
        st["state"] = "stopped"
        self.engine.meta.save()
        return {"stopped": True}

    def preview_datafeed(self, df_id: str) -> list[dict]:
        if df_id not in self._datafeeds():
            raise ResourceNotFoundError(f"No datafeed with id [{df_id}] exists")
        df_cfg = DatafeedConfig(df_id, self._datafeeds()[df_id])
        return datafeed_mod.preview(self.engine, df_cfg, self._cfg(df_cfg.job_id))

    def _advance_datafeed(self, df_id: str, end_ms: int):
        df_cfg = DatafeedConfig(df_id, self._datafeeds()[df_id])
        rt = self.runtimes.get(df_cfg.job_id)
        if rt is None:
            return
        start_ms = rt.processed_end_ms or 0
        if end_ms <= start_ms:
            return
        t0 = time.monotonic()
        n = self._process(df_cfg, rt, start_ms, end_ms)
        st = self._datafeed_state().setdefault(df_id, {})
        st["search_count"] = st.get("search_count", 0) + 1
        st["search_ms"] = st.get("search_ms", 0.0) \
            + (time.monotonic() - t0) * 1000
        if n:
            self.engine.meta.save()

    # ---- the scoring pipeline -------------------------------------------

    def _assign_slots(self, rt: JobRuntime, keys) -> None:
        """Register new (detector, partition) series, growing model state
        under the job's model_memory_limit; over-limit series are dropped
        and the job reports memory_status=hard_limit (reference
        semantics: the model stops growing, existing series keep
        scoring)."""
        fresh = [k for k in sorted(keys, key=lambda k: (k[0], str(k[1])))
                 if k not in rt.series]
        for key in fresh:
            need = len(rt.series) + 1
            grown = model_mod.grow_state(rt.state, need)
            if model_mod.state_nbytes(grown) > rt.cfg.model_memory_limit:
                rt.memory_status = "hard_limit"
                record_ml_event("series_dropped_hard_limit")
                continue
            rt.state = grown
            rt.series[key] = len(rt.series)

    def _account_memory(self, job_id: str, rt: JobRuntime):
        self.engine.breakers.set_steady(
            "model_inference", f"ml:{job_id}", rt.nbytes(),
            label=f"ml job [{job_id}] model state")

    def _process(self, df_cfg: DatafeedConfig, rt: JobRuntime,
                 start_ms: int, end_ms: int) -> int:
        """Pull [start, end) buckets, score them in one device call, write
        record/bucket results. -> buckets processed."""
        cfg = rt.cfg
        t0 = time.monotonic()
        pulled = datafeed_mod.pull(self.engine, df_cfg, cfg, start_ms, end_ms)
        starts = pulled["bucket_starts"]
        B = len(starts)
        if B == 0:
            return 0
        if pulled["truncated_partitions"]:
            record_ml_event("partitions_truncated",
                            pulled["truncated_partitions"])
        self._assign_slots(rt, pulled["series"].keys())
        S = len(rt.series)
        values = np.zeros((B, max(S, 1)), np.float64)
        present = np.zeros((B, max(S, 1)), bool)
        for key, (v, m) in pulled["series"].items():
            slot = rt.series.get(key)
            if slot is None:
                continue  # dropped at the memory hard limit
            values[:, slot] = v
            present[:, slot] = m
        span_ms = cfg.bucket_span * 1000
        phases = ((starts // 1000) // cfg.bucket_span).astype(np.int64)
        rt.state, scored = model_mod.update_and_score(
            rt.state, values[:, :max(S, 1)], present, phases)
        scores = scored["scores"]
        typical = scored["typical"]
        # one-sided detectors only flag their direction
        dets = {d.index: d for d in cfg.detectors}
        for (di, _split), slot in rt.series.items():
            side = dets[di].side
            if side:
                resid = values[:, slot] - typical[:, slot]
                scores[:, slot] = np.where(
                    np.sign(resid) == side, scores[:, slot], 0.0)

        idx = results_mod.ensure_results_index(self.engine, cfg)
        n_records = 0
        for (di, split), slot in rt.series.items():
            det = dets[di]
            for i in np.flatnonzero(
                    present[:, slot]
                    & (scores[:, slot] >= results_mod.RECORD_SCORE_FLOOR)):
                prob = float(10.0 ** (-scores[i, slot] / 10.0))
                doc_id, doc = results_mod.record_doc(
                    cfg, det, int(starts[i]), scores[i, slot],
                    values[i, slot], typical[i, slot], prob, split)
                idx.index_doc(doc_id, doc)
                n_records += 1
        proc_ms = (time.monotonic() - t0) * 1000
        bucket_scores = np.where(present, scores, 0.0).max(axis=1) \
            if S else np.zeros(B)
        for i in range(B):
            doc_id, doc = results_mod.bucket_doc(
                cfg, int(starts[i]), float(bucket_scores[i]),
                int(pulled["event_counts"][i]), proc_ms / B)
            idx.index_doc(doc_id, doc)
        idx.refresh()
        rt.processed_end_ms = int(starts[-1]) + span_ms
        rt.counts["processed_record_count"] += int(
            pulled["event_counts"].sum())
        rt.counts["bucket_count"] += B
        rt.counts["latest_bucket_timestamp"] = int(starts[-1])
        nz = np.flatnonzero(pulled["event_counts"])
        if len(nz):
            rt.counts["latest_record_timestamp"] = int(starts[nz[-1]])
        self._account_memory(cfg.job_id, rt)
        record_ml_event("buckets_processed", B)
        record_ml_event("records_written", n_records)
        from ..telemetry import metrics

        metrics.histogram_record("ml.bucket_processing_time_ms", proc_ms / B)
        return B

    # ---- observability / shutdown ---------------------------------------

    def node_stats(self) -> dict:
        return {
            "anomaly_detectors": {
                "count": len(self._jobs()),
                "opened": len(self.runtimes),
            },
            "datafeeds": {
                "count": len(self._datafeeds()),
                "started": sum(
                    1 for s in self._datafeed_state().values()
                    if s.get("state") == "started"),
            },
            "model_memory_bytes": sum(
                rt.nbytes() for rt in self.runtimes.values()),
        }

    def info(self) -> dict:
        from .. import __version__

        return {
            "defaults": {"anomaly_detectors": {
                "model_memory_limit": "16mb",
                "categorization_analyzer": None,
            }},
            "limits": {"max_open_jobs":
                       self.engine.settings.get("xpack.ml.max_open_jobs")},
            "native_code": {"version": f"jax-native {__version__}"},
            "upgrade_mode": False,
        }

    def shutdown(self):
        """Engine close: checkpoint every open job so nothing learned is
        lost on an orderly node stop."""
        for job_id in list(self.runtimes):
            try:
                self.close_job(job_id)
            except Exception:  # noqa: BLE001 - best effort on shutdown
                pass
