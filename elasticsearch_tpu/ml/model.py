"""Native JAX anomaly-detection model: online seasonal-trend decomposition
plus streaming robust scale/quantile estimation, scored vectorized across
every (detector, partition) series in one device call per batch.

The reference delegates this to the spawned C++ autodetect process
(x-pack/plugin/ml/.../process/AutodetectProcess — one sidecar per job,
records streamed over named pipes). This framework owns the accelerator,
so the model runs where the data already lives: a `lax.scan` over the
batch of buckets, each step updating all S series with pure VPU math
(BM25S-style eager vectorization — batch everything, no per-series loop).

Per series the state is an additive Holt-Winters decomposition in
error-correction form (level + damped trend + seasonal component of fixed
candidate period P) with two robust residual-scale estimators learned
online: an outlier-clipped exponentially-weighted variance and a
Robbins-Monro streaming estimate of the median absolute residual (the
MAD). The anomaly score maps the two-sided normal tail probability of the
standardized residual to the reference's 0-100 range via
score = -10*log10(p), the same shape the reference's
anomaly-score normalizer produces for its probability buckets.

All arrays are padded to a power-of-two series capacity so XLA sees a
stable shape while partitions are discovered mid-stream; a series mask
keeps dead slots inert. State lives host-side between batches (it must
serialize into model snapshots); one jitted call per datafeed batch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# learning rates (error-correction Holt-Winters)
ALPHA = 0.30      # level
BETA = 0.10       # trend (applied to alpha*resid)
GAMMA = 0.25      # seasonal
PHI = 0.98        # trend damping
RHO = 0.10        # EW variance
Q_ETA = 0.10      # Robbins-Monro step for the MAD quantile
WARMUP = 8        # buckets before a series may score
CLIP_Z = 4.0      # residual clip (in sigmas) for the robust var update
MIN_CAP = 8

STATE_KEYS = ("n", "level", "trend", "season", "var", "qmad")


def init_state(cap: int, period: int) -> dict:
    """Fresh model state with `cap` series slots and seasonal period
    `period` buckets (period <= 1 disables the seasonal component)."""
    cap = max(MIN_CAP, 1 << (int(cap) - 1).bit_length())
    p = max(1, int(period))
    return {
        "n": np.zeros(cap, np.int32),
        "level": np.zeros(cap, np.float32),
        "trend": np.zeros(cap, np.float32),
        "season": np.zeros((cap, p), np.float32),
        "var": np.zeros(cap, np.float32),
        "qmad": np.zeros(cap, np.float32),
    }


def state_cap(state: dict) -> int:
    return int(state["level"].shape[0])


def state_period(state: dict) -> int:
    return int(state["season"].shape[1])


def state_nbytes(state: dict) -> int:
    return int(sum(np.asarray(v).nbytes for v in state.values()))


def grow_state(state: dict, need: int) -> dict:
    """Return state with capacity >= need (power of two); new slots fresh."""
    cap = state_cap(state)
    if need <= cap:
        return state
    new_cap = 1 << (int(need) - 1).bit_length()
    out = {}
    for k in STATE_KEYS:
        a = np.asarray(state[k])
        pad = [(0, new_cap - cap)] + [(0, 0)] * (a.ndim - 1)
        out[k] = np.pad(a, pad)
    return out


def _scale_of(level, var, qmad):
    """Robust residual scale: EW sigma vs 1.4826*MAD, floored relative to
    the series level so a near-constant series cannot divide by ~zero."""
    floor = 0.05 * (jnp.abs(level) + 1e-3)
    return jnp.maximum(jnp.maximum(jnp.sqrt(var), 1.4826 * qmad), floor)


def _step(carry, xs):
    """One bucket for all series. xs: (x [S], present [S], phase [])."""
    n, level, trend, season, var, qmad = carry
    x, present, phase = xs
    p = season.shape[1]
    seas = season[:, phase] if p > 1 else jnp.zeros_like(level)
    pred = level + trend + seas
    # a fresh series (n == 0) anchors the level at its first observation
    pred = jnp.where(n == 0, x, pred)
    resid = x - pred
    scale = _scale_of(level, var, qmad)
    warm = n >= WARMUP
    z = jnp.where(warm & present, resid / scale, 0.0)
    # two-sided normal tail -> 0..100 (one-sidedness applied by the caller)
    prob = jax.scipy.special.erfc(jnp.abs(z) * (1.0 / np.sqrt(2.0)))
    score = jnp.clip(-10.0 * jnp.log10(jnp.maximum(prob, 1e-300)), 0.0, 100.0)

    # --- updates (only where the bucket has a value) ---
    nf = n.astype(jnp.float32)
    eff_alpha = jnp.maximum(ALPHA, 1.0 / (nf + 1.0))
    r_clip = jnp.where(warm, jnp.clip(resid, -CLIP_Z * scale, CLIP_Z * scale),
                       resid)
    level2 = level + trend + eff_alpha * resid
    trend2 = PHI * (trend + BETA * eff_alpha * resid)
    var2 = jnp.where(n == 0, 0.0,
                     var + jnp.maximum(RHO, 1.0 / (nf + 1.0))
                     * (r_clip * r_clip - var))
    eta = Q_ETA * jnp.maximum(qmad, 0.1 * jnp.abs(r_clip) + 1e-9)
    qmad2 = jnp.maximum(qmad + eta * jnp.sign(jnp.abs(r_clip) - qmad), 0.0)
    if p > 1:
        snew = season[:, phase] + GAMMA * (1.0 - eff_alpha) * r_clip
        season2 = season.at[:, phase].set(jnp.where(present, snew,
                                                    season[:, phase]))
    else:
        season2 = season
    upd = lambda new, old: jnp.where(present, new, old)
    carry2 = (
        jnp.where(present, n + 1, n),
        upd(level2, level), upd(trend2, trend), season2,
        upd(var2, var), upd(qmad2, qmad),
    )
    return carry2, (score, pred, scale)


@partial(jax.jit, static_argnums=())
def _run_batch(n, level, trend, season, var, qmad, values, present, phases):
    carry = (n, level, trend, season, var, qmad)
    carry, (scores, preds, scales) = jax.lax.scan(
        _step, carry, (values, present, phases))
    return carry, scores, preds, scales


def update_and_score(state: dict, values: np.ndarray, present: np.ndarray,
                     phases: np.ndarray) -> tuple[dict, dict]:
    """Consume `values [B, S]` (S <= capacity; padded on device) with
    `present [B, S]` masks and per-bucket seasonal `phases [B]`.

    -> (new_state, {"scores": [B, S], "typical": [B, S], "scales": [B, S]})
    — one jitted device call for the whole batch."""
    cap = state_cap(state)
    B, S = values.shape
    if S > cap:
        raise ValueError(f"batch has {S} series but capacity is {cap}")
    v = np.zeros((B, cap), np.float32)
    v[:, :S] = values
    m = np.zeros((B, cap), bool)
    m[:, :S] = present
    carry, scores, preds, scales = _run_batch(
        jnp.asarray(state["n"]), jnp.asarray(state["level"]),
        jnp.asarray(state["trend"]), jnp.asarray(state["season"]),
        jnp.asarray(state["var"]), jnp.asarray(state["qmad"]),
        jnp.asarray(v), jnp.asarray(m),
        jnp.asarray(phases.astype(np.int32) % state_period(state)),
    )
    new_state = {k: np.array(a) for k, a in zip(STATE_KEYS, carry)}
    return new_state, {  # np.array: writable host copies (device buffers
        # surface as read-only views through np.asarray)
        "scores": np.array(scores[:, :S]),
        "typical": np.array(preds[:, :S]),
        "scales": np.array(scales[:, :S]),
    }


# ---- snapshot serialization ------------------------------------------------

_MAGIC = b"ESTPUML1"


def serialize_state(state: dict, meta: dict) -> bytes:
    """-> one deterministic payload: magic, JSON manifest (array dtypes/
    shapes + opaque meta), then the raw array bytes. Byte-identical state
    serializes byte-identically, so the content-addressed blob store
    dedups unchanged model snapshots for free."""
    import json

    manifest = {"meta": meta, "arrays": []}
    blobs = []
    for k in STATE_KEYS:
        a = np.ascontiguousarray(state[k])
        manifest["arrays"].append(
            {"key": k, "dtype": str(a.dtype), "shape": list(a.shape)})
        blobs.append(a.tobytes())
    head = json.dumps(manifest, sort_keys=True,
                      separators=(",", ":")).encode()
    return b"".join([_MAGIC, len(head).to_bytes(8, "big"), head] + blobs)


def deserialize_state(payload: bytes) -> tuple[dict, dict]:
    """-> (state, meta); inverse of serialize_state."""
    import json

    if payload[:8] != _MAGIC:
        raise ValueError("not an ML model-state payload")
    hlen = int.from_bytes(payload[8:16], "big")
    manifest = json.loads(payload[16:16 + hlen])
    off = 16 + hlen
    state = {}
    for spec in manifest["arrays"]:
        dt = np.dtype(spec["dtype"])
        count = int(np.prod(spec["shape"])) if spec["shape"] else 1
        nbytes = dt.itemsize * count
        state[spec["key"]] = np.frombuffer(
            payload[off:off + nbytes], dt).reshape(spec["shape"]).copy()
        off += nbytes
    return state, manifest["meta"]
