"""ML result documents: reference-shaped records / buckets written to a
hidden per-job results index, queryable through the normal search surface.

Parity target: x-pack/plugin/core/.../ml/job/results/{AnomalyRecord,
Bucket}.java field-for-field (record_score, initial_record_score, typical/
actual arrays, detector_index, partition fields; bucket anomaly_score,
event_count) — the results APIs in the reference are themselves just
searches over .ml-anomalies-*.
"""

from __future__ import annotations

from ..utils.errors import ResourceNotFoundError
from .config import JobConfig, results_index_name

# records below this score are not persisted (the reference's unusual-
# bucket probability cutoff ~3.5% maps to roughly this -10log10(p))
RECORD_SCORE_FLOOR = 15.0

RESULTS_MAPPINGS = {
    "properties": {
        "job_id": {"type": "keyword"},
        "result_type": {"type": "keyword"},
        "timestamp": {"type": "date"},
        "bucket_span": {"type": "long"},
        "is_interim": {"type": "boolean"},
        "record_score": {"type": "double"},
        "initial_record_score": {"type": "double"},
        "probability": {"type": "double"},
        "detector_index": {"type": "long"},
        "function": {"type": "keyword"},
        "field_name": {"type": "keyword"},
        "partition_field_name": {"type": "keyword"},
        "partition_field_value": {"type": "keyword"},
        "actual": {"type": "double"},
        "typical": {"type": "double"},
        "anomaly_score": {"type": "double"},
        "initial_anomaly_score": {"type": "double"},
        "event_count": {"type": "long"},
        "processing_time_ms": {"type": "double"},
    }
}


def ensure_results_index(engine, job: JobConfig):
    name = results_index_name(job.job_id)
    if name not in engine.indices:
        engine.create_index(name, mappings=RESULTS_MAPPINGS,
                            settings={"hidden": True})
    return engine.indices[name]


def record_doc(job: JobConfig, det, ts_ms: int, score: float,
               actual: float, typical: float, probability: float,
               partition_value: str | None) -> tuple[str, dict]:
    doc = {
        "job_id": job.job_id,
        "result_type": "record",
        "timestamp": int(ts_ms),
        "bucket_span": job.bucket_span,
        "is_interim": False,
        "record_score": round(float(score), 4),
        "initial_record_score": round(float(score), 4),
        "probability": float(probability),
        "detector_index": det.index,
        "function": det.function,
        "actual": [float(actual)],
        "typical": [float(typical)],
    }
    if det.field_name:
        doc["field_name"] = det.field_name
    if det.split_field:
        doc["partition_field_name"] = det.split_field
        doc["partition_field_value"] = partition_value
    doc_id = f"{job.job_id}_record_{ts_ms}_{det.index}_{partition_value or ''}"
    return doc_id, doc


def bucket_doc(job: JobConfig, ts_ms: int, anomaly_score: float,
               event_count: int, processing_time_ms: float) -> tuple[str, dict]:
    doc = {
        "job_id": job.job_id,
        "result_type": "bucket",
        "timestamp": int(ts_ms),
        "bucket_span": job.bucket_span,
        "is_interim": False,
        "anomaly_score": round(float(anomaly_score), 4),
        "initial_anomaly_score": round(float(anomaly_score), 4),
        "event_count": int(event_count),
        "processing_time_ms": float(processing_time_ms),
    }
    return f"{job.job_id}_bucket_{ts_ms}", doc


def _time_range_filter(body: dict, extra_filters: list):
    rng = {}
    if body.get("start") is not None:
        rng["gte"] = body["start"]
    if body.get("end") is not None:
        rng["lt"] = body["end"]
    if rng:
        rng["format"] = "epoch_millis||strict_date_optional_time"
        extra_filters.append({"range": {"timestamp": rng}})


def _query_results(engine, job_id: str, result_type: str, body: dict,
                   score_field: str, threshold_key: str, default_sort: str):
    name = results_index_name(job_id)
    if name not in engine.indices:
        return 0, []
    filters: list = [{"term": {"result_type": result_type}}]
    _time_range_filter(body or {}, filters)
    threshold = (body or {}).get(threshold_key)
    if threshold is not None:
        filters.append({"range": {score_field: {"gte": float(threshold)}}})
    page = (body or {}).get("page") or {}
    size = int(page.get("size", 100))
    from_ = int(page.get("from", 0))
    sort_field = (body or {}).get("sort", default_sort)
    desc_raw = (body or {}).get("desc", False)  # may be a query-param string
    desc = desc_raw if isinstance(desc_raw, bool) \
        else str(desc_raw).lower() in ("", "true", "1")
    engine.indices[name]._maybe_refresh()
    res = engine.search_multi(
        name, query={"bool": {"filter": filters}},
        size=size, from_=from_,
        sort=[{sort_field: {"order": "desc" if desc else "asc"}},
              {"timestamp": {"order": "asc"}}],
        track_total_hits=True,
    )
    total = res["hits"]["total"]["value"]
    return total, [h["_source"] for h in res["hits"]["hits"]]


def get_records(engine, job_id: str, body: dict | None) -> dict:
    total, docs = _query_results(
        engine, job_id, "record", body or {}, "record_score",
        "record_score", "timestamp")
    return {"count": total, "records": docs}


def get_buckets(engine, job_id: str, body: dict | None,
                timestamp: str | None = None) -> dict:
    body = dict(body or {})
    if timestamp is not None:
        body["start"] = timestamp
        body["end"] = int(timestamp) + 1 if str(timestamp).isdigit() else timestamp
    total, docs = _query_results(
        engine, job_id, "bucket", body, "anomaly_score",
        "anomaly_score", "timestamp")
    if timestamp is not None and not docs:
        raise ResourceNotFoundError(
            f"No known bucket with timestamp [{timestamp}]")
    return {"count": total, "buckets": docs}


def get_overall_buckets(engine, job_ids: list[str], body: dict | None) -> dict:
    """Max bucket anomaly_score per timestamp across jobs (the reference's
    overall-bucket reduce with top_n=1)."""
    per_ts: dict[int, dict] = {}
    span = 0
    for job_id in job_ids:
        _, buckets = _query_results(
            engine, job_id, "bucket", body or {}, "anomaly_score",
            "overall_score", "timestamp")
        for b in buckets:
            span = max(span, b["bucket_span"])
            entry = per_ts.setdefault(b["timestamp"], {
                "timestamp": b["timestamp"], "bucket_span": b["bucket_span"],
                "overall_score": 0.0, "is_interim": False, "jobs": []})
            entry["jobs"].append({"job_id": b["job_id"],
                                  "max_anomaly_score": b["anomaly_score"]})
            entry["overall_score"] = max(entry["overall_score"],
                                         b["anomaly_score"])
            entry["is_interim"] = entry["is_interim"] or b["is_interim"]
    out = [per_ts[k] for k in sorted(per_ts)]
    threshold = (body or {}).get("overall_score")
    if threshold is not None:
        out = [b for b in out if b["overall_score"] >= float(threshold)]
    return {"count": len(out), "overall_buckets": out}
