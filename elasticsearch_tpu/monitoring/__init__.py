"""Self-monitoring: device-utilization accounting + the monitoring
pipeline (PR 5).

Two coupled layers:

  - `costmodel` + `device`: an analytic per-kernel FLOPs/bytes model
    combined with the PR-4 wall timings (telemetry.time_kernel) reports
    achieved MFU and bandwidth utilization per kernel per call, plus JIT
    compile-time / executable-cache counters, HBM live/peak gauges, and
    padded-lane waste — surfaced in `profile.device`, `_nodes/stats`,
    and the Prometheus exposition.
  - `collectors` + `service`: a MonitoringService (the reference's
    x-pack monitoring plugin analog) runs interval collectors and writes
    reference-shaped documents into hidden `.monitoring-es-*` TSDB
    indices on the node's own engine, with retention pruning and the
    `xpack.monitoring.collection.{enabled,interval}` dynamic settings —
    the engine dogfoods its own time-series storage, and a prebuilt ML
    job can watch the engine's own latency for regressions.
"""

from .costmodel import KERNEL_COSTS, device_peaks, kernel_cost, utilization
from .device import (
    device_memory_snapshot,
    device_stats,
    install_compile_listener,
    jit_stats,
    kernel_utilization,
    note_executable_cache,
    pack_padded_waste,
    padded_waste_bytes,
)
from .profiler import ProfilerService
from .refresh_profile import (
    RefreshRecorder,
    build_stage,
    collect_build_stages,
    default_recorder,
    refresh_stage,
)
from .service import (
    MONITORING_PREFIX,
    SELF_WATCH_JOB_ID,
    MonitoringService,
    monitoring_index_name,
    setup_self_watch_job,
)
from .xla_introspect import (
    XLA_CHECKS,
    check_dispatch,
    drift_table,
    format_drift_table,
    xla_check_status,
)

# meter XLA compiles from the first time any monitoring-aware code path
# loads (idempotent; jax.monitoring listener)
install_compile_listener()

__all__ = [
    "KERNEL_COSTS", "device_peaks", "kernel_cost", "utilization",
    "device_memory_snapshot", "device_stats", "install_compile_listener",
    "jit_stats", "kernel_utilization", "note_executable_cache",
    "pack_padded_waste", "padded_waste_bytes",
    "MONITORING_PREFIX", "SELF_WATCH_JOB_ID", "MonitoringService",
    "monitoring_index_name", "setup_self_watch_job",
    "ProfilerService", "XLA_CHECKS", "check_dispatch", "drift_table",
    "format_drift_table", "xla_check_status",
    "RefreshRecorder", "build_stage", "collect_build_stages",
    "default_recorder", "refresh_stage",
]
