"""Interval collectors: reference-shaped monitoring documents.

Parity target: x-pack/plugin/monitoring/.../collector/ — each collector
samples one facet of the node (NodeStatsCollector, IndexStatsCollector,
ClusterStatsCollector) into a typed document carrying `type`,
`cluster_uuid`, a source-node stamp, and a `timestamp`, exported to
`.monitoring-es-*` indices. Here the documents are TSDB points: `node`
and `type` (and `index` for index_stats) are time_series_dimension
fields, so (_tsid, @timestamp) de-duplicates re-collections and one
series' points pack adjacently in the columnar device arrays."""

from __future__ import annotations

import time

from ..telemetry import metrics
from .device import device_stats


def _iso_utc(ts: float | None = None) -> str:
    t = time.time() if ts is None else ts
    ms = int(t * 1000) % 1000
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + f".{ms:03d}Z"


# mappings/settings of one .monitoring-es-* index. Dimensions: node +
# type (+ index for index_stats docs). routing_path routes by node, so a
# node's whole history lives on one shard of the monitoring index.
def monitoring_index_body() -> dict:
    return {
        "settings": {
            "index": {
                "mode": "time_series",
                "routing_path": ["node"],
                "hidden": True,
                "number_of_shards": 1,
                "refresh_interval": "1s",
            }
        },
        "mappings": {
            "properties": {
                "@timestamp": {"type": "date"},
                "node": {"type": "keyword", "time_series_dimension": True},
                "type": {"type": "keyword", "time_series_dimension": True},
                "index": {"type": "keyword", "time_series_dimension": True},
                "cluster_uuid": {"type": "keyword"},
            }
        },
    }


def collect_node_stats(engine, node_name: str, now: float | None = None) -> dict:
    """One `type: node_stats` document: indices totals, search/indexing
    counters, request cache, breakers, and the device-utilization
    snapshot (HBM, per-kernel MFU/bandwidth, JIT compile activity) —
    the collector the ML self-watch job feeds on
    (node_stats.indices.search.query_time_in_millis)."""
    from ..cache import request_cache

    now = time.time() if now is None else now
    docs_total = 0
    deleted = 0
    query_total = 0
    query_time_ms = 0
    index_total = 0
    store_bytes = 0
    for idx in engine.indices.values():
        docs_total += idx.live_count
        deleted += sum(1 for e in idx.docs.values() if not e.alive)
        query_total += idx.counters.get("query_total", 0)
        query_time_ms += idx.counters.get("query_time_ms", 0)
        index_total += idx.counters.get("index_total", 0)
        store_bytes += getattr(idx, "_base_nbytes", 0)
    rc = request_cache().stats()
    breakers = {}
    for name, b in engine.breakers.stats().items():
        if isinstance(b, dict):
            breakers[name] = {
                "estimated_size_in_bytes": b.get("estimated_size_in_bytes", 0),
                "limit_size_in_bytes": b.get("limit_size_in_bytes", 0),
                "tripped": b.get("tripped", 0),
            }
    dev = device_stats(engine)
    # flatten the per-kernel table into bounded numeric leaves: dynamic
    # mappings grow one field per kernel metric, not per histogram bucket
    kernels = {}
    for kname, u in dev["utilization"]["kernels"].items():
        kernels[kname.replace(".", "_")] = {
            "calls": u["calls"], "wall_ms": u["wall_ms"],
            "mfu": u["mfu"], "bw_util": u["bw_util"],
            "flops": u["flops"], "bytes": u["bytes"],
        }
    # PR 12: the registry-wide analytic-vs-XLA drift table rides the
    # TSDB doc (bounded numeric leaves), so cost-model trust is
    # queryable history alongside the utilization it underwrites
    drift = {}
    for kname, row in (dev["utilization"].get("costmodel_drift")
                       or {}).items():
        if "flops_ratio" in row:
            drift[kname.replace(".", "_")] = {
                "flops_ratio": row["flops_ratio"],
                "bytes_ratio": row.get("bytes_ratio", 0.0),
            }
    # write-path ground truth (PR 13): refresh/merge counts, cumulative
    # build-stage millis (bounded: one numeric leaf per stage name),
    # tail-tier fraction + refresh lag + docs/s EMA — queryable history
    # for usage_report's write-path table and the tail_fraction trend
    indexing_doc = {}
    try:
        ist = engine.indexing_stats()
        indexing_doc = {
            "refresh_total": ist.get("refresh_total", 0),
            "merge_total": ist.get("merge_total", 0),
            "refresh_full": ist.get("refresh_kinds", {}).get("full", 0),
            "refresh_incremental": ist.get("refresh_kinds", {}).get(
                "incremental", 0),
            "docs_refreshed_total": ist.get("docs_refreshed_total", 0),
            "docs_per_s_ema": ist.get("docs_per_s_ema") or 0.0,
            "tail_fraction": ist.get("tail_fraction", 0.0),
            "tail_docs": ist.get("tail_docs", 0),
            "refresh_lag_ms": ist.get("refresh_lag_ms", 0.0),
            "stage_ms": {k.replace(".", "_"): v
                         for k, v in (ist.get("stage_ms") or {}).items()},
        }
    except Exception:  # noqa: BLE001 - collection must never stop
        pass
    snap = metrics.snapshot()
    rest_h = snap["histograms"].get("es.rest.request.ms") or {}
    shard_h = snap["histograms"].get("es.shard.search.ms") or {}
    # serving front end (serving/): queue/wave/shed accounting so the
    # monitoring history shows saturation as occupancy (and MFU) rising
    # with offered load. Zeros when the node never built the service.
    sv = getattr(engine, "_serving", None)
    sv_st = sv.stats() if sv is not None else {}
    sv_wave = sv_st.get("wave", {})
    occ_h = snap["histograms"].get("es.serving.wave_occupancy") or {}
    # closed loop (PR 9): the SLO engine evaluates on THIS collector
    # interval, and the node's own health status lands in its TSDB — so
    # health/compliance history is queryable like any other series.
    # Bounded leaves only (status codes, counts, a joined id string);
    # failures degrade to empty sections — collection must never stop.
    slo_doc = {}
    health_doc = {}
    # adaptive execution planner (PR 18): decision/mode counts, knob
    # adjustments, and the worst-predicted kernel's |residual| EMA land
    # in the TSDB so cost-model drift is queryable history. Bounded
    # leaves; failures degrade to an empty section.
    planner_doc = {}
    try:
        from ..planner import execution_planner

        pst = execution_planner().stats()
        planner_doc = {
            "enabled": 1 if pst.get("enabled") else 0,
            "decisions": dict(pst.get("decisions") or {}),
            "decision_modes": dict(pst.get("decision_modes") or {}),
            "knobs": dict(pst.get("knobs") or {}),
            "repriced": ",".join(pst.get("repriced") or ()),
            "worst_kernel": pst.get("worst_kernel") or "",
            "worst_abs_residual_ema":
                pst.get("worst_abs_residual_ema") or 0.0,
        }
    except Exception:  # noqa: BLE001
        pass
    # per-tenant ledger (PR 19): the exact apportioned device-ms shares
    # land in the TSDB as history — bounded by the meter's top-K fold
    # (tenant keys are already charset-sanitized by normalize_tenant,
    # so they are safe field keys). Flat numeric leaves per tenant.
    tenants_doc = {}
    try:
        meter = engine._metering
        if meter is not None:
            tenants_doc = {
                t: {
                    "requests": r["requests"],
                    "waves": r["waves"],
                    "device_ms": r["device_ms"],
                    "device_ms_per_s": r["device_ms_per_s"],
                    "queue_wait_ms": r["queue_wait_ms"],
                    "queue_p99_ms": r["queue_p99_ms"],
                    "sheds": r["sheds"],
                    "shed_rate": r["shed_rate"],
                    "cache_hits": r["cache"]["hits"],
                    "cache_misses": r["cache"]["misses"],
                    "ingest_bytes": r["ingest_bytes"],
                } for t, r in meter.rows().items()
            }
    except Exception:  # noqa: BLE001
        pass
    # ESQL dataflow ground truth (PR 20): the per-operator recorder's
    # cumulative walls, materialization high-water marks, and breaker
    # trips land in the TSDB so operator-level ESQL history (the item-5
    # paged-operator substrate) is queryable from any node. Operator
    # keys are the fixed pipe-stage vocabulary plus "driver" — bounded;
    # dots sanitized like stage_ms above.
    esql_doc = {}
    try:
        from ..esql.profile import recorder_for

        est = recorder_for(engine).stats()
        esql_h = snap["histograms"].get("es.esql.query_ms") or {}
        esql_doc = {
            "queries": est.get("queries", 0),
            "rows_total": est.get("rows_total", 0),
            "peak_bytes_hwm": est.get("peak_bytes_hwm", 0),
            "peak_bytes_last": est.get("peak_bytes_last", 0),
            "breaker_trips": est.get("breaker_trips", 0),
            "dominant_operator": est.get("dominant_operator") or "",
            "query_ms_p50": esql_h.get("p50", 0.0),
            "query_ms_p99": esql_h.get("p99", 0.0),
            "operator_ms": {k.replace(".", "_"): v
                            for k, v in
                            (est.get("operator_ms") or {}).items()},
        }
    except Exception:  # noqa: BLE001
        pass
    try:
        ev = engine.slo.evaluate()
        slo_doc = {
            "compliant": 1 if ev["compliant"] else 0,
            "breached_count": ev["breached_count"],
            "objective_count": ev["objective_count"],
            "breached": ",".join(ev["breached"]),
        }
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..xpack.health import STATUS_CODES, health_report

        hr = health_report(engine)
        health_doc = {
            "status": hr["status"],
            "status_code": STATUS_CODES.get(hr["status"], 1),
            "indicators": {
                name: STATUS_CODES.get(ind["status"], 1)
                for name, ind in hr["indicators"].items()
            },
        }
    except Exception:  # noqa: BLE001
        pass
    return {
        "type": "node_stats",
        "cluster_uuid": "elasticsearch-tpu",
        "@timestamp": _iso_utc(now),
        "node": node_name,
        "node_stats": {
            "indices": {
                "docs": {"count": docs_total, "deleted": deleted},
                "store": {"size_in_bytes": store_bytes},
                "search": {
                    "query_total": query_total,
                    "query_time_in_millis": query_time_ms,
                    "shard_query_ms_p50": shard_h.get("p50", 0.0),
                    "shard_query_ms_p99": shard_h.get("p99", 0.0),
                },
                "indexing": {"index_total": index_total},
                "request_cache": {
                    "memory_size_in_bytes": rc.get("memory_size_in_bytes", 0),
                    "hit_count": rc.get("hit_count", 0),
                    "miss_count": rc.get("miss_count", 0),
                    "evictions": rc.get("evictions", 0),
                },
            },
            "rest": {
                "request_ms_p50": rest_h.get("p50", 0.0),
                "request_ms_p99": rest_h.get("p99", 0.0),
                "request_total": rest_h.get("count", 0),
            },
            "breakers": breakers,
            "device": {
                "kind": dev["utilization"]["device_kind"],
                "hbm_live_bytes": dev["memory"].get("live_bytes", 0),
                "hbm_live_arrays": dev["memory"].get("live_arrays", 0),
                "hbm_bytes_in_use": dev["memory"].get("bytes_in_use", 0),
                "hbm_peak_bytes": dev["memory"].get("peak_bytes_in_use", 0),
                "pack_padded_waste_bytes":
                    dev["memory"].get("pack_padded_waste_bytes", 0),
                "kernels": kernels,
                "costmodel_drift": drift,
            },
            "jit": {
                "compiles": dev["jit"]["compiles"],
                "compile_time_in_millis": dev["jit"]["compile_time_in_millis"],
                "cache_hits": dev["jit"]["executable_cache"]["hits"],
                "cache_misses": dev["jit"]["executable_cache"]["misses"],
            },
            "health": health_doc,
            "slo": slo_doc,
            "indexing": indexing_doc,
            "serving": {
                "queue_depth": sv_st.get("queue", {}).get("depth", 0),
                "admitted": sv_st.get("admitted", 0),
                "completed": sv_st.get("completed", 0),
                "shed": sv_st.get("shed", 0),
                "expired": sv_st.get("expired", 0),
                "cancelled": sv_st.get("cancelled", 0),
                "waves": sv_st.get("waves", 0),
                "avg_wave_size": sv_wave.get("avg_size", 0.0) or 0.0,
                "term_occupancy_p50": occ_h.get("p50", 0.0),
                "host_transitions_dispatch": sv_st.get(
                    "host_transitions_total", {}).get("dispatch", 0),
                "host_transitions_fetch": sv_st.get(
                    "host_transitions_total", {}).get("fetch", 0),
            },
            "planner": planner_doc,
            "tenants": tenants_doc,
            "esql": esql_doc,
        },
    }


def collect_index_stats(engine, node_name: str,
                        now: float | None = None) -> list[dict]:
    """`type: index_stats` documents, one per non-hidden user index.
    Dot-prefixed and hidden indices are skipped — the monitoring indices
    must never monitor themselves into unbounded growth (the reference's
    collectors likewise skip the .monitoring-* system indices)."""
    now = time.time() if now is None else now
    out = []
    for name in sorted(engine.indices):
        if name.startswith("."):
            continue
        idx = engine.indices[name]
        if idx.settings.get("hidden"):
            continue
        out.append({
            "type": "index_stats",
            "cluster_uuid": "elasticsearch-tpu",
            "@timestamp": _iso_utc(now),
            "node": node_name,
            "index": name,
            "index_stats": {
                "docs_count": idx.live_count,
                "docs_deleted": sum(
                    1 for e in idx.docs.values() if not e.alive),
                "shards": idx.num_shards,
                "store_size_in_bytes": getattr(idx, "_base_nbytes", 0),
                "search_query_total": idx.counters.get("query_total", 0),
                "search_query_time_in_millis":
                    idx.counters.get("query_time_ms", 0),
                "indexing_index_total": idx.counters.get("index_total", 0),
                "refresh_total": idx.counters.get("refresh_total", 0),
            },
        })
    return out


def collect_all(engine, node_name: str) -> list[dict]:
    """Everything one collection tick exports."""
    now = time.time()
    return [collect_node_stats(engine, node_name, now),
            *collect_index_stats(engine, node_name, now)]
