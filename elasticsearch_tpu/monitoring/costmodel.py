"""Analytic per-kernel cost model: FLOPs + bytes moved per dispatch.

The reference never needs this — its hot loop is a CPU doc-at-a-time
iterator and its monitoring collectors read JVM stats
(monitor/jvm/JvmStats.java). A device engine is judged differently: a
kernel is "fast" only as a fraction of the chip's peak (VERDICT r5: C4
kNN at ~2% of roofline; BM25S https://arxiv.org/pdf/2407.03618 and
GPUSparse https://arxiv.org/pdf/2606.26441 both report achieved-vs-peak,
not QPS alone). This module derives FLOPs and HBM traffic from the
shapes/dtypes already in hand at each dispatch site; telemetry.time_kernel
divides them by the measured wall time and the device's peak rates to
report achieved MFU and bandwidth utilization per kernel per call.

Conventions (documented, asserted by tests/test_monitoring.py):
  - a matmul [M,K]@[K,N] is 2*M*K*N FLOPs per pass (multiply+add);
  - selection/compare work counts 2 ops per scanned element (compare +
    select) — top-k is bandwidth-bound, the ops term keeps its MFU
    honest instead of zero;
  - bytes = operand reads + result writes at their storage dtypes, each
    operand counted ONCE (tiled re-reads from VMEM are free by design —
    that is what the kernels are shaped to guarantee);
  - MFU is reported against the device's peak *bf16* matmul rate (the
    chip's headline number) regardless of compute dtype, so an f32 path
    can never look better than the bf16 path it competes with.

Every `time_kernel` dispatch name in ops/, parallel/, query/ and ann/
MUST have an entry in KERNEL_COSTS (tier-1 lint: test_monitoring.py
walks the call sites). An entry of None marks a wrapper span whose inner kernels carry
the accounting — a deliberate choice, not a missing model.
"""

from __future__ import annotations

import math
import os

# ---------------------------------------------------------------------------
# device peak rates
# ---------------------------------------------------------------------------

# device_kind substring -> (peak bf16 matmul FLOP/s, peak HBM bytes/s).
# Public spec-sheet numbers; first match wins (checked in order).
DEVICE_PEAKS: list[tuple[str, float, float]] = [
    ("v6e", 918e12, 1640e9),   # Trillium
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),    # the bench target (BENCH_NOTES.md)
    ("v5", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
]

# CPU fallback: a nominal 32-vCPU host (AVX2 f32 FMA ~100 GFLOP/s/core
# is generous; utilization numbers on CPU are illustrative only — the
# cost model's flops/bytes stay exact, only the denominator is nominal)
CPU_PEAK_FLOPS = 3.2e12
CPU_PEAK_BW = 100e9

# device_kind substring -> peak per-chip ICI (interchip interconnect)
# bytes/s — the denominator for the collective kernels' traffic
# (sharded.allgather_topk / sharded.global_merge, PR 10). Public
# spec-sheet aggregates (links x per-link rate, both directions summed
# the way the HBM number is); first match wins.
DEVICE_ICI_PEAKS: list[tuple[str, float]] = [
    ("v6e", 448e9),    # Trillium: 4 x 896 Gbps
    ("v5p", 600e9),    # 6 x 800 Gbps
    ("v5e", 200e9),    # 4 x 400 Gbps
    ("v5", 200e9),
    ("v4", 300e9),     # 6 x 400 Gbps
    ("v3", 162e9),
    ("v2", 62e9),
]

# virtual CPU meshes move "collectives" through memcpy; nominal only
CPU_PEAK_ICI = 50e9


def ici_peak() -> float:
    """-> peak ICI bytes/s of the resident device kind (ES_TPU_PEAK_ICI
    overrides; CPU/virtual meshes get the nominal memcpy figure)."""
    env = os.environ.get("ES_TPU_PEAK_ICI")
    if env:
        return float(env)
    _f, _b, kind = device_peaks()
    lk = kind.lower().replace(" ", "")
    for pat, bw in DEVICE_ICI_PEAKS:
        if pat in lk:
            return bw
    return CPU_PEAK_ICI

_peaks_cache: tuple[float, float, str] | None = None


def device_peaks() -> tuple[float, float, str]:
    """-> (peak_flops, peak_bytes_per_s, device_kind). Environment
    overrides ES_TPU_PEAK_FLOPS / ES_TPU_PEAK_BW win (a new device kind
    must not silently inherit another's roofline)."""
    global _peaks_cache
    if _peaks_cache is not None and not (
            os.environ.get("ES_TPU_PEAK_FLOPS")
            or os.environ.get("ES_TPU_PEAK_BW")):
        return _peaks_cache
    kind = "cpu"
    flops, bw = CPU_PEAK_FLOPS, CPU_PEAK_BW
    try:
        import jax

        d = jax.devices()[0]
        kind = getattr(d, "device_kind", d.platform) or d.platform
        if d.platform == "tpu":
            lk = kind.lower().replace(" ", "")
            for pat, f, b in DEVICE_PEAKS:
                if pat in lk:
                    flops, bw = f, b
                    break
    except Exception:  # noqa: BLE001 - no backend: nominal CPU peaks
        pass
    env_f = os.environ.get("ES_TPU_PEAK_FLOPS")
    env_b = os.environ.get("ES_TPU_PEAK_BW")
    if env_f:
        flops = float(env_f)
    if env_b:
        bw = float(env_b)
    out = (flops, bw, kind)
    if not (env_f or env_b):
        _peaks_cache = out
    return out


# ---------------------------------------------------------------------------
# primitive costs (the unit-tested building blocks)
# ---------------------------------------------------------------------------

def matmul_cost(m: int, k: int, n: int, *, passes: int = 1,
                a_bytes: int = 2, b_bytes: int = 2,
                out_bytes: int = 4) -> dict:
    """[M,K]@[K,N] done `passes` times (the split-bf16 tier runs 2 logical
    passes: Wh@T16 + Wh@T16lo). Each pass re-reads both operands (they are
    distinct arrays in the split scheme) and the result is written once."""
    return {
        "flops": 2.0 * m * k * n * passes,
        "bytes": float(passes * (m * k * a_bytes + k * n * b_bytes)
                       + m * n * out_bytes),
    }


def topk_scan_cost(q: int, n: int, *, score_bytes: int = 4) -> dict:
    """Streamed top-k over a [q, n] score field: one bandwidth-bound read
    of the scores, 2 ops (compare + select) per element. The in-VMEM
    running top-k never round-trips HBM, so k does not appear."""
    return {
        "flops": 2.0 * q * n,
        "bytes": float(q * n * score_bytes),
    }


def sparse_bm25_cost(rows: int, *, block: int = 128,
                     lane_bytes: int = 12, out_n: int = 0) -> dict:
    """Blocked-CSR BM25 over `rows` posting blocks: each [BLOCK] lane is
    one (docid i32, tf f32, dl f32) read = 12 bytes, scored by ~6 FLOPs
    (mul, add, mul, add, div, mul — ops/scoring.score_posting_arrays) and
    scatter-added (1 op). out_n > 0 adds the dense accumulator write."""
    lanes = rows * block
    return {
        "flops": 7.0 * lanes,
        "bytes": float(lanes * lane_bytes + out_n * 4),
    }


def impact_gather_cost(q_rows: int, *, block: int = 128,
                       code_bytes: int = 2) -> dict:
    """Impact-tier gather+dequant (ops/kernels.impact_gather): each lane
    reads (docid i32 + code u16|i8) = 4 + code_bytes and writes the
    (docid i32, score f32) candidate pair = 8 bytes; 1 FLOP/lane (the
    dequant multiply) + 1 op of lane bookkeeping. q_rows = total gathered
    block rows across the batch (Q·Ts·B). Compare sparse_bm25_cost's
    12 B + 7 FLOPs/lane — the bytes/query argument of the BM25S tier."""
    lanes = q_rows * block
    return {
        "flops": 2.0 * lanes,
        "bytes": float(lanes * (4 + code_bytes + 8)),
    }


def impact_sum_cost(q: int, n: int, *, cands: int = 0) -> dict:
    """The impact arm's candidate tail (fast_topk_from_candidates): the
    dominating terms are the [q, cands] multi-operand sort (modeled as
    log2(cands) compare+select passes over the 8-byte (docid, score)
    lanes) and the dense-tier selection scan over [q, n]."""
    import math

    parts = [topk_scan_cost(q, n)]
    if cands:
        passes = max(1.0, math.log2(max(cands, 2)))
        parts.append({
            "flops": 2.0 * q * cands * passes,
            "bytes": float(q * cands * 8 * 3),  # read+sort+write passes
        })
    return _merge(*parts)


def knn_tiered_cost(b: int, d: int, n: int, *, kb: int = 128) -> dict:
    """TieredKnnScanner (ops/vector): 2 bf16 matmul passes over the split
    [D, N] corpus (hi + lo halves), then an f32 rescore of the [b, kb]
    survivors (gather [b, kb, D] rows + one einsum)."""
    sel = matmul_cost(b, d, n, passes=2, a_bytes=2, b_bytes=2, out_bytes=0)
    resc_flops = 2.0 * b * kb * d
    resc_bytes = float(b * kb * d * 4 + b * kb * 8)
    return {
        "flops": sel["flops"] + resc_flops + 2.0 * b * n,  # + selection scan
        "bytes": sel["bytes"] + resc_bytes,
    }


def ann_gather_scan_cost(b: int, p: int, l: int, d: int, *,
                         tier: str = "int8") -> dict:
    """The batched ANN gather-scan (ann/kernels): every (query, probed
    cluster) pair DMAs its [L, D] tile at the tier's storage dtype —
    int8 codes + 8 B/slot scale+offset, or the split-bf16 hi+lo pair at
    4D B/slot — plus 12 B/slot of order/live/aux metadata. Unlike the
    full-corpus scans, tiles ARE re-read per probing query (that is the
    gather), so bytes scale with b*p*l, not the corpus. FLOPs: the
    quantized matmul (2*slots*d), the int8 affine correction or the
    second bf16 pass, and 2 ops/slot of selection."""
    slots = float(b * p * l)
    if tier == "int8":
        tile_bytes = slots * (d * 1 + 8)
        mm_flops = 2.0 * slots * d + 2.0 * slots  # matmul + affine fma
    else:  # bf16 hi+lo pair: two passes over 2-byte tiles
        tile_bytes = slots * (2 * d * 2)
        mm_flops = 2.0 * 2.0 * slots * d
    return {
        "flops": mm_flops + 2.0 * slots,  # + selection scan
        "bytes": tile_bytes + slots * 12 + b * d * 4,
    }


def ann_rescore_cost(b: int, kb: int, d: int) -> dict:
    """f32 rescore of ANN survivors: [b, kb, d] row gather + one einsum
    + the (score, id) result writes — the rescore term of
    knn_tiered_cost standing alone."""
    return {
        "flops": 2.0 * b * kb * d,
        "bytes": float(b * kb * d * 4 + b * kb * 8),
    }


def knn_scan_cost(b: int, d: int, n: int) -> dict:
    """f32-HIGHEST exact scan (the escalation arm): one f32 matmul over
    the full corpus + the streamed selection."""
    mm = matmul_cost(b, d, n, passes=1, a_bytes=4, b_bytes=4, out_bytes=0)
    return {
        "flops": mm["flops"] + 2.0 * b * n,
        "bytes": mm["bytes"] + float(b * n * 4),
    }


# ---------------------------------------------------------------------------
# per-dispatch-site registry
# ---------------------------------------------------------------------------

def _merge(*costs: dict) -> dict:
    return {
        "flops": sum(c["flops"] for c in costs),
        "bytes": sum(c["bytes"] for c in costs),
    }


def _fused_pallas_scan(fields: dict) -> dict | None:
    """The fused dense-tier pipeline (ops/fused._fused_pipeline): split-
    bf16 2-pass matmul (in-kernel: tier read once as the stacked
    [2V, N] bf16 operand) + per-tile top-t selection + sparse one-hot
    scatter when posting rows ride along."""
    q = fields.get("queries")
    v = fields.get("v")
    n = fields.get("num_docs")
    if not (q and v and n):
        return None
    dense = matmul_cost(q, v, n, passes=2, a_bytes=2, b_bytes=2, out_bytes=0)
    sel = topk_scan_cost(q, n, score_bytes=0)  # scores stay in VMEM
    parts = [dense, sel]
    rows = fields.get("rows")
    if rows:
        parts.append(sparse_bm25_cost(int(rows)))
    return _merge(*parts)


def _compiled_plan(fields: dict) -> dict | None:
    """Per-query compiled plan (query/executor): dense accumulator
    scatter + streamed/xla selection over [1, N]. Coarse by design — the
    query's term mix is not in the fields; the selection pass dominates."""
    n = fields.get("num_docs")
    if not n:
        return None
    q = fields.get("queries", 1)
    return _merge(topk_scan_cost(q, n),
                  {"flops": 2.0 * q * n, "bytes": float(q * n * 4)})


def _batched_disjunction(fields: dict) -> dict | None:
    """Batched sparse path (ops/batched run/run_fast): postings gather +
    BM25 + per-query candidate selection."""
    q = fields.get("queries")
    n = fields.get("num_docs")
    if not (q and n):
        return None
    rows = fields.get("rows", 0)
    parts = [topk_scan_cost(q, n)]
    if rows:
        parts.append(sparse_bm25_cost(int(rows), out_n=n))
    return _merge(*parts)


def _sharded_spmd(fields: dict) -> dict | None:
    """SPMD scatter/gather searches (parallel/sharded search_batch): one
    program evaluates every shard; num_docs is the TOTAL docs scanned
    (S * n_max)."""
    n = fields.get("num_docs")
    if not n:
        return None
    q = fields.get("queries", fields.get("requests", 1))
    return _merge(topk_scan_cost(q, n),
                  {"flops": 2.0 * q * n, "bytes": float(q * n * 4)})


def _impact_gather(fields: dict) -> dict | None:
    rows = fields.get("rows")
    if not rows:
        return None
    return impact_gather_cost(int(rows),
                              code_bytes=int(fields.get("code_bytes", 2)))


def _impact_sum(fields: dict) -> dict | None:
    q, n = fields.get("queries"), fields.get("num_docs")
    if not (q and n):
        return None
    return impact_sum_cost(q, n, cands=int(fields.get("cands", 0)))


def _impact_sharded(fields: dict) -> dict | None:
    """One SPMD program: code-block gather+dequant per shard + the
    candidate tail; num_docs is the total scanned (S · n_max)."""
    q, n = fields.get("queries"), fields.get("num_docs")
    rows = fields.get("rows")
    if not (q and n and rows):
        return None
    return _merge(
        impact_gather_cost(int(rows),
                           code_bytes=int(fields.get("code_bytes", 2))),
        topk_scan_cost(q, n),
    )


def _knn_tiered(fields: dict) -> dict | None:
    b, d, n = fields.get("queries"), fields.get("dims"), fields.get("num_docs")
    if not (b and d and n):
        return None
    return knn_tiered_cost(b, d, n, kb=fields.get("kb", 128))


def _knn_scan(fields: dict) -> dict | None:
    b, d, n = fields.get("queries"), fields.get("dims"), fields.get("num_docs")
    if not (b and d and n):
        return None
    return knn_scan_cost(b, d, n)


def _ann_centroid_probe(fields: dict) -> dict | None:
    """[B, D] @ [D, C] f32 routing matmul + per-centroid selection."""
    b, d, c = fields.get("queries"), fields.get("dims"), fields.get("nlist")
    if not (b and d and c):
        return None
    mm = matmul_cost(b, d, c, passes=1, a_bytes=4, b_bytes=4, out_bytes=0)
    return _merge(mm, {"flops": 2.0 * b * c, "bytes": float(b * c * 4)})


def _ann_gather_scan(fields: dict) -> dict | None:
    b, d = fields.get("queries"), fields.get("dims")
    p, l = fields.get("nprobe"), fields.get("tile")
    if not (b and d and p and l):
        return None
    return ann_gather_scan_cost(b, p, l, d,
                                tier=fields.get("scan_tier", "int8"))


def _ann_rescore(fields: dict) -> dict | None:
    b, d, kb = fields.get("queries"), fields.get("dims"), fields.get("kb")
    if not (b and d and kb):
        return None
    return ann_rescore_cost(b, kb, d)


# ---------------------------------------------------------------------------
# write-path build stages (PR 13): the refresh/build pipeline gets the
# same flops/bytes accounting the query kernels carry, so the ROADMAP
# item-2 device port has a host baseline with per-stage attribution on
# day one. On the host these run as numpy loops — the MFU/bandwidth
# fractions are honest "how far from the roofline is this stage" numbers
# the port must close, not utilization claims.
# ---------------------------------------------------------------------------

def kmeans_build_cost(n: int, d: int, c: int, *, iters: int = 8) -> dict:
    """Lloyd k-means (ops/vector.kmeans_ivf): per iteration one [N,D]@[D,C]
    f32 distance matmul, a 2-ops/element argmax over [N,C], and the
    centroid scatter update reading the [N,D] corpus once more."""
    mm = matmul_cost(n, d, c, passes=iters, a_bytes=4, b_bytes=4,
                     out_bytes=0)
    return {
        "flops": mm["flops"] + 2.0 * n * c * iters + 2.0 * n * d * iters,
        "bytes": mm["bytes"] + float(iters * (n * 4 + c * d * 4)),
    }


def csr_assemble_build_cost(postings: int, *, n_docs: int = 0) -> dict:
    """Blocked-postings scatter (index/pack.py build): every posting is
    read from the flat CSR ((docid i32, tf f32) = 8 B) and written into
    its blocked lane ((docid, tf, dl) = 12 B); 2 ops/posting of index
    arithmetic; plus the per-doc norm gather."""
    return {
        "flops": 2.0 * postings,
        "bytes": float(postings * (8 + 12) + n_docs * 4),
    }


def norms_build_cost(n_docs: int, nfields: int) -> dict:
    """Smallfloat norm quantization (index/smallfloat.quantize_lengths):
    one i64 length read + one u8 norm write per (doc, field) lane, 2
    ops/lane for the quantize bucket search."""
    lanes = n_docs * max(nfields, 1)
    return {"flops": 2.0 * lanes, "bytes": float(lanes * (8 + 1))}


def impact_quantize_build_cost(rows: int, *, block: int = 128,
                               code_bytes: int = 2) -> dict:
    """Impact-code derivation over the blocked postings ([rows, BLOCK]
    lanes): tfn = tf/(tf + k_base + k_slope·dl) then scale+round+clip —
    ~6 FLOPs/lane; reads (tf f32, dl f32), writes one code. Identical
    model for the host derivation (pack.py, basis="host") and the
    on-device elementwise pass (sharded.refresh_impacts,
    basis="device") — the split between the two IS the attribution."""
    lanes = rows * block
    return {"flops": 6.0 * lanes, "bytes": float(lanes * (8 + code_bytes))}


def ann_tiles_build_cost(c: int, l: int, d: int) -> dict:
    """ANN tile packing (ann/index.build_ann): every [C, L] slot gathers
    its f32 vector row, scalar-quantizes it to int8 (~4 ops/element:
    min/max scan + affine + round) and writes codes + scale/offset/order
    metadata."""
    slots = float(c * l)
    return {
        "flops": 4.0 * slots * d,
        "bytes": slots * (d * 4 + d * 1 + 12),
    }


def device_put_build_cost(nbytes: float) -> dict:
    """Pack upload (sharded.stacked_to_device / update_live): a pure
    host→device transfer — zero FLOPs, judged on bandwidth only (the
    denominator is the HBM peak; PCIe/DMA peaks are below it, so the
    fraction is conservative)."""
    return {"flops": 0.0, "bytes": float(nbytes)}


def merge_build_cost(docs: int, *, nbytes: float = 0.0) -> dict:
    """Tier merge (engine._merge_tiers): a wrapper over a full rebuild —
    the inner stages carry the precise accounting; this entry keeps the
    merge-level roofline honest as one read of the old resident pack plus
    one write of its replacement, with 2 ops/doc of visibility
    bookkeeping."""
    return {"flops": 2.0 * docs, "bytes": float(2.0 * nbytes)}


def segment_merge_build_cost(docs: int, *, nbytes: float = 0.0) -> dict:
    """LSM tail-segment fold (engine._merge_tail_segments, PR 15): a
    wrapper over the union rebuild of the tail segments ONLY — the
    inner build.* stages (csr_assemble, impact_quantize, device_put…)
    carry the precise accounting; same read-old + write-new convention
    as build.merge, scoped to the tail bytes instead of the base."""
    return {"flops": 2.0 * docs, "bytes": float(2.0 * nbytes)}


def analyze_build_cost(nbytes: int) -> dict:
    """Batch text analysis (analysis/batched.py, PR 16): tokenization +
    term hashing over the burst's packed byte tensor. Bytes-based
    convention (BENCH_NOTES round 20) — work scales with input
    CHARACTERS, not docs: ~16 ops/byte (char-class tests, case fold,
    two segmented polynomial hash lanes with their scan combines) and
    ~3× the input bytes of traffic (read the char tensor once, write
    the boundary masks and two u32 hash lanes amortized over scan
    tiles). The identical model prices the device kernel
    (basis="device") and the batched host pass (basis="host") — the
    split between the two IS the attribution, like build.impact_quantize."""
    nbytes = float(max(int(nbytes), 1))
    return {"flops": 16.0 * nbytes, "bytes": 3.0 * nbytes}


def allgather_merge_cost(s: int, q: int, k: int, *,
                         id_bytes: int = 8) -> dict:
    """The on-device coordinator merge (PR 10): every shard's [q, k]
    (score f32, id i64) rows all-gather across the s mesh devices, then
    one lax.top_k over the [q, s*k] gathered field. ici_bytes is the
    total row volume crossing the interconnect once (s*q*k rows of
    4+id_bytes B — BENCH_NOTES round 14); HBM bytes are the gathered
    read + merged [q, k] write; 2 ops/element of selection."""
    rows = float(s * q * k)
    ici = rows * (4 + id_bytes)
    return {
        "flops": 2.0 * rows,
        "bytes": ici + float(q * k * (4 + id_bytes + 4)),
        "ici_bytes": ici,
    }


def _sharded_allgather_topk(fields: dict) -> dict | None:
    """One pjit SPMD program: per-shard scan (impact gather or raw-BM25
    disjunction, by tier) + the in-program all-gather top-k merge."""
    s = fields.get("shards")
    q, n = fields.get("queries"), fields.get("num_docs")
    k = fields.get("k")
    if not (s and q and n and k):
        return None
    if fields.get("tier") == "impact":
        scan = _impact_sharded(fields)
    else:
        scan = _batched_disjunction(fields)
    if scan is None:
        scan = topk_scan_cost(q, n)
    merge = allgather_merge_cost(int(s), int(q), int(k))
    out = _merge(scan, merge)
    out["ici_bytes"] = merge["ici_bytes"]
    return out


def _sharded_global_merge(fields: dict) -> dict | None:
    """The standalone merge program (probe / out-of-program rows)."""
    s, q, k = fields.get("shards"), fields.get("queries"), fields.get("k")
    if not (s and q and k):
        return None
    return allgather_merge_cost(int(s), int(q), int(k))


def _fused_sharded_allgather(fields: dict) -> dict | None:
    """The PR-11 fused one-program route: the per-shard fused Pallas
    pipeline (split-bf16 in-kernel matmul + per-tile selection, num_docs
    is the TOTAL padded docs scanned S·n_pad) inside an embedded
    shard_map region, plus the in-program all-gather top-k merge —
    ici_bytes judged against the interconnect peak like the other
    collective kernels."""
    s, k = fields.get("shards"), fields.get("k")
    scan = _fused_pallas_scan(fields)
    if not (s and k) or scan is None:
        return None
    merge = allgather_merge_cost(int(s), int(fields["queries"]), int(k))
    out = _merge(scan, merge)
    out["ici_bytes"] = merge["ici_bytes"]
    return out


def _serving_wave(fields: dict) -> dict | None:
    """The end-to-end serving wave (PR 11): every lane's compiled
    programs dispatched in one phase and pulled by ONE combined fetch —
    this span wraps that fetch, so its wall time is the wave's device
    execution. Modeled coarsely as the dominant scan over the wave's
    total (queries × resident docs) plus the all-gather merge; per-lane
    precision lives in the per-kernel entries, this one keeps the
    wave-level roofline honest."""
    s = fields.get("shards")
    q, n = fields.get("queries"), fields.get("num_docs")
    k = fields.get("k")
    if not (s and q and n and k):
        return None
    scan = topk_scan_cost(int(q), int(n))
    merge = allgather_merge_cost(int(s), int(q), int(k))
    out = _merge(scan, merge)
    out["ici_bytes"] = merge["ici_bytes"]
    return out


def _build_kmeans(fields: dict) -> dict | None:
    n, d, c = fields.get("n"), fields.get("dims"), fields.get("nlist")
    if not (n and d and c):
        return None
    return kmeans_build_cost(int(n), int(d), int(c),
                             iters=int(fields.get("iters", 8)))


def _build_csr_assemble(fields: dict) -> dict | None:
    p = fields.get("postings")
    if p is None:
        return None
    return csr_assemble_build_cost(int(p),
                                   n_docs=int(fields.get("num_docs", 0)))


def _build_norms(fields: dict) -> dict | None:
    n = fields.get("num_docs")
    if n is None:
        return None
    return norms_build_cost(int(n), int(fields.get("nfields", 1)))


def _build_impact_quantize(fields: dict) -> dict | None:
    rows = fields.get("rows")
    if rows is None:
        return None
    return impact_quantize_build_cost(
        int(rows), code_bytes=int(fields.get("code_bytes", 2)))


def _build_ann_tiles(fields: dict) -> dict | None:
    c, l, d = fields.get("nlist"), fields.get("tile"), fields.get("dims")
    if not (c and l and d):
        return None
    return ann_tiles_build_cost(int(c), int(l), int(d))


def _build_device_put(fields: dict) -> dict | None:
    nbytes = fields.get("nbytes")
    if nbytes is None:
        return None
    return device_put_build_cost(float(nbytes))


def _build_merge(fields: dict) -> dict | None:
    docs = fields.get("docs")
    if docs is None:
        return None
    return merge_build_cost(int(docs),
                            nbytes=float(fields.get("nbytes", 0.0)))


def _build_segment_merge(fields: dict) -> dict | None:
    docs = fields.get("docs")
    if docs is None:
        return None
    return segment_merge_build_cost(int(docs),
                                    nbytes=float(fields.get("nbytes", 0.0)))


def _build_analyze(fields: dict) -> dict | None:
    nbytes = fields.get("nbytes")
    if nbytes is None:
        return None
    return analyze_build_cost(int(nbytes))


def _esql_stats_exchange(fields: dict) -> dict | None:
    """STATS partial-aggregation exchange (esql/exchange.py): per-shard
    one-hot [G,R]x[R] matmul partials per value view (double columns one
    view; long columns ship i64 + hi/lo f64 = 3 views; the bare count
    rides the group one-hot), then the [S,...] collective merge. Useful
    work only — the padded R already prices the padding the layout pays,
    matching the dense-matmul convention of vector.knn_scan."""
    s, r, g = fields.get("shards"), fields.get("rows"), fields.get("groups")
    if not (s and r and g):
        return None
    s, r, g = int(s), int(r), int(g)
    dc = int(fields.get("dbl_cols", 0))
    lc = int(fields.get("long_cols", 0))
    views = dc + 3 * lc + 1
    flops = 2.0 * s * r * g * views
    bytes_ = (
        s * r * (4.0                      # group ordinals (i32)
                 + 9.0 * dc               # f64 values + ok mask
                 + 33.0 * lc)             # i64 + hi/lo f64 + ok mask
        + s * g * 8.0 * (4.0 * max(dc, 1) + 2.0 * lc)  # partial outputs
    )
    return {"flops": flops, "bytes": bytes_}


def _esql_topn_exchange(fields: dict) -> dict | None:
    """SORT|LIMIT top-n exchange (esql/topn.py): per-shard lexicographic
    lax.sort over K encoded rank keys + the row index, then the gathered
    re-sort of S*n winners. Sort flops priced as comparator work
    ~ rows*log2(rows) per key lane (the sharded.global_merge sort
    convention); bytes move each [K+1] key lane once in and once out."""
    s, r = fields.get("shards"), fields.get("rows")
    if not (s and r):
        return None
    s, r = int(s), int(r)
    k1 = int(fields.get("keys", 1)) + 1
    n = int(fields.get("n", 1)) or 1
    lg = max(math.log2(max(r, 2)), 1.0)
    lgm = max(math.log2(max(s * n, 2)), 1.0)
    flops = 2.0 * s * k1 * r * lg + 2.0 * k1 * (s * n) * lgm
    bytes_ = 2.0 * 8.0 * k1 * (s * r + s * n)
    return {"flops": flops, "bytes": bytes_}


# name -> cost fn (None = wrapper span; inner kernels carry the cost).
# Keys are the literal time_kernel(...) names at the dispatch sites —
# the tier-1 lint (tests/test_monitoring.py) enforces the bijection.
KERNEL_COSTS: dict[str, object] = {
    "fused.pallas_scan": _fused_pallas_scan,
    "fused.msearch": None,           # wraps fused.pallas_scan (+escalation)
    "batched.disjunction": _batched_disjunction,
    "batched.escalation": _batched_disjunction,
    "compiled_plan": _compiled_plan,
    "sharded.spmd_topk": _sharded_spmd,
    "sharded.exact_disjunction": _batched_disjunction,
    "sharded.fused_pipeline": _fused_pallas_scan,
    # pjit GSPMD path (PR 10): the one-program scan + all-gather merge,
    # and the standalone device merge — both carry an ici_bytes term
    # judged against the ICI peak (ici_util)
    "sharded.allgather_topk": _sharded_allgather_topk,
    "sharded.global_merge": _sharded_global_merge,
    # PR 17: tenant superpacks — one program scoring a wave that mixes
    # queries from many tenant lanes of a shared size-class layout; the
    # body is the batched disjunction over lane-indexed gathers, so the
    # same cost shape applies (num_docs = the class's padded doc width)
    "superpack.tenant_gather": _batched_disjunction,
    # PR 11: the fused Pallas arm riding the one-program route (embedded
    # shard_map region + in-program merge), and the serving wave's
    # single combined fetch — both collective entries with ici_util
    "sharded.fused_allgather_topk": _fused_sharded_allgather,
    "serving.wave_program": _serving_wave,
    "sharded.wand_pass1": None,      # pruned postings subset: rows unknown
    "sharded.wand_pass2": None,      #   until finalize — wall time only
    # impact-scored sparse tier (BM25S, PR 8)
    "sparse.impact_gather": _impact_gather,
    "sparse.impact_sum": _impact_sum,
    "sharded.impact_disjunction": _impact_sharded,
    "sparse.tail_scan": _sharded_spmd,  # exact scan of the post-build tail
    "vector.knn_tiered": _knn_tiered,
    "vector.knn_scan": _knn_scan,
    "ann.centroid_probe": _ann_centroid_probe,
    "ann.gather_scan": _ann_gather_scan,
    "ann.rescore": _ann_rescore,
    "ann.tail_scan": _knn_scan,      # exact f32 scan of the tail tier
    # write-path build stages (PR 13): refresh/build gets the same
    # accounting — dispatched via monitoring/refresh_profile.build_stage
    # (the lint scans those literals too), host today, the item-2 port's
    # baseline tomorrow
    "build.kmeans": _build_kmeans,
    "build.impact_quantize": _build_impact_quantize,
    "build.csr_assemble": _build_csr_assemble,
    "build.norms": _build_norms,
    "build.ann_tiles": _build_ann_tiles,
    "build.device_put": _build_device_put,
    "build.merge": _build_merge,
    # PR 15: the LSM tail-segment fold (background device merge riding
    # the serving queue as the `_merge` tenant)
    "build.segment_merge": _build_segment_merge,
    # PR 16: batch text analysis — the former host `analyze` wall as a
    # costed dispatch (bytes-based; analysis/batched.analyze_burst)
    "build.analyze": _build_analyze,
    # PR 20: the ESQL device exchanges (esql/exchange.py, esql/topn.py) —
    # the only device dispatches in the whole pipe; host operators are
    # profiled by esql/profile.py and exempt here by design
    "esql.stats_exchange": _esql_stats_exchange,
    "esql.topn_exchange": _esql_topn_exchange,
}


def kernel_cost(name: str, fields: dict) -> dict | None:
    """-> {"flops", "bytes"} for one dispatch, or None (unknown name,
    wrapper entry, or shape fields missing)."""
    fn = KERNEL_COSTS.get(name)
    if fn is None:
        return None
    try:
        return fn(fields)
    except Exception:  # noqa: BLE001 - accounting must never fail a search
        return None


def utilization(name: str, fields: dict, seconds: float) -> dict | None:
    """-> {flops, bytes, mfu, bw_util[, ici_bytes, ici_util]} for one
    timed dispatch, or None. Collective kernels (an ici_bytes term in
    their cost) additionally report achieved ICI utilization against
    the interconnect peak."""
    cost = kernel_cost(name, fields)
    if cost is None:
        return None
    peak_f, peak_b, _kind = device_peaks()
    sec = max(seconds, 1e-9)
    out = {
        "flops": cost["flops"],
        "bytes": cost["bytes"],
        "mfu": cost["flops"] / sec / peak_f,
        "bw_util": cost["bytes"] / sec / peak_b,
    }
    if cost.get("ici_bytes"):
        out["ici_bytes"] = cost["ici_bytes"]
        out["ici_util"] = cost["ici_bytes"] / sec / ici_peak()
    return out
