"""Device-memory accounting, JIT compile tracking, and the per-kernel
utilization snapshot.

Reference parallel: monitor/jvm/JvmStats + monitor/os/OsStats feed the
reference's node stats; here the "JVM" is the XLA runtime, so the node
must account HBM (live array bytes, allocator high-watermark), compile
activity (counts, seconds, executable-cache hit rates — a fresh compile
key mid-serving is this engine's GC-pause analog), and the padded-lane
waste its fixed-shape compilation discipline trades for compile reuse.
"""

from __future__ import annotations

import threading

from ..telemetry import metrics
from .costmodel import device_peaks

_compile_lock = threading.Lock()
_compile_installed = False


def install_compile_listener() -> None:
    """Register a jax.monitoring duration listener that meters every XLA
    backend compile into the registry (es.jit.compiles counter +
    es.jit.compile.ms histogram). Idempotent; survives metrics.reset()
    (the listener re-creates its instruments on the next compile)."""
    global _compile_installed
    with _compile_lock:
        if _compile_installed:
            return
        try:
            import jax.monitoring as jmon

            def _on_duration(event: str, duration: float, **_kw):
                if event.endswith("backend_compile_duration"):
                    metrics.counter_inc("es.jit.compiles")
                    metrics.counter_inc("es.jit.compile_time_ms",
                                        duration * 1000.0)
                    metrics.histogram_record("es.jit.compile.ms",
                                             duration * 1000.0)

            jmon.register_event_duration_secs_listener(_on_duration)
            _compile_installed = True
        except Exception:  # noqa: BLE001 - older jax: counters stay at 0
            _compile_installed = True


def note_executable_cache(site: str, hit: bool) -> None:
    """Count a framework executable-cache lookup (query/executor compiled
    plans, ops/fused scanned pipelines, the sharded fused arm). A miss
    means the NEXT execution pays trace+XLA compile — the serving-latency
    cliff every cache here exists to avoid."""
    metrics.counter_inc(
        f"es.jit.cache.{'hits' if hit else 'misses'}")
    metrics.counter_inc(
        f"es.jit.cache.{site}.{'hits' if hit else 'misses'}")


def jit_stats() -> dict:
    """Compile + executable-cache counters for _nodes/stats."""
    snap = metrics.snapshot()
    c = snap["counters"]
    h = snap["histograms"].get("es.jit.compile.ms") or {}
    return {
        "compiles": int(c.get("es.jit.compiles", 0)),
        "compile_time_in_millis": int(c.get("es.jit.compile_time_ms", 0.0)),
        "compile_ms_max": h.get("max", 0.0),
        "executable_cache": {
            "hits": int(c.get("es.jit.cache.hits", 0)),
            "misses": int(c.get("es.jit.cache.misses", 0)),
        },
    }


# ---------------------------------------------------------------------------
# HBM / host memory
# ---------------------------------------------------------------------------

def device_memory_snapshot() -> dict:
    """Live device-array bytes (exact: jax.live_arrays) plus the
    allocator's own view when the backend exposes one (TPU memory_stats:
    bytes_in_use / peak_bytes_in_use / bytes_limit; CPU returns none).
    The live/peak pair is the "driver-recorded device-bound proof"
    VERDICT asked for: HBM residency measured, not asserted."""
    import jax

    out: dict = {"backend": None, "device_kind": None,
                 "live_arrays": 0, "live_bytes": 0}
    try:
        d = jax.devices()[0]
        out["backend"] = d.platform
        out["device_kind"] = getattr(d, "device_kind", d.platform)
        live = 0
        count = 0
        for a in jax.live_arrays():
            try:
                live += a.nbytes
                count += 1
            except Exception:  # noqa: BLE001 - deleted buffer race
                continue
        out["live_arrays"] = count
        out["live_bytes"] = int(live)
        out["device_count"] = len(jax.devices())
        from ..parallel.spmd import spmd_mode

        # the slice execution model (PR 10): pjit = GSPMD sharded pack +
        # on-device all-gather merge; shardmap = legacy per-shard bodies
        out["spmd_mode"] = spmd_mode()
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend without memory stats
            stats = None
        if stats:
            for src, dst in (("bytes_in_use", "bytes_in_use"),
                             ("peak_bytes_in_use", "peak_bytes_in_use"),
                             ("bytes_limit", "bytes_limit"),
                             ("largest_free_block_bytes",
                              "largest_free_block_bytes")):
                if src in stats:
                    out[dst] = int(stats[src])
    except Exception:  # noqa: BLE001 - no backend at all
        pass
    return out


def pack_padded_waste(sp) -> int:
    """Bytes of a StackedPack occupied by PADDING (docs padded to n_max
    per shard, posting blocks padded to nb_max) — the HBM rent paid for
    uniform SPMD shapes. Shape arithmetic only: no array is scanned."""
    S = max(sp.S, 1)
    doc_slots = S * max(sp.n_max, 1)
    real_docs = sum(p.num_docs for p in sp.shards)
    doc_pad = max(doc_slots - real_docs, 0) / doc_slots
    blk_slots = S * max(sp.nb_max, 1)
    real_blocks = sum(p.num_blocks for p in sp.shards)
    blk_pad = max(blk_slots - real_blocks, 0) / blk_slots
    waste = 0.0
    for arr in (sp.post_docids, sp.post_tfs, sp.post_dls):
        waste += arr.nbytes * blk_pad
    doc_arrays = [sp.live]
    doc_arrays.extend(sp.norms.values())
    doc_arrays.extend(sp.text_present.values())
    if sp.dense_tf is not None:
        doc_arrays.append(sp.dense_tf)
    for col in sp.stacked_docvalues.values():
        doc_arrays.append(col.values)
        doc_arrays.append(col.has_value)
    for vc in sp.vectors.values():
        doc_arrays.append(vc.values)
        doc_arrays.append(vc.has_value)
    for arr in doc_arrays:
        waste += arr.nbytes * doc_pad
    return int(waste)


def padded_waste_bytes(engine) -> int:
    """Padded-lane waste across every resident searcher of the node.
    Reads the private tier handles directly — the `searcher` property
    force-merges tiers as a side effect, which a stats read must never
    trigger."""
    total = 0
    for idx in engine.indices.values():
        for s in idx.tier_searchers():
            try:
                total += pack_padded_waste(s.sp)
            except Exception:  # noqa: BLE001 - stats must not fail
                continue
    # tenant superpacks (PR 17) rent additional padded HBM: vacant lanes
    # + per-lane size-class padding, the same accounting over the shared
    # layout (the manager reuses pack_padded_waste via a lane shim)
    if engine._superpacks is not None:
        try:
            total += engine._superpacks.padded_waste_bytes()
        except Exception:  # noqa: BLE001 - stats must not fail
            pass
    return total


# ---------------------------------------------------------------------------
# the utilization snapshot (per kernel, cumulative)
# ---------------------------------------------------------------------------

def kernel_utilization() -> dict:
    """{kernel_name: {calls, wall_ms, flops, bytes, mfu, bw_util,
    mfu_p50, mfu_max}} aggregated from the registry's per-kernel
    instruments (time_kernel feeds them on every dispatch). Cumulative
    MFU = total flops / total wall seconds / peak — the number future
    perf PRs are judged against."""
    snap = metrics.snapshot()
    counters = snap["counters"]
    hists = snap["histograms"]
    peak_f, peak_b, kind = device_peaks()
    out: dict = {}
    for name, h in hists.items():
        if not (name.startswith("es.kernel.") and name.endswith(".ms")):
            continue
        kname = name[len("es.kernel."):-len(".ms")]
        flops = counters.get(f"es.kernel.{kname}.flops", 0.0)
        nbytes = counters.get(f"es.kernel.{kname}.bytes", 0.0)
        sec = max(h["sum"] / 1000.0, 1e-9)
        entry = {
            "calls": h["count"],
            "wall_ms": round(h["sum"], 3),
            "wall_ms_p50": round(h["p50"], 3),
            "flops": flops,
            "bytes": nbytes,
            "mfu": round(flops / sec / peak_f, 6),
            "bw_util": round(nbytes / sec / peak_b, 6),
        }
        mh = hists.get(f"es.kernel.{kname}.mfu_pct")
        if mh:
            entry["mfu_pct_p50"] = round(mh["p50"], 4)
            entry["mfu_pct_max"] = round(mh["max"], 4)
        out[kname] = entry
    # PR 12: per-kernel analytic-vs-XLA drift (the compiled-program
    # cross-check) rides the utilization section, so a reader of the
    # roofline numbers sees how much to trust the numerator
    from .xla_introspect import OBSERVATIONS, drift_table

    for kname, entry in out.items():
        o = OBSERVATIONS.get(kname)
        if o is not None and "drift" in o:
            entry["xla_drift"] = dict(o["drift"])
    return {"device_kind": kind, "peak_flops": peak_f,
            "peak_bytes_per_sec": peak_b, "kernels": out,
            "costmodel_drift": drift_table()}


def device_stats(engine=None) -> dict:
    """The `_nodes/stats` device section: memory + utilization + jit."""
    out = {
        "memory": device_memory_snapshot(),
        "utilization": kernel_utilization(),
        "jit": jit_stats(),
    }
    if engine is not None:
        out["memory"]["pack_padded_waste_bytes"] = padded_waste_bytes(engine)
    return out
