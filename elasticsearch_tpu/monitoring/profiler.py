"""Duration-bounded device profiling: the `jax.profiler` trace surface.

The reference ships Universal Profiling (whole-fleet eBPF) as a
stand-alone x-pack stack; this engine's profiler of record is the XLA
runtime's own: `jax.profiler.start_trace/stop_trace` writes an XPlane
protobuf trace (TensorBoard/XProf-readable) containing every device
kernel launch, transfer, and host callback of the window. This module
wraps it as a node service so that

  - operators can start/stop a capture over REST
    (`POST /_profiler/{start,stop}`, `GET /_profiler`);
  - the watcher `capture` action can take a bounded trace when an SLO
    objective breaches (evidence, not just an alert doc);
  - every capture is DURATION-BOUNDED (`xpack.profiling.max_duration`
    clamps requests; a watchdog timer force-stops a forgotten trace), and
  - the trace directory is retention-pruned by the monitoring
    CleanerService (`xpack.profiling.retention`) like the dated hidden
    indices — a breach storm cannot fill the disk.

Only one trace can be active per process (an XLA constraint); concurrent
start/capture requests get a structured refusal, never a crash.
"""

from __future__ import annotations

import os
import shutil
import threading
import time

from ..telemetry import log, metrics

CAPTURE_PREFIX = "capture-"

# the XLA profiler is a PROCESS singleton: multiple engines in one
# process (cluster test fixtures, embedded nodes) must share one lock
# and one active-trace slot, or a second engine's start corrupts the
# first engine's capture
_GLOBAL_LOCK = threading.Lock()


class _Shared:
    """Process-global active-trace slot (shared by every engine)."""

    active: dict | None = None
    watchdog: threading.Timer | None = None


class ProfilerService:
    """Per-engine bounded jax.profiler trace capture."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = _GLOBAL_LOCK
        self.captures_total = 0
        self.last_capture: dict | None = None

    @property
    def _active(self):
        return _Shared.active

    # -- settings ----------------------------------------------------------

    def _get(self, key, default=None):
        try:
            v = self.engine.settings.get(key)
        except Exception:  # noqa: BLE001 - engines without the setting
            return default
        return default if v is None else v

    @property
    def enabled(self) -> bool:
        return bool(self._get("xpack.profiling.enabled", True))

    def max_duration_s(self) -> float:
        from ..utils.durations import parse_duration_seconds

        raw = self._get("xpack.profiling.max_duration", "10s")
        return max(parse_duration_seconds(raw, 10.0) or 10.0, 0.05)

    def retention_s(self) -> float:
        from ..utils.durations import parse_duration_seconds

        raw = self._get("xpack.profiling.retention", "1h")
        return max(parse_duration_seconds(raw, 3600.0) or 3600.0, 1.0)

    def trace_dir(self) -> str:
        configured = str(self._get("xpack.profiling.trace_dir", "") or "")
        if configured:
            return configured
        data = getattr(self.engine, "data_path", None)
        if data:
            return os.path.join(data, "profiler")
        import tempfile

        return os.path.join(tempfile.gettempdir(),
                            f"elasticsearch-tpu-profiler-{os.getpid()}")

    # -- trace lifecycle ---------------------------------------------------

    def start(self, duration_s: float | None = None,
              reason: str = "manual") -> dict:
        """Start a trace into a fresh capture dir. duration_s (clamped to
        xpack.profiling.max_duration) arms the watchdog that force-stops
        the trace — an operator who forgets `stop` cannot leave the
        profiler running across a serving day."""
        if not self.enabled:
            return {"started": False, "reason": "xpack.profiling.enabled "
                                                "is false"}
        bound = self.max_duration_s()
        dur = min(duration_s, bound) if duration_s else bound
        with self._lock:
            if _Shared.active is not None:
                return {"started": False, "reason": "trace already active",
                        "active": self._status_locked()}
            cap_dir = os.path.join(
                self.trace_dir(), f"{CAPTURE_PREFIX}{int(time.time() * 1000)}")
            os.makedirs(cap_dir, exist_ok=True)
            try:
                import jax.profiler

                jax.profiler.start_trace(cap_dir)
            except Exception as e:  # noqa: BLE001 - backend w/o profiler
                return {"started": False,
                        "reason": f"{type(e).__name__}: {e}"}
            _Shared.active = {"dir": cap_dir,
                              "started_unix": time.time(),
                              "bound_s": dur, "trigger": reason,
                              "owner": id(self)}
            _Shared.watchdog = threading.Timer(
                dur, self.stop, kwargs={"_watchdog": True})
            _Shared.watchdog.daemon = True
            _Shared.watchdog.start()
            metrics.counter_inc("es.profiler.traces_started")
            return {"started": True, "dir": cap_dir, "bound_s": dur,
                    "trigger": reason}

    def stop(self, _watchdog: bool = False) -> dict:
        with self._lock:
            active = _Shared.active
            if active is None:
                return {"stopped": False, "reason": "no active trace"}
            _Shared.active = None
            if _Shared.watchdog is not None:
                _Shared.watchdog.cancel()
                _Shared.watchdog = None
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                log.debug("profiler stop failed: %s", e)
            files = []
            nbytes = 0
            for root, _dirs, names in os.walk(active["dir"]):
                for nm in names:
                    p = os.path.join(root, nm)
                    try:
                        nbytes += os.path.getsize(p)
                    except OSError:
                        continue
                    files.append(os.path.relpath(p, active["dir"]))
            out = {
                "stopped": True,
                "dir": active["dir"],
                "trigger": active["trigger"],
                "duration_ms": round(
                    (time.time() - active["started_unix"]) * 1000, 3),
                "by_watchdog": _watchdog,
                "files": sorted(files),
                "bytes": nbytes,
            }
            self.captures_total += 1
            self.last_capture = out
            metrics.counter_inc("es.profiler.traces_completed")
            return out

    def capture(self, duration_s: float | None = None,
                reason: str = "breach") -> dict:
        """Synchronous bounded capture (the watcher action): start, hold
        the window open (a tiny device op guarantees the trace is never
        empty of device activity), stop. Refuses politely if a trace is
        already running."""
        dur = min(duration_s or 0.2, self.max_duration_s())
        started = self.start(duration_s=max(dur * 4, 1.0), reason=reason)
        if not started.get("started"):
            return started
        try:
            import jax
            import jax.numpy as jnp

            x = jnp.ones((128, 128), jnp.float32)
            jax.block_until_ready(x @ x)
            time.sleep(dur)
        except Exception:  # noqa: BLE001 - the stop below still runs
            pass
        return self.stop()

    # -- introspection / retention ----------------------------------------

    def _status_locked(self) -> dict:
        a = _Shared.active
        return {"active": a is not None,
                **({"dir": a["dir"], "trigger": a["trigger"],
                    "running_ms": round(
                        (time.time() - a["started_unix"]) * 1000, 1)}
                   if a is not None else {})}

    def status(self) -> dict:
        with self._lock:
            st = self._status_locked()
        st.update({
            "enabled": self.enabled,
            "trace_dir": self.trace_dir(),
            "max_duration_s": self.max_duration_s(),
            "retention_s": self.retention_s(),
            "captures_total": self.captures_total,
            "last_capture": self.last_capture,
            "retained_captures": self.list_captures(),
        })
        return st

    def list_captures(self) -> list[str]:
        base = self.trace_dir()
        try:
            return sorted(d for d in os.listdir(base)
                          if d.startswith(CAPTURE_PREFIX))
        except OSError:
            return []

    def prune(self) -> list[str]:
        """Delete capture dirs older than xpack.profiling.retention.
        Called by the monitoring CleanerService pass alongside the dated
        hidden indices; the active capture is never pruned."""
        base = self.trace_dir()
        cutoff_ms = (time.time() - self.retention_s()) * 1000
        with self._lock:
            active_dir = (_Shared.active["dir"]
                          if _Shared.active else None)
        pruned = []
        for d in self.list_captures():
            full = os.path.join(base, d)
            if full == active_dir:
                continue
            try:
                stamp = float(d[len(CAPTURE_PREFIX):])
            except ValueError:
                continue
            if stamp < cutoff_ms:
                shutil.rmtree(full, ignore_errors=True)
                pruned.append(d)
        if pruned:
            metrics.counter_inc("es.profiler.captures_pruned", len(pruned))
        return pruned

    def close(self) -> None:
        # only stop a trace THIS engine started — in multi-engine
        # processes (cluster fixtures) closing one engine must not kill
        # another engine's in-flight capture
        with self._lock:
            owned = (_Shared.active is not None
                     and _Shared.active.get("owner") == id(self))
        if owned:
            self.stop()
