"""Write-path ground truth: refresh/build stage profiling (PR 13).

Every query-side kernel reports its roofline fraction (PR 5 cost model,
PR 12 XLA cross-check), but the write path — kmeans IVF builds, impact
quantization, CSR assembly, ANN tile packing, device uploads — ran as
unprofiled host loops. ROADMAP item 2 ("device-side index construction")
needs a baseline to beat and a way to prove the port moved work onto the
chip; this module is that measurement substrate:

  - `build_stage("build.<name>", **fields)` wraps one build stage in a
    `telemetry.time_kernel` dispatch (KERNEL_COSTS carries a flops/bytes
    model per stage, so host-vs-device attribution works the day the
    stage becomes a device kernel) AND, when a refresh is being
    profiled, charges the stage's wall time to the active collector;
  - `refresh_stage("<name>")` marks collector-only host phases
    (routing, analysis) that are not candidate device kernels — they
    stay visible in the profile instead of hiding in a residual;
  - `RefreshProfile` records follow the PR-12 flight-recorder
    discipline: stage timings are cut from ONE contiguous sequence of
    boundary timestamps, so they sum to the refresh wall time by
    construction (asserted by tests, not sampled); each record carries
    docs/bytes processed, the refresh kind (full/incremental/merge) and
    the resulting tail-tier state;
  - a bounded ring per engine (`indexing.profile.size`, dynamic) serves
    `GET /_refresh/profile`, feeds the `_nodes/stats` `indexing`
    section and the monitoring TSDB node_stats docs, and underwrites
    the `slo.write.*` objectives (monitoring/slo.py).

The reference's RefreshStats/MergeStats count operations and total
millis (index/refresh/RefreshStats.java); they never say WHERE a
refresh spent its time, because a CPU engine has no host-vs-device
attribution problem. Here the split IS the roadmap item."""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager

# ---------------------------------------------------------------------------
# contiguous stage collection
# ---------------------------------------------------------------------------

# the residual bucket: wall time not inside any named stage (doc
# routing, python bookkeeping, breaker admission). Named explicitly so
# an untagged hot loop shows up as a growing host_other, not as silently
# missing time.
OTHER_STAGE = "host_other"


class StageCollector:
    """Flat-sum stage clock: every stage enter/exit cuts the clock at one
    boundary timestamp and charges the elapsed segment to the stage that
    was on top of the stack. All segments derive from the SAME timestamp
    sequence, so sum(stages) == wall exactly (before rounding) — the
    flight-recorder contiguity discipline applied to refresh."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._stack: list[str] = [OTHER_STAGE]
        self.stages: dict[str, float] = {}  # name -> seconds
        # (name, start_s, end_s) spans relative to t0 — the overlap
        # evidence: main-thread stage windows (events) plus worker
        # spans (async_events, via note_span), so analyze(k) ∥
        # build(k−1) is visible in the RefreshProfile timestamps, not
        # just inferable from sums
        self.events: list[tuple[str, float, float]] = []
        self.async_events: list[tuple[str, float, float]] = []
        # seconds of concurrent work per stage name, charged via
        # note_span by worker threads — kept OUT of `stages` so the
        # flat-sum invariant (sum(stages) == wall) stays per-thread
        self.async_stages: dict[str, float] = {}
        self._elock = threading.Lock()

    def _cut(self) -> None:
        now = time.perf_counter()
        name = self._stack[-1]
        self.stages[name] = self.stages.get(name, 0.0) + (now - self._last)
        self._last = now

    @contextmanager
    def stage(self, name: str):
        self._cut()
        t_en = self._last
        self._stack.append(name)
        try:
            yield
        finally:
            self._cut()
            self._stack.pop()
            with self._elock:
                self.events.append(
                    (name, t_en - self._t0, self._last - self._t0))

    def note_span(self, name: str, t_start: float, t_end: float) -> None:
        """Record work done on ANOTHER thread (perf_counter timestamps):
        an event span for the overlap timeline plus an async stage
        charge. Thread-safe; never touches the flat-sum clock."""
        with self._elock:
            self.async_events.append(
                (name, t_start - self._t0, t_end - self._t0))
            self.async_stages[name] = (self.async_stages.get(name, 0.0)
                                       + (t_end - t_start))

    def finish(self) -> tuple[float, dict[str, float]]:
        """-> (wall_seconds, {stage: seconds}). wall is the last boundary
        minus the first, i.e. exactly the stage sum."""
        self._cut()
        return self._last - self._t0, dict(self.stages)


_collector: contextvars.ContextVar[StageCollector | None] = (
    contextvars.ContextVar("refresh_stage_collector", default=None))


def active_collector() -> StageCollector | None:
    """The collector of the refresh being profiled on THIS thread, if
    any — captured by the stacked build before spawning analyze
    workers, whose fresh thread contexts see None and report back via
    note_span."""
    return _collector.get()


@contextmanager
def collect_build_stages():
    """Activate a StageCollector for the duration of one refresh/build;
    nested build_stage/refresh_stage marks charge into it. Yields the
    collector (bench.py reads .finish() directly for the build_profile
    record)."""
    c = StageCollector()
    token = _collector.set(c)
    try:
        yield c
    finally:
        _collector.reset(token)


@contextmanager
def refresh_stage(name: str):
    """Collector-only stage mark for host phases that are NOT candidate
    device kernels (doc routing, analysis/tokenization): visible in the
    RefreshProfile, absent from KERNEL_COSTS by design."""
    c = _collector.get()
    if c is None:
        yield
        return
    with c.stage(name):
        yield


@contextmanager
def build_stage(name: str, **fields):
    """One build-stage dispatch: always a `telemetry.time_kernel(name)`
    (the dispatch-site lint requires a KERNEL_COSTS entry for the
    literal name — a new build stage cannot ship unaccounted), plus a
    collector stage charge when a refresh is being profiled. `name` is
    the full kernel name ("build.kmeans", "build.csr_assemble", ...)."""
    from ..telemetry import time_kernel

    c = _collector.get()
    if c is None:
        with time_kernel(name, **fields):
            yield
        return
    with c.stage(name):
        with time_kernel(name, **fields):
            yield


# ---------------------------------------------------------------------------
# the per-refresh record + bounded ring
# ---------------------------------------------------------------------------

def _iso_utc(ts: float | None = None) -> str:
    t = time.time() if ts is None else ts
    ms = int(t * 1000) % 1000
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + f".{ms:03d}Z"


class RefreshRecorder:
    """Bounded ring of RefreshProfile records plus the cumulative
    write-path accounting the `_nodes/stats` `indexing` section reports:
    refresh/merge counts by kind, cumulative per-stage millis, the
    current tail fraction, and a docs/s ingest EMA (rate measured
    refresh-over-refresh, smoothed — the closed-loop C7 bench arm's
    sustained-ingest readout)."""

    EMA_ALPHA = 0.3

    def __init__(self, size: int = 256):
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(int(size), 1))
        self._seq = 0
        self._counts: dict[str, int] = {}
        self._stage_ms: dict[str, float] = {}
        self._docs_total = 0
        self._last_record_t: float | None = None
        self._docs_per_s_ema: float | None = None
        self._last_tail_fraction = 0.0

    def set_size(self, size) -> None:
        size = max(int(size), 1)
        with self._lock:
            if size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=size)

    def record(self, profile: dict) -> dict:
        """Append one finished RefreshProfile; returns it with its
        sequence number stamped."""
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            profile = {"refresh": self._seq, **profile}
            self._ring.append(profile)
            kind = profile.get("kind", "full")
            self._counts[kind] = self._counts.get(kind, 0) + 1
            for stage, ms in (profile.get("stages_ms") or {}).items():
                self._stage_ms[stage] = self._stage_ms.get(stage, 0.0) + ms
            # worker-thread stage time (analyze/build overlap) counts in
            # the cumulative accounting — the SLO analyze fraction and
            # the health dominant-stage diagnosis must see every
            # millisecond, overlapped or not
            for stage, ms in (profile.get("async_stages_ms") or {}).items():
                self._stage_ms[stage] = self._stage_ms.get(stage, 0.0) + ms
            docs = int(profile.get("docs", 0))
            self._docs_total += docs
            if profile.get("tail_fraction") is not None:
                self._last_tail_fraction = profile["tail_fraction"]
            if self._last_record_t is not None and docs:
                dt = max(now - self._last_record_t, 1e-6)
                rate = docs / dt
                self._docs_per_s_ema = (
                    rate if self._docs_per_s_ema is None
                    else self.EMA_ALPHA * rate
                    + (1.0 - self.EMA_ALPHA) * self._docs_per_s_ema)
            self._last_record_t = now
        from ..telemetry import metrics

        metrics.counter_inc(f"es.indexing.refresh.{kind}")
        return profile

    def profiles(self, n: int | None = None) -> dict:
        """The recorded refreshes, oldest first (GET /_refresh/profile)."""
        with self._lock:
            profs = list(self._ring)
            total = self._seq
        if n is not None:
            profs = profs[-max(int(n), 0):]
        return {
            "capacity": self._ring.maxlen,
            "recorded_total": total,
            "retained": len(profs),
            "profiles": profs,
        }

    def indexing_stats(self) -> dict:
        with self._lock:
            return {
                "refresh_total": sum(self._counts.values()),
                "refresh_kinds": dict(self._counts),
                "merge_total": (self._counts.get("merge", 0)
                                + self._counts.get("segment_merge", 0)),
                "stage_ms": {k: round(v, 3)
                             for k, v in sorted(self._stage_ms.items())},
                "docs_refreshed_total": self._docs_total,
                "docs_per_s_ema": (round(self._docs_per_s_ema, 3)
                                   if self._docs_per_s_ema is not None
                                   else None),
                "tail_fraction": self._last_tail_fraction,
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._counts.clear()
            self._stage_ms.clear()
            self._docs_total = 0
            self._last_record_t = None
            self._docs_per_s_ema = None
            self._last_tail_fraction = 0.0


# standalone EsIndex instances (no owning Engine — the unit-test and
# library-embedding path) record here; Engine-owned indices record into
# their engine's own recorder so in-process multi-node fixtures never
# mix nodes' write paths
_default_recorder = RefreshRecorder()


def default_recorder() -> RefreshRecorder:
    return _default_recorder


def recorder_for(index) -> RefreshRecorder:
    eng = getattr(index, "engine", None)
    if eng is not None:
        try:
            return eng.refresh_recorder
        except Exception:  # noqa: BLE001 - recorder must never fail refresh
            pass
    return _default_recorder


@contextmanager
def profile_refresh(index, kind: str):
    """Wrap one refresh/merge of `index`: activates the stage collector,
    and on exit assembles the RefreshProfile (stage sums == wall by
    construction) with the resulting tier state and records it. Never
    raises past the refresh itself."""
    from ..telemetry import current_node_name

    with collect_build_stages() as c:
        yield c
    try:
        wall_s, stages = c.finish()
        tiers = index.tier_stats()
        if kind == "incremental" or kind == "segment_merge":
            # incremental packs the new docs; a segment fold (PR 15)
            # reprocesses exactly the tail union — never the base
            docs = tiers["tail_docs"]
        else:  # full rebuild / major merge processes every visible doc
            docs = tiers["base_docs"] + tiers["tail_docs"]
        profile = {
            "@timestamp": _iso_utc(),
            "node": current_node_name(),
            "index": index.name,
            "kind": kind,
            "docs": int(docs),
            "bytes": int(getattr(index, "_base_nbytes", 0)),
            "stages_ms": {k: round(v * 1000, 4) for k, v in stages.items()},
            "wall_ms": round(wall_s * 1000, 4),
            "tail_fraction": tiers["tail_fraction"],
            "tiers": {"base_docs": tiers["base_docs"],
                      "tail_docs": tiers["tail_docs"],
                      "segments": tiers.get("segments", 0)},
        }
        with c._elock:
            events = list(c.events)
            async_events = list(c.async_events)
            async_stages = dict(c.async_stages)
        profile["stage_events_ms"] = (
            [[name, round(s * 1000, 3), round(e * 1000, 3), "main"]
             for name, s, e in events]
            + [[name, round(s * 1000, 3), round(e * 1000, 3), "worker"]
               for name, s, e in async_events])
        if async_stages:
            # worker-thread time (analyze overlap pipeline): outside the
            # flat-sum stages by construction, folded into the
            # recorder's cumulative stage accounting by record()
            profile["async_stages_ms"] = {
                k: round(v * 1000, 4) for k, v in async_stages.items()}
            # overlap evidence as one scalar: worker span time that ran
            # concurrently with main-thread stage work (main spans
            # union-merged first — nesting must not double count)
            merged: list[list[float]] = []
            for s, e in sorted((s, e) for _n, s, e in events):
                if merged and s <= merged[-1][1]:
                    merged[-1][1] = max(merged[-1][1], e)
                else:
                    merged.append([s, e])
            ov = 0.0
            for _n, a0, a1 in async_events:
                for m0, m1 in merged:
                    ov += max(0.0, min(a1, m1) - max(a0, m0))
            profile["analyze_overlap_ms"] = round(ov * 1000, 4)
        recorder_for(index).record(profile)
    except Exception:  # noqa: BLE001 - profiling must never fail a refresh
        pass
