"""MonitoringService: the node dogfoods its own TSDB.

Parity target: x-pack/plugin/monitoring/.../MonitoringService.java — an
interval scheduler runs the collectors and hands their documents to the
local exporter, which writes `.monitoring-es-<version>-<date>` indices;
CleanerService prunes indices older than the retention window
(xpack.monitoring.history.duration). Here the exporter writes through
the node's OWN engine (the documents land in hidden time_series-mode
indices, so the cluster's history is queryable through the normal
search / date_histogram / ES|QL surface), and on a replicated cluster
node the exporter posts the bulk through the gateway instead, so the
docs ride the replicated op log and every replica holds every node's
history (cluster/http.py wires that exporter).

The collection thread is a daemon with jittered-free fixed sleep; all
engine access happens through the same public calls REST handlers use.
"""

from __future__ import annotations

import threading
import time

from ..telemetry import log, metrics
from .collectors import collect_all, monitoring_index_body

MONITORING_PREFIX = ".monitoring-es-8-"


def monitoring_index_name(ts: float | None = None) -> str:
    """Daily index: .monitoring-es-8-YYYY.MM.DD (UTC)."""
    t = time.time() if ts is None else ts
    return MONITORING_PREFIX + time.strftime("%Y.%m.%d", time.gmtime(t))


# date-suffixed hidden indices the CleanerService owns: the monitoring
# TSDB, the watcher's execution history (xpack/watcher.py), and the
# serving-wave flight-recorder dumps (serving/service.py, PR 12) all age
# out on the same xpack.monitoring.history.duration window
_DATED_PREFIXES = (MONITORING_PREFIX, ".watcher-history-8-",
                   ".flight-recorder-")


def _index_date(name: str):
    """-> epoch seconds of the index's UTC date, or None if not a
    dated monitoring/watcher-history index name."""
    for prefix in _DATED_PREFIXES:
        if not name.startswith(prefix):
            continue
        try:
            import calendar

            st = time.strptime(name[len(prefix):], "%Y.%m.%d")
            return calendar.timegm(st)
        except ValueError:
            return None
    return None


class MonitoringService:
    """Per-node collection scheduler + exporter + retention cleaner.

    `exporter(index_name, docs)` defaults to writing the node's own
    engine; `pruner(index_names)` defaults to deleting through it. A
    cluster gateway overrides both so writes replicate (cluster/http)."""

    def __init__(self, engine, node_name: str | None = None,
                 exporter=None, pruner=None):
        self.engine = engine
        self.node_name = node_name or engine.tasks.node
        self.exporter = exporter
        self.pruner = pruner
        # when set (rest/app.make_app wires the engine worker pool's
        # submit), every engine-touching step of a tick runs serialized
        # with REST traffic instead of racing it from this thread. The
        # EXPORTER deliberately runs outside it: a cluster exporter posts
        # through the gateway, whose op application needs the worker —
        # running both on one single-thread pool would deadlock.
        self.submit = None
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stop = False
        self._lock = threading.Lock()
        self.collections_total = 0
        self.documents_written = 0
        self.last_collection_ms: float | None = None
        self.last_error: str | None = None

    # -- settings ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self.engine.settings.get(
            "xpack.monitoring.collection.enabled"))

    def interval_seconds(self) -> float:
        from ..utils.durations import parse_duration_seconds

        raw = self.engine.settings.get("xpack.monitoring.collection.interval")
        sec = parse_duration_seconds(raw, 10.0)
        return max(sec if sec is not None else 10.0, 0.1)

    def retention_seconds(self) -> float:
        from ..utils.durations import parse_duration_seconds

        raw = self.engine.settings.get("xpack.monitoring.history.duration")
        sec = parse_duration_seconds(raw, 7 * 86400.0)
        return sec if sec is not None else 7 * 86400.0

    def set_enabled(self, value) -> None:
        """Dynamic-setting consumer: start/stop the collection thread."""
        if value:
            self.start()
        else:
            self.stop()

    def set_interval(self, _value) -> None:
        """Dynamic-setting consumer: wake the loop so the new interval
        takes effect immediately instead of after one stale sleep."""
        self._wake.set()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._wake.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"monitoring-{self.node_name}")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._wake.set()
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            self._thread = None

    def _loop(self) -> None:
        while True:
            if self._stop or not self.enabled:
                return
            try:
                self.collect_once()
            except Exception as e:  # noqa: BLE001 - keep collecting
                self.last_error = f"{type(e).__name__}: {e}"
                metrics.counter_inc("es.monitoring.collection_errors")
                log.debug("monitoring collection failed: %s", e)
            self._wake.wait(self.interval_seconds())
            self._wake.clear()

    # -- one tick ----------------------------------------------------------

    def _serialized(self, fn):
        if self.submit is None:
            return fn()
        return self.submit(fn).result(timeout=120)

    def collect_once(self) -> int:
        """Run every collector, export the documents, prune expired
        indices. -> number of documents written. Callable directly (the
        tests and `POST /_monitoring/_collect` use it synchronously).
        Must NOT be invoked from the engine worker itself when `submit`
        is wired (the serialized steps would self-deadlock)."""
        t0 = time.perf_counter()
        docs = self._serialized(
            lambda: collect_all(self.engine, self.node_name))
        index_name = monitoring_index_name()
        if self.exporter is not None:
            self.exporter(index_name, docs)
        else:
            self._serialized(
                lambda: self._export_local(index_name, docs))
        self.prune()
        self.collections_total += 1
        self.documents_written += len(docs)
        self.last_collection_ms = round(
            (time.perf_counter() - t0) * 1000, 3)
        metrics.counter_inc("es.monitoring.collections")
        metrics.counter_inc("es.monitoring.documents", len(docs))
        return len(docs)

    def _export_local(self, index_name: str, docs: list[dict]) -> None:
        """Default exporter: the node's own engine. The index is created
        hidden + time_series on first use; (_tsid, @timestamp) ids make
        re-export idempotent."""
        eng = self.engine
        if index_name not in eng.indices:
            body = monitoring_index_body()
            settings = {k: v for k, v in body["settings"]["index"].items()}
            eng.create_index(index_name, mappings=body["mappings"],
                             settings=settings)
        idx = eng.indices[index_name]
        for doc in docs:
            idx.index_doc(None, doc)
        idx.refresh()

    # -- retention ---------------------------------------------------------

    def prune(self) -> list[str]:
        """Delete .monitoring-es-* indices whose UTC date fell out of the
        retention window (ILM-style age deletion; the reference's
        CleanerService). Today's index is never deleted regardless of a
        tiny retention (the window floors at one day boundary)."""
        cutoff = time.time() - self.retention_seconds()
        # profiler trace dirs age out on their own xpack.profiling
        # retention window (only when the service was ever built — a
        # prune must not instantiate it)
        prof = getattr(self.engine, "_profiler", None)
        if prof is not None:
            try:
                prof.prune()
            except Exception:  # noqa: BLE001 - pruning must keep going
                pass
        expired = []
        for name in list(self.engine.indices):
            d = _index_date(name)
            # an index covers its whole UTC day: expire only when the END
            # of its day predates the cutoff
            if d is not None and d + 86400.0 < cutoff:
                expired.append(name)
        if not expired:
            return []
        if self.pruner is not None:
            self.pruner(expired)
        else:
            def _delete():
                for name in expired:
                    try:
                        self.engine.delete_index(name)
                    except Exception:  # noqa: BLE001 - raced deletion
                        continue

            self._serialized(_delete)
        metrics.counter_inc("es.monitoring.indices_pruned", len(expired))
        return expired

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval": self.engine.settings.get(
                "xpack.monitoring.collection.interval"),
            "running": self._thread is not None and self._thread.is_alive(),
            "collections_total": self.collections_total,
            "documents_written": self.documents_written,
            "last_collection_ms": self.last_collection_ms,
            "last_error": self.last_error,
            "indices": sorted(n for n in self.engine.indices
                              if n.startswith(MONITORING_PREFIX)),
        }


# ---------------------------------------------------------------------------
# prebuilt ML self-watch job
# ---------------------------------------------------------------------------

SELF_WATCH_JOB_ID = "monitoring-node-latency"


def setup_self_watch_job(engine, bucket_span: str = "15m",
                         open_job: bool = False) -> dict:
    """Create (idempotently) the prebuilt anomaly job watching the
    engine's OWN search latency through its monitoring history: a
    high_mean detector over node_stats.indices.search.query_time_in_millis
    partitioned by node, fed by a datafeed over .monitoring-es-* — the
    reference ships the same idea as its preconfigured ML modules. The
    engine literally watches itself for latency regressions."""
    ml = engine.ml
    existing = engine.meta.extras.get("ml_jobs", {})
    created = SELF_WATCH_JOB_ID not in existing
    if created:
        ml.put_job(SELF_WATCH_JOB_ID, {
            "description": "self-monitoring: node search latency",
            "analysis_config": {
                "bucket_span": bucket_span,
                "detectors": [{
                    "function": "high_mean",
                    "field_name":
                        "node_stats.indices.search.query_time_in_millis",
                    "partition_field_name": "node",
                }],
            },
            "data_description": {"time_field": "@timestamp"},
        })
        ml.put_datafeed(f"datafeed-{SELF_WATCH_JOB_ID}", {
            "job_id": SELF_WATCH_JOB_ID,
            "indices": [MONITORING_PREFIX + "*"],
            "query": {"bool": {"filter": [
                {"term": {"type": "node_stats"}}]}},
        })
    if open_job:
        ml.open_job(SELF_WATCH_JOB_ID)
    return {"job_id": SELF_WATCH_JOB_ID, "created": created,
            "datafeed_id": f"datafeed-{SELF_WATCH_JOB_ID}"}
