"""SLO engine: declarative objectives over the node's own measured signals.

The closed loop the self-monitoring pipeline was missing: PR 4-5 gave
the node p99 latency histograms, per-kernel MFU/bandwidth from the
analytic cost model, serving queue/shed accounting, breaker state and
HBM gauges — this module turns them into machine-checked objectives,
evaluated on the monitoring collector interval, each materialized as
both a `_health_report` indicator (xpack/health.py) and the prebuilt
`slo-compliance` watch (xpack/watcher.py), so a p99 regression or an
MFU collapse fires an alert instead of waiting for a human to read
`.monitoring-es-*`. The kernel floors make the BENCH_NOTES roofline
claims standing invariants: a perf PR that silently drops a kernel
below its recorded floor flips the kernel-utilization indicator.

Objectives are registered via DYNAMIC settings (slo.*): thresholds
change on a live node, no restart. `slo.kernel.floors` is a JSON object
mapping kernel-name patterns to floors, e.g.
`{"fused.*": {"mfu": 0.01}, "ann.gather_scan": {"bw_util": 0.2}}`;
`slo.custom` is a JSON list of ad-hoc objectives over the metrics
snapshot: `[{"id": "...", "path": "histograms.es.rest.request.ms.p99",
"max": 500}]` (greedy dotted-path resolution — metric names contain
dots)."""

from __future__ import annotations

import fnmatch
import json
import time

from ..telemetry import metrics

STATUS_CODES = {"green": 0, "yellow": 1, "red": 2}


def _default_breach_profile_ms() -> int:
    """Breach-capture trace window of the PREBUILT watch: 200ms on a
    real device, 0 (flight-recorder dump only) on the CPU backend —
    see the capture_diagnostics comment in ensure_prebuilt_watch."""
    try:
        import jax

        return 200 if jax.default_backend() == "tpu" else 0
    except Exception:  # noqa: BLE001 - no backend: no trace
        return 0


def _objective(oid: str, kind: str, description: str, measured, threshold,
               breached: bool | None, direction: str) -> dict:
    status = ("no_data" if breached is None
              else "breached" if breached else "compliant")
    return {
        "id": oid, "kind": kind, "description": description,
        "measured": measured, "threshold": threshold,
        "direction": direction, "status": status,
    }


class SloEngine:
    """Evaluates every registered objective against the live registry /
    device / serving / breaker state. `evaluate()` is cheap (one metrics
    snapshot + arithmetic); `current()` serves a bounded-age cached
    evaluation to read-heavy callers (health indicators, Prometheus)."""

    def __init__(self, engine):
        self.engine = engine
        self.last_evaluation: dict | None = None
        self._last_eval_monotonic: float | None = None

    @property
    def enabled(self) -> bool:
        try:
            return bool(self.engine.settings.get("slo.enabled"))
        except Exception:  # noqa: BLE001
            return True

    def _get(self, key, default=None):
        try:
            v = self.engine.settings.get(key)
        except Exception:  # noqa: BLE001
            return default
        return default if v is None else v

    # -- evaluation ---------------------------------------------------------

    def evaluate(self) -> dict:
        snap = metrics.snapshot()
        objectives: list[dict] = []
        if self.enabled:
            objectives.extend(self._latency_objectives(snap))
            objectives.extend(self._kernel_objectives())
            objectives.extend(self._serving_objectives())
            objectives.extend(self._breaker_objectives())
            objectives.extend(self._hbm_objectives())
            objectives.extend(self._write_objectives())
            objectives.extend(self._planner_objectives())
            objectives.extend(self._tenant_objectives())
            objectives.extend(self._esql_objectives(snap))
            objectives.extend(self._custom_objectives(snap))
        breached = [o["id"] for o in objectives if o["status"] == "breached"]
        out = {
            "timestamp": int(time.time() * 1000),
            "enabled": self.enabled,
            "objective_count": len(objectives),
            "objectives": objectives,
            "breached": breached,
            "breached_count": len(breached),
            "compliant": not breached,
        }
        metrics.gauge_set("es.slo.compliant", 0 if breached else 1)
        metrics.gauge_set("es.slo.breached", len(breached))
        metrics.gauge_set("es.slo.objectives", len(objectives))
        self.last_evaluation = out
        self._last_eval_monotonic = time.monotonic()
        return out

    def current(self, max_age_s: float = 15.0) -> dict:
        """The last evaluation if it is fresh enough, else a new one."""
        if (self.last_evaluation is not None
                and self._last_eval_monotonic is not None
                and time.monotonic() - self._last_eval_monotonic <= max_age_s):
            return self.last_evaluation
        return self.evaluate()

    # -- objective families --------------------------------------------------

    def _latency_objectives(self, snap) -> list[dict]:
        out = []
        for oid, setting, hist, what in (
                ("search-p99-latency", "slo.search.p99_ms",
                 "es.rest.request.ms", "REST request"),
                ("shard-query-p99-latency", "slo.shard.p99_ms",
                 "es.shard.search.ms", "shard query")):
            thr = float(self._get(setting, 0) or 0)
            if thr <= 0:
                continue
            h = snap["histograms"].get(hist)
            measured = (round(h["p99"], 3)
                        if h and h.get("count") else None)
            out.append(_objective(
                oid, "latency",
                f"{what} p99 latency <= {thr:g}ms ({hist})",
                measured, thr,
                None if measured is None else measured > thr, "max"))
        return out

    def _kernel_objectives(self) -> list[dict]:
        raw = str(self._get("slo.kernel.floors", "") or "").strip()
        if not raw:
            return []
        try:
            floors = json.loads(raw)
        except json.JSONDecodeError:
            return [_objective("kernel-floors", "kernel",
                               "slo.kernel.floors is not valid JSON",
                               None, raw, True, "min")]
        min_calls = int(self._get("slo.kernel.min_calls", 3) or 3)
        from .device import kernel_utilization

        util = kernel_utilization()["kernels"]
        out = []
        for pattern in sorted(floors):
            spec = floors[pattern] or {}
            matched = {k: u for k, u in util.items()
                       if fnmatch.fnmatch(k, pattern)
                       and u["calls"] >= min_calls}
            for key, label in (("mfu", "MFU"), ("bw_util", "bandwidth")):
                floor = spec.get(key)
                if floor is None:
                    continue
                oid = f"kernel-{key}-floor[{pattern}]"
                if not matched:
                    out.append(_objective(
                        oid, "kernel",
                        f"{label} of kernels matching [{pattern}] >= "
                        f"{floor:g} (no dispatches yet)",
                        None, floor, None, "min"))
                    continue
                worst = min(matched, key=lambda k: matched[k][key])
                measured = matched[worst][key]
                out.append(_objective(
                    oid, "kernel",
                    f"{label} of kernel [{worst}] >= {floor:g} "
                    f"(floor over [{pattern}], cost-model measured)",
                    measured, floor, measured < floor, "min"))
        return out

    def _serving_objectives(self) -> list[dict]:
        sv = getattr(self.engine, "_serving", None)
        if sv is None:
            return []
        st = sv.stats()
        out = []
        depth = st.get("queue", {}).get("depth", 0)
        cap = max(st.get("queue", {}).get("max_depth", 1) or 1, 1)
        frac = float(self._get("slo.serving.queue_fraction", 0.95) or 0.95)
        out.append(_objective(
            "serving-queue-depth", "serving",
            f"serving queue depth <= {frac:.0%} of max_depth [{cap}]",
            round(depth / cap, 4), frac, depth / cap > frac, "max"))
        admitted = st.get("admitted", 0)
        shed = st.get("shed", 0)
        budget = float(self._get("slo.serving.shed_rate", 0.2) or 0.2)
        total = admitted + shed
        measured = round(shed / total, 4) if total else None
        out.append(_objective(
            "serving-shed-rate", "serving",
            f"serving shed rate <= {budget:.0%} of offered requests",
            measured, budget,
            None if measured is None else measured > budget, "max"))
        return out

    def _breaker_objectives(self) -> list[dict]:
        budget = float(self._get("slo.breaker.trip_budget", 1000) or 1000)
        if budget < 0:
            return []
        tripped = 0
        try:
            for b in self.engine.breakers.stats().values():
                if isinstance(b, dict):
                    tripped += int(b.get("tripped", 0))
        except Exception:  # noqa: BLE001
            return []
        return [_objective(
            "breaker-trips", "breaker",
            f"cumulative circuit-breaker trips <= {budget:g}",
            tripped, budget, tripped > budget, "max")]

    def _hbm_objectives(self) -> list[dict]:
        frac = float(self._get("slo.hbm.headroom_fraction", 0.98) or 0.98)
        if frac <= 0:
            return []
        from .device import device_memory_snapshot

        mem = device_memory_snapshot()
        limit = mem.get("bytes_limit")
        used = mem.get("bytes_in_use", mem.get("live_bytes", 0))
        measured = round(used / limit, 4) if limit else None
        return [_objective(
            "hbm-headroom", "device",
            f"HBM in use <= {frac:.0%} of the allocator limit",
            measured, frac,
            None if measured is None else measured > frac, "max")]

    def _write_objectives(self) -> list[dict]:
        """Write-path floors (PR 13): the exact-scan tail-tier fraction
        and the refresh lag of unrefreshed writes, measured from the
        live index state via Engine.indexing_stats(). A write-heavy
        tenant that outruns merging degrades BOTH the recall contract
        (tail grows) and freshness (lag grows) — these objectives make
        the degradation fire the slo-compliance watch with the breaching
        number on record instead of waiting for a recall regression."""
        tail_max = float(self._get("slo.write.tail_fraction", 0) or 0)
        lag_max = float(self._get("slo.write.refresh_lag_ms", 0) or 0)
        analyze_max = float(self._get("slo.write.analyze_fraction", 0) or 0)
        if tail_max <= 0 and lag_max <= 0 and analyze_max <= 0:
            return []
        try:
            idx_stats = self.engine.indexing_stats()
        except Exception:  # noqa: BLE001 - stats failure: no_data, not 500
            idx_stats = {}
        out = []
        if tail_max > 0:
            measured = idx_stats.get("tail_fraction")
            out.append(_objective(
                "write-tail-fraction", "write",
                f"exact-scan tail-tier doc fraction <= {tail_max:g} "
                "(precomputed base tiers keep serving the corpus)",
                measured, tail_max,
                None if measured is None else measured > tail_max, "max"))
        if lag_max > 0:
            measured = idx_stats.get("refresh_lag_ms")
            out.append(_objective(
                "write-refresh-lag", "write",
                f"oldest unrefreshed write waits <= {lag_max:g}ms for "
                "visibility",
                measured, lag_max,
                None if measured is None else measured > lag_max, "max"))
        if analyze_max > 0:
            # PR 16: share of cumulative build-stage time spent in text
            # analysis (build.analyze + the host-oracle `analyze`
            # stage). The vectorized path keeps this low; a regression
            # back to a host analyze wall breaches the floor and the
            # indexing health indicator names the dominant stage.
            stage_ms = idx_stats.get("stage_ms") or {}
            total = sum(stage_ms.values())
            an = (stage_ms.get("build.analyze", 0.0)
                  + stage_ms.get("analyze", 0.0))
            measured = round(an / total, 4) if total > 0 else None
            out.append(_objective(
                "write-analyze-fraction", "write",
                f"text analysis <= {analyze_max:g} of cumulative build "
                "stage time (vectorized ingest holds the analyze wall "
                "down)",
                measured, analyze_max,
                None if measured is None else measured > analyze_max,
                "max"))
        return out

    def _planner_objectives(self) -> list[dict]:
        """Planner residual ceiling (PR 18): the execution planner's
        routing is only as good as its cost model, so the worst
        per-kernel |predicted-vs-actual| residual EMA is a standing
        objective — drift past the ceiling names the misfitted kernel
        in the breach instead of silently misrouting waves."""
        ceiling = float(self._get("slo.planner.residual", 0) or 0)
        if ceiling <= 0:
            return []
        from ..planner import execution_planner

        worst, worst_val = execution_planner().worst_kernel()
        measured = round(worst_val, 4) if worst_val is not None else None
        return [_objective(
            "planner-residual", "planner",
            f"execution-planner |residual| EMA <= {ceiling:g} "
            + (f"(worst kernel [{worst}])" if worst
               else "(no observed dispatches yet)"),
            measured, ceiling,
            None if measured is None else measured > ceiling, "max")]

    def _tenant_objectives(self) -> list[dict]:
        """Per-tenant noisy-neighbor budgets (PR 19): every objective
        reads the exact-apportioned TenantMeter ledger, so a breach
        names the worst tenant with its real share of the shared device
        wall, not a sampled guess. All three default to 0 (disabled);
        the meter is consulted only if already built — a node serving
        no traffic never constructs it."""
        budget_ms = float(self._get("slo.tenant.device_ms_per_s", 0) or 0)
        p99_max = float(self._get("slo.tenant.queue_p99_ms", 0) or 0)
        shed_max = float(self._get("slo.tenant.shed_rate", 0) or 0)
        if budget_ms <= 0 and p99_max <= 0 and shed_max <= 0:
            return []
        meter = getattr(self.engine, "_metering", None)
        rows = meter.rows() if meter is not None else {}
        out = []

        def _worst(key):
            named = {t: r[key] for t, r in rows.items()
                     if r.get(key) is not None}
            if not named:
                return None, None
            t = max(named, key=lambda k: (named[k], k))
            return t, named[t]

        if budget_ms > 0:
            t, v = _worst("device_ms_per_s")
            out.append(_objective(
                "tenant-device-budget", "tenant",
                f"per-tenant device-ms/s burn <= {budget_ms:g}"
                + (f" (hungriest tenant [{t}])" if t
                   else " (no metered waves yet)"),
                round(v, 3) if v is not None else None, budget_ms,
                None if v is None else v > budget_ms, "max"))
        if p99_max > 0:
            t, v = _worst("queue_p99_ms")
            out.append(_objective(
                "tenant-queue-p99", "tenant",
                f"per-tenant queue-wait p99 <= {p99_max:g}ms"
                + (f" (worst tenant [{t}])" if t
                   else " (no metered waits yet)"),
                round(v, 3) if v is not None else None, p99_max,
                None if v is None else v > p99_max, "max"))
        if shed_max > 0:
            t, v = _worst("shed_rate")
            out.append(_objective(
                "tenant-shed-rate", "tenant",
                f"per-tenant shed rate <= {shed_max:.0%} of its offered "
                "requests"
                + (f" (worst tenant [{t}])" if t else ""),
                round(v, 4) if v is not None else None, shed_max,
                None if v is None else v > shed_max, "max"))
        return out

    def _esql_objectives(self, snap) -> list[dict]:
        """ESQL dataflow floors (PR 20): the per-operator profile in
        esql/profile.py gives every query an exact wall decomposition and
        a materialization-bytes high-water mark; these objectives put
        ceilings on both. Breach descriptions name the DOMINANT operator
        from the recorder's cumulative per-operator walls, so the
        slo-compliance watch and the esql_dataflow health indicator point
        at the pipe stage to fix, not just the symptom. Both default to 0
        (disabled)."""
        p99_max = float(self._get("slo.esql.p99_ms", 0) or 0)
        peak_max = float(self._get("slo.esql.peak_bytes", 0) or 0)
        if p99_max <= 0 and peak_max <= 0:
            return []
        from ..esql.profile import recorder_for

        st = recorder_for(self.engine).stats()
        dom = st.get("dominant_operator")
        dom_note = (f" (dominant operator [{dom}])" if dom
                    else " (no profiled queries yet)")
        out = []
        if p99_max > 0:
            h = snap["histograms"].get("es.esql.query_ms")
            measured = (round(h["p99"], 3)
                        if h and h.get("count") else None)
            out.append(_objective(
                "esql-p99-latency", "esql",
                f"ESQL query p99 latency <= {p99_max:g}ms" + dom_note,
                measured, p99_max,
                None if measured is None else measured > p99_max, "max"))
        if peak_max > 0:
            measured = st.get("peak_bytes_hwm") or None
            out.append(_objective(
                "esql-peak-bytes", "esql",
                f"ESQL peak live materialization <= {peak_max:g} bytes"
                + dom_note,
                measured, peak_max,
                None if measured is None else measured > peak_max, "max"))
        return out

    def _custom_objectives(self, snap) -> list[dict]:
        raw = str(self._get("slo.custom", "") or "").strip()
        if not raw:
            return []
        try:
            specs = json.loads(raw)
        except json.JSONDecodeError:
            return [_objective("custom", "custom",
                               "slo.custom is not valid JSON",
                               None, raw, True, "max")]
        from ..xpack.watcher import resolve_path

        out = []
        for i, spec in enumerate(specs if isinstance(specs, list) else []):
            oid = spec.get("id") or f"custom-{i}"
            path = spec.get("path") or spec.get("metric") or ""
            got = resolve_path(snap, path)
            measured = got if isinstance(got, (int, float)) else None
            breached = None
            thr = None
            direction = "max"
            if measured is not None and spec.get("max") is not None:
                thr = float(spec["max"])
                breached = measured > thr
            elif measured is not None and spec.get("min") is not None:
                thr, direction = float(spec["min"]), "min"
                breached = measured < thr
            out.append(_objective(
                oid, "custom",
                spec.get("description") or f"[{path}] within threshold",
                measured, thr, breached, direction))
        return out

    # -- the prebuilt watch ---------------------------------------------------

    def ensure_prebuilt_watch(self) -> dict:
        """Materialize the objectives as a watch: every SLO breach fires
        through the same alert state machine operators already watch
        (`.alerts-default` carries the slo-compliance alert; acking it
        silences the actions until compliance recovers)."""
        from ..xpack.watcher import SLO_WATCH_ID

        svc = self.engine.watcher
        if SLO_WATCH_ID in svc._watches():
            return {"watch_id": SLO_WATCH_ID, "created": False}
        interval = self._get("xpack.monitoring.collection.interval", "10s")
        svc.put(SLO_WATCH_ID, {
            "trigger": {"schedule": {"interval": interval or "10s"}},
            "input": {"slo": {}},
            "condition": {"compare": {
                "ctx.payload.breached_count": {"gt": 0}}},
            "actions": {"log_breach": {
                "logging": {"text": "SLO objectives breached"},
                "throttle_period": "1m",
            }, "capture_diagnostics": {
                # PR 12: a breach ships evidence — the serving-wave
                # flight recorder dumped to .flight-recorder-* and, on a
                # real device, a bounded jax.profiler trace of the
                # breach window. The scheduled default traces only on
                # TPU: the CPU XPlane collector in the pinned jaxlib is
                # not crash-safe under repeated captures with concurrent
                # cluster traffic (DIVERGENCES "Compiled-program
                # introspection"); a watch with an explicit profile_ms
                # still traces on any backend.
                "capture": {"flight_recorder": True,
                            "profile_ms": _default_breach_profile_ms()},
                "throttle_period": "5m",
            }},
            "metadata": {"prebuilt": True, "managed_by": "slo"},
        })
        return {"watch_id": SLO_WATCH_ID, "created": True}
