"""XLA ground truth for the analytic cost model (PR 12).

Every roofline figure the node reports (MFU / bandwidth / ICI
utilization, the SLO kernel floors, the BENCH records) divides measured
wall time into the PR-5 *analytic* FLOPs/bytes — hand-derived formulas
in monitoring/costmodel.py. A drifted formula would silently mis-grade
every perf claim. XLA already knows the truth: the lowered program of
every compiled-plan cache carries `cost_analysis()` (flops, bytes
accessed) and the compiled executable `memory_analysis()` (argument /
output / temp bytes). This module cross-checks the two at the cache
sites themselves and publishes a per-kernel **drift gauge**:

    es.costmodel.drift.<kernel>.{flops,bytes} = analytic / XLA

Capture discipline (bounded by construction — a cross-check must never
become the serving-latency regression it exists to catch):

  - `check_dispatch` is called at the compiled-plan dispatch sites
    (query/executor, parallel/sharded ``_compiled*``/``_msearch_merged``,
    ops/batched, ops/vector) with the jitted fn, its dispatch args, and
    the SAME shape fields the site feeds `telemetry.time_kernel`;
  - per (kernel, abstract-shape signature) it captures at most once, and
    per kernel at most ES_TPU_XLA_CHECK_MAX (default 3) times — after
    that every call is one dict lookup;
  - the XLA numbers come from ``fn.lower(args).compile()`` — the
    OPTIMIZED executable (post-fusion), i.e. the program that actually
    runs, plus its memory_analysis. ES_TPU_XLA_CHECK=0 disables capture
    entirely (the drift table then only reports check statuses).

Drift convention (BENCH_NOTES round 16): the analytic model counts
USEFUL work (operands read once, 2 ops/element of selection); XLA counts
EXECUTED work (padding lanes, masked selects, sort comparators, scatter
plumbing). Ratios are therefore expected BELOW 1.0 on composite
programs and near 1.0 only where one dense op dominates (the f32 matmul
scan, the standalone all-gather merge). The tracked regression signal is
drift GROWTH between records (scripts/bench_regress.py, advisory), not
|1 - ratio|; the per-kernel `tol` bands below bound the kernels whose
analytic model is exact-dominant and are asserted by tier-1 on CPU.
"""

from __future__ import annotations

import os
import threading
import time

from ..telemetry import log, metrics

# ---------------------------------------------------------------------------
# check-status registry (linted: tests/test_monitoring.py requires every
# KERNEL_COSTS entry to declare a status here — "checked" or an
# exempt-with-reason. A silent exemption fails tier-1.)
# ---------------------------------------------------------------------------

# status "checked": a check_dispatch site is wired at the kernel's
# compiled-plan cache. Optional "tol": (lo, hi) band the analytic/XLA
# flops ratio must sit in — only declared where the analytic model is
# exact-dominant (asserted on CPU by tier-1; measured values in the
# comments). "bytes_tol" likewise for the bytes ratio.
# status "exempt": no XLA cross-check, with the reason on record.
_PALLAS = ("Pallas custom call — opaque to XLA HLO cost analysis "
           "(reports zero flops for the kernel body)")
XLA_CHECKS: dict[str, dict] = {
    "compiled_plan": {"status": "checked"},
    "batched.disjunction": {"status": "checked"},
    "batched.escalation": {
        "status": "checked",
        "note": "same executable family as batched.disjunction "
                "(the rerun dispatches through the same chunk cache)"},
    "sharded.spmd_topk": {"status": "checked"},
    "sharded.exact_disjunction": {"status": "checked"},
    "sharded.impact_disjunction": {"status": "checked"},
    # measured on the 4-shard CPU mesh: flops ratio 0.52-0.71, bytes
    # 0.96-0.98 — the merge program is small enough that the analytic
    # 2-ops/element selection convention tracks XLA's sort closely
    "sharded.global_merge": {"status": "checked",
                             "tol": (0.2, 2.0), "bytes_tol": (0.5, 2.0)},
    "sharded.allgather_topk": {"status": "checked"},
    "sparse.impact_gather": {"status": "checked"},
    "sparse.impact_sum": {"status": "checked"},
    # measured: flops ratio 0.98 (one f32 dot dominates; XLA adds only
    # the top-k sort comparators) — the dense-matmul parity anchor
    "vector.knn_scan": {"status": "checked",
                        "tol": (0.5, 1.5), "bytes_tol": (0.05, 2.0)},
    "vector.knn_tiered": {
        "status": "exempt",
        "reason": "routes through the split-bf16 Pallas selection on "
                  "TPU; the XLA fallback arm is cross-checked via "
                  "vector.knn_scan"},
    "fused.pallas_scan": {"status": "exempt", "reason": _PALLAS},
    "fused.msearch": {"status": "exempt",
                      "reason": "wrapper span (inner kernels carry the "
                                "accounting and the checks)"},
    "sharded.fused_pipeline": {"status": "exempt", "reason": _PALLAS},
    "sharded.fused_allgather_topk": {
        "status": "exempt",
        "reason": _PALLAS + "; the merge half of the program is "
                  "cross-checked via sharded.global_merge"},
    "serving.wave_program": {
        "status": "exempt",
        "reason": "wave-level combined fetch spanning many per-lane "
                  "programs — each lane's own kernel is cross-checked"},
    # PR 17: the tenant-gather body is batched.disjunction over
    # lane-indexed gathers; same sort/cumsum machinery, same cost shape
    "superpack.tenant_gather": {"status": "checked"},
    "sharded.wand_pass1": {"status": "exempt",
                           "reason": "experimental flag, wall-time-only "
                                     "accounting (no cost entry)"},
    "sharded.wand_pass2": {"status": "exempt",
                           "reason": "experimental flag, wall-time-only "
                                     "accounting (no cost entry)"},
    "sparse.tail_scan": {
        "status": "exempt",
        "reason": "tail-tier scan dispatched inside the engine's tiered "
                  "merge, no caller-visible executable cache; shares the "
                  "sharded.spmd_topk model"},
    "ann.centroid_probe": {
        "status": "exempt",
        "reason": "probe matmul jitted inside ann/kernels without a "
                  "caller-visible executable cache; dense-matmul parity "
                  "is anchored by vector.knn_scan"},
    "ann.gather_scan": {"status": "exempt", "reason": _PALLAS},
    "ann.rescore": {
        "status": "exempt",
        "reason": "rescore einsum jitted inside ann/kernels; covered by "
                  "the vector.knn_scan matmul anchor"},
    "ann.tail_scan": {
        "status": "exempt",
        "reason": "exact f32 tail scan through scan_topk; same program "
                  "family as vector.knn_scan"},
    # write-path build stages (PR 13 substrate; PR 15 device port). The
    # ported stages are exempt-with-reason on a STRONGER ground than a
    # cost cross-check: each device kernel is asserted BYTE-IDENTICAL
    # to its host twin by tests/test_device_build.py, so the analytic
    # flops/bytes model describes both sides of the basis split.
    "build.kmeans": {
        "status": "exempt",
        "reason": "PR 15: one jitted Lloyd while_loop "
                  "(device_build.kmeans_device); assignment parity with "
                  "the eager loop asserted by tests; dense-matmul cost "
                  "parity anchored by vector.knn_scan"},
    "build.impact_quantize": {
        "status": "exempt",
        "reason": "one elementwise device jit "
                  "(device_build.impact_codes_device) asserted BIT-EQUAL "
                  "to the host twin by tests/test_impact.py — stronger "
                  "than a cost cross-check"},
    "build.csr_assemble": {
        "status": "exempt",
        "reason": "PR 15: jitted segment-scatter kernel "
                  "(device_build.csr_blocked_scatter_device) asserted "
                  "byte-equal to the host numpy scatter by "
                  "tests/test_device_build.py"},
    "build.norms": {
        "status": "exempt",
        "reason": "host smallfloat quantization loop (no compiled "
                  "executable)"},
    "build.ann_tiles": {
        "status": "exempt",
        "reason": "PR 15: jitted lax-sort/segment + int8 quantize "
                  "kernel (device_build.ann_tiles_device) asserted "
                  "byte-equal to the host tile loop by "
                  "tests/test_device_build.py"},
    "build.device_put": {
        "status": "exempt",
        "reason": "pure host→device transfer — no program to analyze; "
                  "bandwidth-only cost entry"},
    "build.merge": {
        "status": "exempt",
        "reason": "wrapper over a full rebuild; the inner build.* stages "
                  "carry the per-stage accounting"},
    "build.segment_merge": {
        "status": "exempt",
        "reason": "PR 15 wrapper over the tail-union rebuild (the LSM "
                  "fold); the inner build.* stages carry the per-stage "
                  "accounting"},
    "build.analyze": {
        "status": "exempt",
        "reason": "PR 16: batch tokenize+hash kernel "
                  "(device_build.analyze_hash_device) asserted "
                  "term/position/length-identical to the host analyzer "
                  "oracle by tests/test_batched_analysis.py — stronger "
                  "than a cost cross-check; the batched host basis has "
                  "no compiled executable to introspect"},
    # PR 20: the ESQL exchange dispatches — per-query inline jits with
    # no caller-visible executable cache to wire check_dispatch through
    "esql.stats_exchange": {
        "status": "exempt",
        "reason": "PR 20: per-query jit built from the pipe's agg shape "
                  "(no caller-visible executable cache); the one-hot "
                  "matmul partials share the dense-matmul parity anchor "
                  "(vector.knn_scan), and the exchange output is "
                  "asserted bit-identical to the host _run_stats "
                  "evaluator by tests/test_esql_exchange.py"},
    "esql.topn_exchange": {
        "status": "exempt",
        "reason": "PR 20: per-query jit over the encoded rank keys; the "
                  "lax.sort comparator convention is cross-checked via "
                  "sharded.global_merge, and the selection is asserted "
                  "bit-identical to the host sort+limit by "
                  "tests/test_esql_topn.py"},
}


def xla_check_status(name: str) -> dict:
    return XLA_CHECKS.get(name, {"status": "undeclared"})


# ---------------------------------------------------------------------------
# capture state
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_seen_sigs: set = set()            # (name, signature)
_capture_counts: dict[str, int] = {}
# kernel -> latest observation (survives metrics.reset(): the drift
# table in _nodes/stats / Prometheus / bench reads from here, not from
# the registry gauges alone)
OBSERVATIONS: dict[str, dict] = {}


def enabled() -> bool:
    return os.environ.get("ES_TPU_XLA_CHECK", "auto") != "0"


def _max_captures() -> int:
    try:
        return int(os.environ.get("ES_TPU_XLA_CHECK_MAX", "3"))
    except ValueError:
        return 3


def reset_for_tests() -> None:
    with _lock:
        _seen_sigs.clear()
        _capture_counts.clear()
        OBSERVATIONS.clear()


def _signature(args, kwargs) -> tuple:
    """Hashable abstract signature of the dispatch args — the same
    identity jit caches executables under (shapes + dtypes + treedef)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    descs = tuple(
        (getattr(x, "shape", None) is not None
         and (tuple(x.shape), str(getattr(x, "dtype", type(x).__name__))))
        or (type(x).__name__, str(x)[:32])
        for x in leaves
    )
    return (str(treedef), descs)


def _normalize_cost(ca) -> dict:
    """jax returns a dict (Lowered) or a list of per-partition dicts
    (Compiled); fold to one {flops, bytes}."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
    }


def _memory_dict(mem) -> dict:
    out = {}
    for attr, key in (("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes"),
                      ("alias_size_in_bytes", "alias_bytes")):
        v = getattr(mem, attr, None)
        if v is not None:
            out[key] = int(v)
    if out:
        # the executable's peak working set: everything resident at once
        out["peak_bytes"] = (out.get("argument_bytes", 0)
                             + out.get("output_bytes", 0)
                             + out.get("temp_bytes", 0))
    return out


def check_dispatch(name: str, fn, args=(), kwargs=None,
                   fields: dict | None = None) -> dict | None:
    """Cross-check one compiled-plan dispatch against XLA. Called at the
    dispatch sites with the jitted `fn` and the concrete args about to
    execute; captures (lower + compile + cost/memory analysis) at most
    once per (kernel, shape signature) and `ES_TPU_XLA_CHECK_MAX` times
    per kernel, then becomes a dict lookup. Never raises — the
    cross-check must never fail a search."""
    try:
        if not enabled():
            return None
        spec = XLA_CHECKS.get(name)
        if spec is not None and spec.get("status") == "exempt":
            return None
        with _lock:
            if _capture_counts.get(name, 0) >= _max_captures():
                return None
        sig = _signature(args, kwargs)
        with _lock:
            if (name, sig) in _seen_sigs:
                return None
            _seen_sigs.add((name, sig))
            _capture_counts[name] = _capture_counts.get(name, 0) + 1
        return _capture(name, fn, args, kwargs or {}, fields or {})
    except Exception as e:  # noqa: BLE001 - accounting never fails a search
        log.debug("xla cross-check for [%s] failed: %s", name, e)
        return None


def _capture(name: str, fn, args, kwargs, fields: dict) -> dict | None:
    from .costmodel import kernel_cost

    t0 = time.perf_counter()
    lowered = fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    xla = _normalize_cost(compiled.cost_analysis())
    mem = {}
    try:
        mem = _memory_dict(compiled.memory_analysis())
    except Exception:  # noqa: BLE001 - older backends: cost only
        mem = {}
    analytic = kernel_cost(name, fields) or {}
    obs = {
        "kernel": name,
        "xla": {"flops": xla["flops"], "bytes": xla["bytes"]},
        "analytic": {"flops": float(analytic.get("flops", 0.0)),
                     "bytes": float(analytic.get("bytes", 0.0))},
        "memory": mem,
        "fields": {k: v for k, v in fields.items()
                   if isinstance(v, (int, float, str, bool))},
        "capture_ms": round((time.perf_counter() - t0) * 1000, 3),
        "captured_unix": time.time(),
    }
    if analytic:
        obs["drift"] = {
            "flops": round(obs["analytic"]["flops"]
                           / max(xla["flops"], 1.0), 6),
            "bytes": round(obs["analytic"]["bytes"]
                           / max(xla["bytes"], 1.0), 6),
        }
        metrics.gauge_set(f"es.costmodel.drift.{name}.flops",
                          obs["drift"]["flops"])
        metrics.gauge_set(f"es.costmodel.drift.{name}.bytes",
                          obs["drift"]["bytes"])
    metrics.counter_inc("es.costmodel.xla_checks")
    with _lock:
        prev = OBSERVATIONS.get(name)
        obs["captures"] = (prev["captures"] + 1) if prev else 1
        OBSERVATIONS[name] = obs
    return obs


def check_traceable(name: str, traceable, args=(), static_kwargs=None,
                    fields: dict | None = None) -> dict | None:
    """check_dispatch for sites whose program is a plain traceable (the
    routing helper jits internally): wraps it in jax.jit first."""
    try:
        import functools

        import jax

        fn = jax.jit(functools.partial(traceable, **(static_kwargs or {})))
        return check_dispatch(name, fn, args, None, fields)
    except Exception as e:  # noqa: BLE001
        log.debug("xla cross-check for [%s] failed: %s", name, e)
        return None


def observation(name: str) -> dict | None:
    with _lock:
        return OBSERVATIONS.get(name)


def drift_table() -> dict:
    """The registry-wide cross-check table: one row per KERNEL_COSTS
    entry — check status, and for captured kernels the analytic/XLA
    flops+bytes ratios and the executable's memory analysis. Feeds
    `_nodes/stats` device.utilization, the monitoring TSDB node_stats
    docs, bench records (`xla_cost_check`), and usage_report."""
    from .costmodel import KERNEL_COSTS

    with _lock:
        obs = {k: dict(v) for k, v in OBSERVATIONS.items()}
    out = {}
    for kname in sorted(KERNEL_COSTS):
        spec = xla_check_status(kname)
        row = {"status": spec.get("status", "undeclared")}
        if spec.get("reason"):
            row["reason"] = spec["reason"]
        if spec.get("tol"):
            row["flops_tolerance"] = list(spec["tol"])
        o = obs.get(kname)
        if o is not None:
            row["captures"] = o["captures"]
            row["analytic_flops"] = o["analytic"]["flops"]
            row["xla_flops"] = o["xla"]["flops"]
            row["analytic_bytes"] = o["analytic"]["bytes"]
            row["xla_bytes"] = o["xla"]["bytes"]
            if "drift" in o:
                row["flops_ratio"] = o["drift"]["flops"]
                row["bytes_ratio"] = o["drift"]["bytes"]
            if o.get("memory"):
                row["memory"] = dict(o["memory"])
        out[kname] = row
    return out


def format_drift_table(table: dict | None = None) -> str:
    """Human-readable drift table (tier1_gate / usage_report output)."""
    table = drift_table() if table is None else table
    lines = [f"{'kernel':<32} {'status':<10} {'flops a/x':>10} "
             f"{'bytes a/x':>10}  note"]
    for kname, row in sorted(table.items()):
        fr = row.get("flops_ratio")
        br = row.get("bytes_ratio")
        note = row.get("reason", "")[:48]
        lines.append(
            f"{kname:<32} {row.get('status', '?'):<10} "
            f"{(f'{fr:.3f}' if fr is not None else '-'):>10} "
            f"{(f'{br:.3f}' if br is not None else '-'):>10}  {note}")
    return "\n".join(lines)
