"""Native (C++) host runtime: compile-on-first-use, graceful fallback.

The reference binds prebuilt native libraries through JNA/Panama FFI
(reference behavior: libs/native/.../NativeAccess.java selecting zstd, POSIX
mlockall, systemd bindings at runtime). Here the native pieces compile from
source with the system toolchain on first use and load via ctypes; every
caller must work without them (pure-Python fallback), mirroring the
reference's NoopNativeAccess degradation.

Components:
  - packing.cpp  — index accumulator hot loop (tokenize/hash/postings)
  - zstd.py      — ctypes binding to system libzstd (WAL/blob compression)
  - posix.py     — mlockall / rlimit bootstrap checks
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


class PackSizes(ctypes.Structure):
    _fields_ = [
        ("n_terms", ctypes.c_int64),
        ("term_bytes", ctypes.c_int64),
        ("n_postings", ctypes.c_int64),
        ("n_positions", ctypes.c_int64),
    ]


def _build_lib() -> ctypes.CDLL | None:
    src = os.path.join(_HERE, "packing.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(_HERE, "_build")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"packing_{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (subprocess.SubprocessError, OSError):
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.builder_new.restype = ctypes.c_void_p
    lib.builder_free.argtypes = [ctypes.c_void_p]
    lib.builder_add_text.restype = ctypes.c_int64
    lib.builder_add_text.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int32,
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
    ]
    lib.builder_add_tokens.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int32,
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.builder_add_field_len.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.builder_pack_sizes.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(PackSizes),
    ]
    lib.builder_pack_fill.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 8
    lib.builder_field_len_count.restype = ctypes.c_int64
    lib.builder_field_len_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.builder_field_len_fill.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p, ctypes.c_void_p,
    ]
    return lib


def get_lib() -> ctypes.CDLL | None:
    """The packing library, or None when the toolchain is unavailable or
    ES_TPU_NATIVE=0 disables native code."""
    global _LIB, _TRIED
    if os.environ.get("ES_TPU_NATIVE", "1") == "0":
        return None
    with _LOCK:
        if not _TRIED:
            _TRIED = True
            _LIB = _build_lib()
    return _LIB


def available() -> bool:
    return get_lib() is not None
