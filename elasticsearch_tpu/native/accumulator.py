"""Pythonic wrapper over the C++ index accumulator (packing.cpp).

One NativeAccumulator per in-flight shard pack build; owns the C++ builder
handle. Produces the flat-CSR form that index/pack.py's vectorized packer
consumes — identical to what the pure-Python fallback produces from its
dicts, so packs are bit-compatible either way.
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import PackSizes, get_lib


class NativeAccumulator:
    def __init__(self):
        self.lib = get_lib()
        if self.lib is None:
            raise RuntimeError("native packing library unavailable")
        self.h = self.lib.builder_new()
        self.field_ids: dict[str, int] = {}

    def close(self):
        if self.h is not None:
            self.lib.builder_free(self.h)
            self.h = None

    __del__ = close

    def _fid(self, fld: str) -> int:
        fid = self.field_ids.get(fld)
        if fid is None:
            fid = self.field_ids[fld] = len(self.field_ids)
        return fid

    def add_text(self, fld: str, docid: int, text: str, pos_base: int) -> int:
        """ASCII standard-analyzer fast path; -1 = non-ASCII, caller must
        fall back to add_tokens with Python-analyzed tokens."""
        raw = text.encode("ascii", errors="surrogateescape") if text.isascii() else None
        if raw is None:
            return -1
        return self.lib.builder_add_text(
            self.h, self._fid(fld), docid, raw, len(raw), pos_base, 1
        )

    def add_tokens(
        self, fld: str, docid: int, terms: list[str], positions: list[int] | None
    ):
        """Pre-tokenized path. positions[i] < 0 (or None list) skips the
        position key for that token."""
        if not terms:
            return
        n = len(terms)
        encoded = [t.encode("utf-8") for t in terms]
        buf = b"".join(encoded)
        lens = np.fromiter((len(e) for e in encoded), np.int32, count=n)
        pos = (
            np.full(n, -1, np.int64)
            if positions is None
            else np.asarray(positions, np.int64)
        )
        self.lib.builder_add_tokens(
            self.h, self._fid(fld), docid, buf,
            lens.ctypes.data_as(ctypes.c_void_p),
            pos.ctypes.data_as(ctypes.c_void_p), n,
        )

    def pack(self):
        """-> (keys, post_offsets, flat_docs, flat_tfs, pos_offsets, flat_pos)

        keys: list[(field, term)] sorted exactly like Python's
        sorted(postings.keys()); offsets are [T+1] int64 CSR directories.
        """
        names = sorted(self.field_ids)
        rank = np.zeros(max(len(self.field_ids), 1), np.uint32)
        for r, name in enumerate(names):
            rank[self.field_ids[name]] = r
        sizes = PackSizes()
        self.lib.builder_pack_sizes(
            self.h, rank.ctypes.data_as(ctypes.c_void_p), len(names),
            ctypes.byref(sizes),
        )
        T = sizes.n_terms
        term_buf = ctypes.create_string_buffer(max(sizes.term_bytes, 1))
        term_lens = np.zeros(max(T, 1), np.int32)
        term_fids = np.zeros(max(T, 1), np.uint32)
        post_offsets = np.zeros(T + 1, np.int64)
        flat_docs = np.zeros(max(sizes.n_postings, 1), np.int32)
        flat_tfs = np.zeros(max(sizes.n_postings, 1), np.float32)
        pos_offsets = np.zeros(T + 1, np.int64)
        flat_pos = np.zeros(max(sizes.n_positions, 1), np.int64)
        self.lib.builder_pack_fill(
            self.h, term_buf,
            term_lens.ctypes.data_as(ctypes.c_void_p),
            term_fids.ctypes.data_as(ctypes.c_void_p),
            post_offsets.ctypes.data_as(ctypes.c_void_p),
            flat_docs.ctypes.data_as(ctypes.c_void_p),
            flat_tfs.ctypes.data_as(ctypes.c_void_p),
            pos_offsets.ctypes.data_as(ctypes.c_void_p),
            flat_pos.ctypes.data_as(ctypes.c_void_p),
        )
        id_to_name = {v: k for k, v in self.field_ids.items()}
        keys = []
        off = 0
        raw = term_buf.raw
        for i in range(T):
            ln = int(term_lens[i])
            keys.append(
                (id_to_name[int(term_fids[i])], raw[off : off + ln].decode("utf-8"))
            )
            off += ln
        return (
            keys,
            post_offsets,
            flat_docs[: sizes.n_postings],
            flat_tfs[: sizes.n_postings],
            pos_offsets,
            flat_pos[: sizes.n_positions],
        )
