// Host-side index accumulator: the C++ hot loop of indexing.
//
// Plays the role the JVM+Lucene IndexWriter RAM buffer plays in the
// reference (reference behavior: index/engine/InternalEngine.java:1387
// feeding IndexWriter.addDocuments; the native-component inventory is
// SURVEY.md §2.2). Everything per-token — tokenization, term hashing,
// postings/position accumulation — happens here; Python/numpy handles the
// per-term vectorized packing into blocked-CSR arrays.
//
// Contract (kept bit-compatible with the pure-Python PackBuilder):
//   - ASCII fast-path tokenizer == analysis/analyzers.py StandardAnalyzer
//     for ASCII input: runs of [A-Za-z0-9] with one optional interior
//     apostrophe group, lowercased, 255-char split, stopword-free.
//   - positions keys: docid * POS_L + pos, dropped at pos >= POS_L - 64,
//     multi-value gap handled by the caller via pos_base.
//   - term sort order: (field sort rank, term bytes) — UTF-8 byte order ==
//     code-point order, matching Python's sorted(postings.keys()).
//
// Exposed as a C ABI for ctypes; all buffers are caller-allocated numpy.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>
#include <algorithm>

namespace {

constexpr int64_t POS_L = 1 << 17;
constexpr int MAX_TOKEN_LEN = 255;

struct TermEntry {
    std::vector<int32_t> docs;
    std::vector<float> tfs;
    std::vector<int64_t> pos_keys;
    void add(int32_t doc, float tf_inc) {
        if (!docs.empty() && docs.back() == doc) {
            tfs.back() += tf_inc;
        } else {
            docs.push_back(doc);
            tfs.push_back(tf_inc);
        }
    }
};

struct FieldLen {
    int32_t doc;
    int32_t len;
};

struct Builder {
    // key = field_id (4 bytes big-endian) + term bytes
    std::unordered_map<std::string, TermEntry> terms;
    std::vector<std::vector<FieldLen>> field_lens;  // per field_id
    std::string keybuf;

    TermEntry& entry(uint32_t field_id, const char* term, size_t len) {
        keybuf.resize(4 + len);
        keybuf[0] = (char)(field_id >> 24);
        keybuf[1] = (char)(field_id >> 16);
        keybuf[2] = (char)(field_id >> 8);
        keybuf[3] = (char)(field_id);
        memcpy(&keybuf[4], term, len);
        return terms[keybuf];
    }
};

inline bool is_word(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9');
}

}  // namespace

extern "C" {

void* builder_new() { return new Builder(); }

void builder_free(void* h) { delete static_cast<Builder*>(h); }

// Tokenize ASCII text with standard-analyzer semantics and accumulate
// postings + positions for (field_id, docid). pos_base offsets positions
// (multi-value gap handled by caller). record_positions == 0 skips position
// keys (fields with index_options that exclude positions).
// Returns (last_position + 1) or 0 if no tokens; -1 if non-ASCII byte seen
// (caller must fall back to Python tokenization for this value).
int64_t builder_add_text(void* h, uint32_t field_id, int32_t docid,
                         const char* text, int64_t len, int64_t pos_base,
                         int record_positions) {
    for (int64_t i = 0; i < len; i++) {
        if ((unsigned char)text[i] >= 0x80) return -1;
    }
    Builder* b = static_cast<Builder*>(h);
    char lower[MAX_TOKEN_LEN];
    int64_t pos = 0;
    int64_t i = 0;
    int64_t n_tokens = 0;
    while (i < len) {
        if (!is_word((unsigned char)text[i])) { i++; continue; }
        int64_t start = i;
        while (i < len && is_word((unsigned char)text[i])) i++;
        // one optional interior apostrophe group: 'x+ (ASCII quote only;
        // the Python regex also accepts U+2019 but that is non-ASCII input)
        if (i < len && text[i] == '\'' && i + 1 < len &&
            is_word((unsigned char)text[i + 1])) {
            i++;
            while (i < len && is_word((unsigned char)text[i])) i++;
        }
        int64_t tlen = i - start;
        // overlong tokens split at MAX_TOKEN_LEN boundaries (each piece is
        // its own token+position, matching Analyzer.analyze)
        for (int64_t off = 0; off < tlen; off += MAX_TOKEN_LEN) {
            int64_t plen = std::min<int64_t>(MAX_TOKEN_LEN, tlen - off);
            for (int64_t j2 = 0; j2 < plen; j2++) {
                char c = text[start + off + j2];
                lower[j2] = (c >= 'A' && c <= 'Z') ? c + 32 : c;
            }
            TermEntry& e = b->entry(field_id, lower, plen);
            e.add(docid, 1.0f);
            int64_t p = pos_base + pos;
            if (record_positions && p < POS_L - 64) {
                e.pos_keys.push_back((int64_t)docid * POS_L + p);
            }
            pos++;
            n_tokens++;
        }
    }
    (void)n_tokens;
    return pos;
}

// Pre-tokenized path (Python analyzer fallback / keyword terms).
// terms = concatenated UTF-8 bytes; lens[i] each term's length;
// positions[i] absolute position or -1 (skip position key); tf_inc added
// per token (keywords pass 1.0 repeatedly to accumulate multi-value tf).
void builder_add_tokens(void* h, uint32_t field_id, int32_t docid,
                        const char* terms, const int32_t* lens,
                        const int64_t* positions, int64_t n) {
    Builder* b = static_cast<Builder*>(h);
    const char* p = terms;
    for (int64_t i = 0; i < n; i++) {
        TermEntry& e = b->entry(field_id, p, lens[i]);
        e.add(docid, 1.0f);
        if (positions[i] >= 0 && positions[i] < POS_L - 64) {
            e.pos_keys.push_back((int64_t)docid * POS_L + positions[i]);
        }
        p += lens[i];
    }
}

// Record one text value's token count toward the field's doc length/norms.
void builder_add_field_len(void* h, uint32_t field_id, int32_t docid,
                           int32_t len) {
    Builder* b = static_cast<Builder*>(h);
    if (b->field_lens.size() <= field_id) b->field_lens.resize(field_id + 1);
    auto& v = b->field_lens[field_id];
    if (!v.empty() && v.back().doc == docid) {
        v.back().len += len;
    } else {
        v.push_back({docid, len});
    }
}

// ---- pack phase ----------------------------------------------------------

struct PackSizes {
    int64_t n_terms;
    int64_t term_bytes;
    int64_t n_postings;
    int64_t n_positions;
};

// Sort terms by (field_rank, term bytes) and report output sizes.
// field_rank[field_id] is the rank of the field name in Python's sort order.
// The sorted order is cached on the builder for the fill call.
struct SortedRef {
    uint32_t rank;
    const std::string* key;
    const TermEntry* entry;
};

static thread_local std::vector<SortedRef> g_sorted;

void builder_pack_sizes(void* h, const uint32_t* field_rank,
                        int64_t n_fields, PackSizes* out) {
    Builder* b = static_cast<Builder*>(h);
    g_sorted.clear();
    g_sorted.reserve(b->terms.size());
    int64_t tb = 0, np = 0, npos = 0;
    for (auto& kv : b->terms) {
        uint32_t fid = ((uint32_t)(unsigned char)kv.first[0] << 24) |
                       ((uint32_t)(unsigned char)kv.first[1] << 16) |
                       ((uint32_t)(unsigned char)kv.first[2] << 8) |
                       (uint32_t)(unsigned char)kv.first[3];
        uint32_t rank = fid < (uint32_t)n_fields ? field_rank[fid] : fid;
        g_sorted.push_back({rank, &kv.first, &kv.second});
        tb += (int64_t)kv.first.size() - 4;
        np += (int64_t)kv.second.docs.size();
        npos += (int64_t)kv.second.pos_keys.size();
    }
    std::sort(g_sorted.begin(), g_sorted.end(),
              [](const SortedRef& a, const SortedRef& c) {
                  if (a.rank != c.rank) return a.rank < c.rank;
                  // unsigned byte order: UTF-8 byte order == code-point
                  // order, matching Python's str sort (char is signed!)
                  const unsigned char* ab =
                      (const unsigned char*)a.key->data() + 4;
                  const unsigned char* cb =
                      (const unsigned char*)c.key->data() + 4;
                  return std::lexicographical_compare(
                      ab, ab + a.key->size() - 4, cb, cb + c.key->size() - 4);
              });
    out->n_terms = (int64_t)g_sorted.size();
    out->term_bytes = tb;
    out->n_postings = np;
    out->n_positions = npos;
}

// Fill caller-allocated buffers in the order computed by builder_pack_sizes.
void builder_pack_fill(void* h, char* term_buf, int32_t* term_lens,
                       uint32_t* term_fids, int64_t* post_offsets,
                       int32_t* flat_docs, float* flat_tfs,
                       int64_t* pos_offsets, int64_t* flat_pos) {
    (void)h;
    int64_t tb = 0, np = 0, npos = 0;
    int64_t t = 0;
    post_offsets[0] = 0;
    pos_offsets[0] = 0;
    for (const auto& ref : g_sorted) {
        const std::string& key = *ref.key;
        const TermEntry& e = *ref.entry;
        int64_t tl = (int64_t)key.size() - 4;
        memcpy(term_buf + tb, key.data() + 4, tl);
        tb += tl;
        term_lens[t] = (int32_t)tl;
        term_fids[t] = ((uint32_t)(unsigned char)key[0] << 24) |
                       ((uint32_t)(unsigned char)key[1] << 16) |
                       ((uint32_t)(unsigned char)key[2] << 8) |
                       (uint32_t)(unsigned char)key[3];
        memcpy(flat_docs + np, e.docs.data(), e.docs.size() * sizeof(int32_t));
        memcpy(flat_tfs + np, e.tfs.data(), e.tfs.size() * sizeof(float));
        np += (int64_t)e.docs.size();
        memcpy(flat_pos + npos, e.pos_keys.data(),
               e.pos_keys.size() * sizeof(int64_t));
        npos += (int64_t)e.pos_keys.size();
        t++;
        post_offsets[t] = np;
        pos_offsets[t] = npos;
    }
    g_sorted.clear();
    g_sorted.shrink_to_fit();
}

// Per-field doc-length export: sizes then fill.
int64_t builder_field_len_count(void* h, uint32_t field_id) {
    Builder* b = static_cast<Builder*>(h);
    if (b->field_lens.size() <= field_id) return 0;
    return (int64_t)b->field_lens[field_id].size();
}

void builder_field_len_fill(void* h, uint32_t field_id, int32_t* docs,
                            int32_t* lens) {
    Builder* b = static_cast<Builder*>(h);
    auto& v = b->field_lens[field_id];
    for (size_t i = 0; i < v.size(); i++) {
        docs[i] = v[i].doc;
        lens[i] = v[i].len;
    }
}

}  // extern "C"
