"""POSIX native access: mlockall + rlimit probes for bootstrap checks.

The reference locks process memory and validates rlimits at boot
(reference behavior: libs/native/.../PosixNativeAccess.java mlockall;
bootstrap/BootstrapChecks.java memory-lock / max-file-descriptors checks).
TPU hosts care for the same reason: the host-side pack build and WAL must
not page out under memory pressure while feeding HBM.
"""

from __future__ import annotations

import ctypes
import resource

MCL_CURRENT = 1
MCL_FUTURE = 2

_libc: ctypes.CDLL | None = None


def _lc() -> ctypes.CDLL | None:
    global _libc
    if _libc is None:
        try:
            _libc = ctypes.CDLL(None, use_errno=True)
        except OSError:
            return None
    return _libc


def mlockall() -> bool:
    """Lock all current+future pages; False (with no exception) on failure,
    matching the reference's warn-and-continue behavior."""
    lc = _lc()
    if lc is None or not hasattr(lc, "mlockall"):
        return False
    return lc.mlockall(MCL_CURRENT | MCL_FUTURE) == 0


def max_open_files() -> int:
    return resource.getrlimit(resource.RLIMIT_NOFILE)[0]


def max_address_space_unlimited() -> bool:
    return resource.getrlimit(resource.RLIMIT_AS)[0] == resource.RLIM_INFINITY


def bootstrap_checks() -> list[str]:
    """Non-fatal warnings, the analog of BootstrapChecks in dev mode."""
    warnings = []
    if max_open_files() < 65535:
        warnings.append(
            f"max file descriptors [{max_open_files()}] is low; 65535+ recommended"
        )
    if not max_address_space_unlimited():
        warnings.append("max size virtual memory is not unlimited")
    return warnings
