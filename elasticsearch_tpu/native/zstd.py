"""ctypes binding to system libzstd, zlib fallback.

The reference ships prebuilt zstd natives bound via JNA/Panama
(reference behavior: libs/native/libraries/build.gradle:21,46-51 and
libs/native/.../Zstd.java) and uses them for transport message and stored
field compression. Same role here for WAL segments and snapshot blobs.

Framed format tag byte: b'Z' + zstd frame, or b'G' + zlib stream, so either
side can decompress regardless of which codec was available at write time.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import zlib

_zstd: ctypes.CDLL | None = None
_tried = False


def _lib() -> ctypes.CDLL | None:
    global _zstd, _tried
    if not _tried:
        _tried = True
        name = ctypes.util.find_library("zstd")
        if name:
            try:
                lib = ctypes.CDLL(name)
                lib.ZSTD_compressBound.restype = ctypes.c_size_t
                lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
                lib.ZSTD_compress.restype = ctypes.c_size_t
                lib.ZSTD_compress.argtypes = [
                    ctypes.c_void_p, ctypes.c_size_t,
                    ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
                ]
                lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
                lib.ZSTD_getFrameContentSize.argtypes = [
                    ctypes.c_void_p, ctypes.c_size_t,
                ]
                lib.ZSTD_decompress.restype = ctypes.c_size_t
                lib.ZSTD_decompress.argtypes = [
                    ctypes.c_void_p, ctypes.c_size_t,
                    ctypes.c_void_p, ctypes.c_size_t,
                ]
                lib.ZSTD_isError.restype = ctypes.c_uint
                lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
                _zstd = lib
            except OSError:
                _zstd = None
    return _zstd


def zstd_available() -> bool:
    return _lib() is not None


def compress(data: bytes, level: int = 3) -> bytes:
    lib = _lib()
    if lib is None:
        return b"G" + zlib.compress(data, 6)
    bound = lib.ZSTD_compressBound(len(data))
    buf = ctypes.create_string_buffer(bound)
    n = lib.ZSTD_compress(buf, bound, data, len(data), level)
    if lib.ZSTD_isError(n):
        return b"G" + zlib.compress(data, 6)
    return b"Z" + buf.raw[:n]


def decompress(framed: bytes) -> bytes:
    if not framed:
        return b""
    tag, payload = framed[:1], framed[1:]
    if tag == b"G":
        return zlib.decompress(payload)
    if tag != b"Z":
        raise ValueError(f"unknown compression frame tag {tag!r}")
    lib = _lib()
    if lib is None:
        raise RuntimeError("zstd frame but libzstd unavailable on this host")
    size = lib.ZSTD_getFrameContentSize(payload, len(payload))
    if size in (2**64 - 1, 2**64 - 2):  # ERROR / UNKNOWN
        raise ValueError("corrupt zstd frame")
    buf = ctypes.create_string_buffer(int(size) or 1)
    n = lib.ZSTD_decompress(buf, int(size) or 1, payload, len(payload))
    if lib.ZSTD_isError(n):
        raise ValueError("zstd decompression failed")
    return buf.raw[:n]
