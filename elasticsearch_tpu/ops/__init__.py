from .scoring import bm25_idf, term_score_blocks, DEAD_SLOT_PAD

__all__ = ["bm25_idf", "term_score_blocks", "DEAD_SLOT_PAD"]
