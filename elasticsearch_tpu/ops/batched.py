"""Batched multi-query BM25 execution: the `_msearch` fast path.

The reference executes an _msearch as independent async per-shard searches
(reference behavior: action/search/TransportMultiSearchAction.java fan-out).
On TPU a batch of term-disjunction queries is a single fused program with NO
scatter anywhere (profiling: element scatter runs ~200ns/element on TPU — the
one pattern to design out):

  dense tier:  scores[Q, N] = W[Q, V_dense] @ dense_tfn[V_dense, N]   (MXU)
  sparse tail: gather CSR rows -> per-posting partial scores -> sort by
               docid -> run-sum (cummax segmented-scan trick) -> explicit
               (docid, score) candidates
  merge:       dense top-k (candidates masked out) ++ candidates -> top-k

Exactness: every sparse candidate's full score = its run-sum + the dense-tier
score gathered at its docid; a doc with only dense contributions is exact in
the matmul; duplicates between the two lists are removed by masking the dense
top-k entries that appear among candidates. Totals are exact:
|{dense match}| + |{candidates with zero dense score}|.

Constraint: all term weights must be > 0 (true for BM25: idf > 0, boost > 0),
so "matches" == "score > 0". The generic per-query path handles boost == 0.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..index.pack import BLOCK


@dataclass
class BatchPlan:
    """Host-side per-batch inputs (all fixed-shape, stackable)."""

    W: np.ndarray  # [Q, V_dense] f32 dense-tier weights (0 = term unused)
    sparse_rows: np.ndarray  # [Q, Ts, B] int32 CSR block rows (0-padded)
    sparse_weights: np.ndarray  # [Q, Ts] f32
    k: int
    dense_only: bool = False  # no sparse terms anywhere -> fused Pallas path


def batch_term_disjunction(
    dev: dict,
    plan_shapes: tuple,  # (Ts, B, k) — trace-time constants
    W: jax.Array,
    sparse_rows: jax.Array,
    sparse_weights: jax.Array,
    avgdl: float,
    num_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
    has_norms: bool = True,
):
    """-> (scores [Q,k], docids [Q,k], totals [Q]). Jit-traceable."""
    Ts, B, k = plan_shapes
    live = dev["live"]
    n = num_docs

    # ---- dense tier on the MXU ------------------------------------------
    dense = dev.get("dense_tfn")
    if dense is not None and W.shape[1] > 0:
        # HIGHEST: full-f32 MXU passes — default TPU matmul rounds through
        # bf16, which costs ~1e-4 relative score error vs the scalar path
        scores_d = jnp.matmul(W, dense, precision=jax.lax.Precision.HIGHEST)
    else:
        scores_d = jnp.zeros((W.shape[0], n), jnp.float32)
    scores_d = jnp.where(live[None, :], scores_d, 0.0)

    # ---- sparse tail: explicit candidates, no scatter -------------------
    docids = dev["post_docids"][sparse_rows]  # [Q, Ts, B, 128]
    tfs = dev["post_tfs"][sparse_rows]
    if has_norms:
        dls = dev["post_dls"][sparse_rows]
        denom = tfs + k1 * (1.0 - b + b * dls / avgdl)
    else:
        denom = tfs + k1
    part = sparse_weights[:, :, None, None] * tfs / denom  # pad lanes -> 0
    Q = docids.shape[0]
    C = Ts * B * BLOCK
    cd = docids.reshape(Q, C)
    cs = part.reshape(Q, C)
    # padding lanes carry docid == num_docs and score 0; sort pushes them last
    order = jnp.argsort(cd, axis=1)
    sd = jnp.take_along_axis(cd, order, axis=1)
    sv = jnp.take_along_axis(cs, order, axis=1)
    # run sums: csum - (csum just before this run's start), run start base
    # propagated forward by cummax (csum - sv is non-decreasing: sv >= 0)
    csum = jnp.cumsum(sv, axis=1)
    col = jnp.arange(C)
    starts = jnp.where(col[None, :] == 0, True, sd != jnp.roll(sd, 1, axis=1))
    base = jnp.where(starts, csum - sv, -jnp.inf)
    run_base = jax.lax.cummax(base, axis=1)
    run_sum = csum - run_base
    is_end = jnp.where(col[None, :] == C - 1, True, sd != jnp.roll(sd, -1, axis=1))
    live_c = live[jnp.minimum(sd, n - 1)] & (sd < n)
    valid_end = is_end & live_c
    # full candidate score = sparse run sum + dense score at that doc
    dg = jnp.take_along_axis(scores_d, jnp.minimum(sd, n - 1), axis=1)
    cand = jnp.where(valid_end, run_sum + dg, -jnp.inf)

    # ---- merge ----------------------------------------------------------
    masked_d = jnp.where(live[None, :] & (scores_d > 0), scores_d, -jnp.inf)
    dv, di = jax.lax.top_k(masked_d, k)  # [Q, k]
    dup = (di[:, :, None] == sd[:, None, :]) & valid_end[:, None, :]
    dv = jnp.where(dup.any(-1), -jnp.inf, dv)
    all_v = jnp.concatenate([cand, dv], axis=1)
    all_i = jnp.concatenate([sd, di], axis=1)
    # exact (score desc, docid asc) order across both lists: non-negative IEEE
    # f32 bit patterns sort like values as int32 (and -inf sorts below all),
    # so pack [score_bits | ~docid] into one int64 rank key
    score_bits = jax.lax.bitcast_convert_type(all_v, jnp.int32).astype(jnp.int64)
    rank = (score_bits << 32) + (jnp.int64(0xFFFFFFFF) - all_i.astype(jnp.int64))
    _, fidx = jax.lax.top_k(rank, k)
    fv = jnp.take_along_axis(all_v, fidx, axis=1)
    fids = jnp.take_along_axis(all_i, fidx, axis=1)

    totals = (masked_d > 0).sum(axis=1) + (valid_end & (dg <= 0) & (run_sum > 0)).sum(axis=1)
    return fv, fids, totals.astype(jnp.int32)


class BatchTermSearcher:
    """Compiled-plan cache for batched term-disjunction queries against one
    ShardSearcher's device pack."""

    def __init__(self, searcher):
        self.searcher = searcher
        self._cache = {}

    def _compiled(self, key):
        fn = self._cache.get(key)
        if fn is None:
            Ts, B, k, fld = key
            pack = self.searcher.pack
            fn = jax.jit(
                lambda dev, W, sr, sw: batch_term_disjunction(
                    dev,
                    (Ts, B, k),
                    W,
                    sr,
                    sw,
                    avgdl=pack.avgdl(fld),
                    num_docs=pack.num_docs,
                    has_norms=fld in self.searcher.ctx.has_norms,
                )
            )
            self._cache[key] = fn
        return fn

    def plan(self, fld: str, queries: list[list[tuple[str, float]]], k: int) -> BatchPlan:
        """queries: per query a list of (term, boost) on field `fld`."""
        from .scoring import bm25_idf

        pack = self.searcher.pack
        k = min(max(k, 1), max(pack.num_docs, 1))
        V = pack.dense_tfn.shape[0] if pack.dense_tfn is not None else 0
        Q = len(queries)
        doc_count = pack.field_stats.get(fld, {}).get("doc_count") or pack.num_docs
        max_ts, max_b = 1, 1
        parsed = []
        for terms in queries:
            dense, sparse = [], []
            for term, boost in terms:
                w = 0.0
                s0, nb, df = pack.term_blocks(fld, term)
                if df > 0:
                    w = boost * bm25_idf(doc_count, df)
                dr = pack.dense_row_of(fld, term)
                if dr is not None:
                    dense.append((dr, w))
                elif nb > 0:
                    sparse.append((s0, nb, w))
                    max_b = max(max_b, nb)
            max_ts = max(max_ts, len(sparse))
            parsed.append((dense, sparse))
        B = 1 << (max_b - 1).bit_length()
        W = np.zeros((Q, V), np.float32)
        rows = np.zeros((Q, max_ts, B), np.int32)
        ws = np.zeros((Q, max_ts), np.float32)
        for qi, (dense, sparse) in enumerate(parsed):
            for dr, w in dense:
                W[qi, dr] += w
            for ti, (s0, nb, w) in enumerate(sparse):
                rows[qi, ti, :nb] = np.arange(s0, s0 + nb)
                ws[qi, ti] = w
        dense_only = V > 0 and all(not sparse for _, sparse in parsed)
        return BatchPlan(W, rows, ws, k, dense_only)

    def run(self, fld: str, plan: BatchPlan):
        """-> (scores [Q,k], docids [Q,k], totals [Q]) on device (async)."""
        if plan.dense_only:
            # whole batch lives in the dense tier: fused Pallas scan+topk —
            # scores never leave VMEM (ops/kernels.py)
            from .kernels import scan_topk

            dev = self.searcher.dev
            return scan_topk(
                jnp.asarray(plan.W), dev["dense_tfn"], dev["live"], plan.k
            )
        fn = self._compiled(
            (plan.sparse_rows.shape[1], plan.sparse_rows.shape[2], plan.k, fld)
        )
        return fn(
            self.searcher.dev,
            jnp.asarray(plan.W),
            jnp.asarray(plan.sparse_rows),
            jnp.asarray(plan.sparse_weights),
        )

    def search(self, fld: str, queries: list[list[tuple[str, float]]], k: int = 10):
        return jax.device_get(self.run(fld, self.plan(fld, queries, k)))
