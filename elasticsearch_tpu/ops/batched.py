"""Batched multi-query BM25 execution: the `_msearch` fast path.

The reference executes an _msearch as independent async per-shard searches
(reference behavior: action/search/TransportMultiSearchAction.java fan-out).
On TPU a batch of term-disjunction queries is a single fused program with NO
scatter anywhere (profiling: element scatter runs ~200ns/element on TPU — the
one pattern to design out):

  dense tier:  scores[Q, N] = W[Q, V_dense] @ dense_tfn[V_dense, N]   (MXU)
  sparse tail: gather CSR rows -> per-posting partial scores -> sort by
               docid -> run-sum (cummax segmented-scan trick) -> explicit
               (docid, score) candidates
  merge:       dense top-k (candidates masked out) ++ candidates -> top-k

Exactness: every sparse candidate's full score = its run-sum + the dense-tier
score gathered at its docid; a doc with only dense contributions is exact in
the matmul; duplicates between the two lists are removed by masking the dense
top-k entries that appear among candidates. Totals are exact:
|{dense match}| + |{candidates with zero dense score}|.

Constraint: all term weights must be > 0 (true for BM25: idf > 0, boost > 0),
so "matches" == "score > 0". The generic per-query path handles boost == 0.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..index.pack import BLOCK


@dataclass
class BatchPlan:
    """Host-side per-batch inputs (all fixed-shape, stackable)."""

    W: np.ndarray  # [Q, V_dense] f32 dense-tier weights (0 = term unused)
    sparse_rows: np.ndarray  # [Q, Ts, B] int32 CSR block rows (0-padded)
    sparse_weights: np.ndarray  # [Q, Ts] f32
    k: int
    dense_only: bool = False  # no sparse terms anywhere -> fused Pallas path
    # per-query dense (tier row, weight) pairs [Q, Td] (0-padded): the
    # sparse view of W, for the tiered path's canonical f32 rescore
    dense_rows: np.ndarray | None = None
    dense_w: np.ndarray | None = None
    # impact tier (BM25S): per-sparse-term dequant weights
    # boost·idf·ubf/qmax [Q, Ts]; None when the pack carries no impact
    # tier (the raw-postings BM25 arms are the only option then)
    impact_w: np.ndarray | None = None


def batch_term_disjunction(
    dev: dict,
    plan_shapes: tuple,  # (Ts, B, k) — trace-time constants
    W: jax.Array,
    sparse_rows: jax.Array,
    sparse_weights: jax.Array,
    avgdl: float,
    num_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
    has_norms: bool = True,
    impact_w: jax.Array | None = None,
):
    """-> (scores [Q,k], docids [Q,k], totals [Q]). Jit-traceable.

    With `impact_w` ([Q, Ts] dequant weights) the sparse tail scores from
    the quantized impact tier (dev["impact_codes"]) instead of the raw
    tf/dl postings — a pure gather+multiply, no BM25 math; everything
    downstream (candidate machinery, totals, merge order) is identical.

    GSPMD contract (PR 10, relaxed PR 11): this function is also the
    vmapped per-shard body of the pjit sharded msearch program
    (`parallel/sharded._msearch_merged`), where XLA's SPMD partitioner
    shards it over the mesh — keep it pure XLA so that stays true. A
    body that needs Pallas/custom calls is no longer locked out of the
    one-program route: it rides an embedded shard_map manual region
    instead (`parallel/spmd.manual_shard_region`, the fused arm's PR-11
    path) — manual regions never ask the partitioner to split anything."""
    Ts, B, k = plan_shapes
    live = dev["live"]
    n = num_docs

    # ---- dense tier on the MXU ------------------------------------------
    dense = dev.get("dense_tfn")
    if dense is not None and W.shape[1] > 0:
        # HIGHEST: full-f32 MXU passes — default TPU matmul rounds through
        # bf16, which costs ~1e-4 relative score error vs the scalar path
        scores_d = jnp.matmul(W, dense, precision=jax.lax.Precision.HIGHEST)
    else:
        scores_d = jnp.zeros((W.shape[0], n), jnp.float32)
    scores_d = jnp.where(live[None, :], scores_d, 0.0)

    # ---- sparse tail: explicit candidates, no scatter -------------------
    docids = dev["post_docids"][sparse_rows]  # [Q, Ts, B, 128]
    if impact_w is not None:
        codes = dev["impact_codes"][sparse_rows].astype(jnp.float32)
        part = impact_w[:, :, None, None] * codes  # pad lanes -> 0
    else:
        tfs = dev["post_tfs"][sparse_rows]
        if has_norms:
            dls = dev["post_dls"][sparse_rows]
            denom = tfs + k1 * (1.0 - b + b * dls / avgdl)
        else:
            denom = tfs + k1
        part = sparse_weights[:, :, None, None] * tfs / denom  # pad -> 0
    Q = docids.shape[0]
    C = Ts * B * BLOCK
    cd = docids.reshape(Q, C)
    cs = part.reshape(Q, C)
    # padding lanes carry docid == num_docs and score 0; sort pushes them
    # last. Multi-operand sort, not argsort + take_along_axis: the take is
    # a per-element gather (~30ns/element on TPU), measured 5x slower.
    sd, sv = jax.lax.sort((cd, cs), dimension=1, num_keys=1)
    # run sums: csum - (csum just before this run's start), run start base
    # propagated forward by cummax (csum - sv is non-decreasing: sv >= 0).
    # f64 prefix sums: a f32 cumsum carries O(prefix/value * 2^-24) noise
    # (~1e-4 relative at C=8k), enough to randomly split docs whose true
    # scores tie — this path is the accuracy reference, so it pays for
    # (slow, emulated) f64 to keep per-doc sums exact to f32 ulps.
    sv64 = sv.astype(jnp.float64)
    csum = jnp.cumsum(sv64, axis=1)
    col = jnp.arange(C)
    starts = jnp.where(col[None, :] == 0, True, sd != jnp.roll(sd, 1, axis=1))
    base = jnp.where(starts, csum - sv64, -jnp.inf)
    run_base = jax.lax.cummax(base, axis=1)
    run_sum = (csum - run_base).astype(jnp.float32)
    is_end = jnp.where(col[None, :] == C - 1, True, sd != jnp.roll(sd, -1, axis=1))
    live_c = live[jnp.minimum(sd, n - 1)] & (sd < n)
    valid_end = is_end & live_c
    # full candidate score = sparse run sum + dense score at that doc
    dg = jnp.take_along_axis(scores_d, jnp.minimum(sd, n - 1), axis=1)
    cand = jnp.where(valid_end, run_sum + dg, -jnp.inf)

    # ---- merge ----------------------------------------------------------
    masked_d = jnp.where(live[None, :] & (scores_d > 0), scores_d, -jnp.inf)
    dv, di = jax.lax.top_k(masked_d, k)  # [Q, k]
    dup = (di[:, :, None] == sd[:, None, :]) & valid_end[:, None, :]
    dv = jnp.where(dup.any(-1), -jnp.inf, dv)
    all_v = jnp.concatenate([cand, dv], axis=1)
    all_i = jnp.concatenate([sd, di], axis=1)
    # exact (score desc, docid asc) order across both lists: non-negative IEEE
    # f32 bit patterns sort like values as int32 (and -inf sorts below all),
    # so pack [score_bits | ~docid] into one int64 rank key
    score_bits = jax.lax.bitcast_convert_type(all_v, jnp.int32).astype(jnp.int64)
    rank = (score_bits << 32) + (jnp.int64(0xFFFFFFFF) - all_i.astype(jnp.int64))
    _, fidx = jax.lax.top_k(rank, k)
    fv = jnp.take_along_axis(all_v, fidx, axis=1)
    fids = jnp.take_along_axis(all_i, fidx, axis=1)

    totals = (masked_d > 0).sum(axis=1) + (valid_end & (dg <= 0) & (run_sum > 0)).sum(axis=1)
    return fv, fids, totals.astype(jnp.int32)


def batch_term_disjunction_fast(
    dev: dict,
    extras: dict,  # fast-path device arrays (see BatchTermSearcher._fast_extras)
    plan_shapes: tuple,  # (Ts, B, k, M) — trace-time constants
    W: jax.Array,
    sparse_rows: jax.Array,
    sparse_weights: jax.Array,
    avgdl: float,
    num_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
    has_norms: bool = True,
    bf16: bool = False,
):
    """Throughput-oriented mixed dense+sparse scoring for large shards.

    The exact path (batch_term_disjunction) gathers dense scores at EVERY
    sparse candidate — a [Q, Ts*B*128] element gather from [Q, N] that runs at
    ~30ns/element on TPU (the one pathological op class on this hardware,
    measured: 247ms for 8.4M elements). This path cuts candidates to the
    per-query top-M by sparse run-sum before the gather, with an on-device
    proof obligation that the cut did not change the top-k:

        dropped_best[q] + ub_dense[q] < kth_score[q]

    where ub_dense is the query's dense-tier score upper bound (sum of
    weight * per-row max tf/(tf+K)). `exact[q]` reports the proof; callers
    re-run the exact path for the (rare) failing queries.

    Totals follow the reference's default `track_total_hits=10000` contract
    (reference behavior: search/internal/ContextIndexSearcher.java hit-count
    thresholds; TotalHits.Relation GREATER_THAN_OR_EQUAL_TO): `totals_lb` is
    an exact count of dense-tier matches plus kept sparse-only candidates — a
    lower bound that is exact whenever no candidates were cut (C <= M).

    With bf16=True the dense tier matmul runs natively on the MXU in
    bfloat16 with f32 accumulation. The resulting <=0.2% score perturbation
    is below the reference's own 1-byte norm quantization noise
    (index/smallfloat.py; reference SmallFloat.intToByte4), and the top-k
    proof above is evaluated on the perturbed scores, so claimed-exact
    results are exact *for the bf16 score function*.

    -> (scores [Q,k], docids [Q,k], totals_lb [Q], exact [Q] bool,
        dropped [Q] i32) — true total is within [totals_lb, totals_lb +
    dropped]; dropped == 0 means totals_lb is exact.
    """
    Ts, B, k, M = plan_shapes

    # ---- sparse tail ----------------------------------------------------
    docids = dev["post_docids"][sparse_rows]  # [Q, Ts, B, 128]
    tfs = dev["post_tfs"][sparse_rows]
    if has_norms:
        dls = dev["post_dls"][sparse_rows]
        denom = tfs + k1 * (1.0 - b + b * dls / avgdl)
    else:
        denom = tfs + k1
    part = sparse_weights[:, :, None, None] * tfs / denom
    Q = docids.shape[0]
    C = Ts * B * BLOCK
    cd = docids.reshape(Q, C)
    cs = part.reshape(Q, C)
    return fast_topk_from_candidates(
        dev, extras, (k, M), W, cd, cs, num_docs=num_docs, bf16=bf16)


def fast_topk_from_candidates(
    dev: dict,
    extras: dict,
    plan_shapes: tuple,  # (k, M) — trace-time constants
    W: jax.Array,
    cd: jax.Array,  # [Q, C] i32 candidate docids (pad: num_docs)
    cs: jax.Array,  # [Q, C] f32 per-lane partial scores (pad: 0)
    num_docs: int,
    bf16: bool = False,
):
    """The dense tier + candidate sort/run-sum/cut/merge machinery of the
    fast path, taking explicit per-lane candidates: shared by the raw
    BM25 gather (batch_term_disjunction_fast) and the impact-tier
    gather+sum pipeline (BatchTermSearcher.run_impact), so both arms
    carry the identical exactness-proof and totals contracts — 'exact'
    means exact for whichever score function produced the lanes."""
    k, M = plan_shapes
    live = dev["live"]
    n = num_docs

    dense = extras.get("dense_bf16") if bf16 else dev.get("dense_tfn")
    if dense is not None and W.shape[1] > 0:
        Wd = W.astype(jnp.bfloat16) if bf16 else W
        # HIGHEST precision unless bf16 was requested: JAX's *default* f32
        # matmul is itself reduced precision (~3e-4 relative, measured on
        # both backends), enough to swap near-tied ranks vs the bit-exact
        # path — parity with the per-query reference requires full f32
        scores_d = jnp.matmul(
            Wd, dense,
            precision=(None if bf16 else jax.lax.Precision.HIGHEST),
            preferred_element_type=jnp.float32,
        )
        # the proof bound must dominate the *computed* score function: under
        # bf16 both W and the tier round, so use the bf16-derived row maxima
        # inflated by the two operands' worst-case relative rounding
        if bf16:
            ub_dense = jnp.matmul(W, extras["rowmax_bf16"]) * (1.0 + 2.0**-7)
        else:
            # the bound itself must not round below the true sum: HIGHEST
            # here too (it is a [Q,V]x[V] matvec — negligible cost)
            ub_dense = jnp.matmul(
                W, extras["rowmax"], precision=jax.lax.Precision.HIGHEST
            ) * (1.0 + 2.0**-18)
    else:
        scores_d = jnp.zeros((W.shape[0], n), jnp.float32)
        ub_dense = jnp.zeros((W.shape[0],), jnp.float32)
    scores_d = jnp.where(live[None, :], scores_d, 0.0)
    masked_d = jnp.where(scores_d > 0, scores_d, -jnp.inf)
    dv, di = jax.lax.top_k(masked_d, k)
    dense_count = (masked_d > 0).sum(axis=1, dtype=jnp.int32)

    Q, C = cd.shape
    # multi-operand sort replaces argsort + 2x take_along_axis (measured
    # 114ms -> 23ms at [512, 16k]: take_along_axis is itself a gather)
    sd, sv = jax.lax.sort((cd, cs), dimension=1, num_keys=1)
    csum = jnp.cumsum(sv, axis=1)
    col = jnp.arange(C)
    starts = jnp.where(col[None, :] == 0, True, sd != jnp.roll(sd, 1, axis=1))
    base = jnp.where(starts, csum - sv, -jnp.inf)
    run_base = jax.lax.cummax(base, axis=1)
    run_sum = csum - run_base
    is_end = jnp.where(col[None, :] == C - 1, True, sd != jnp.roll(sd, -1, axis=1))
    valid_end = is_end & (sd < n)

    # ---- candidate cut: keep top-M by run-sum ---------------------------
    if M < C:
        # sort (run_sum desc) carrying docids; ascending sort on negated key
        neg = jnp.where(valid_end, -run_sum, jnp.inf)
        _, cd_all, rs_all, ve_all = jax.lax.sort(
            (neg, sd, run_sum, valid_end), dimension=1, num_keys=1
        )
        cd_m, rs_m, ve_m = cd_all[:, :M], rs_all[:, :M], ve_all[:, :M]
        dropped_best = jnp.where(ve_all[:, M], rs_all[:, M], -jnp.inf)
    else:
        cd_m, rs_m, ve_m = sd, run_sum, valid_end
        dropped_best = jnp.full((Q,), -jnp.inf)

    # live-docs check deferred to the kept set (the cut may retain deleted
    # docs over live ones; the exactness proof below stays valid because
    # dropped_best bounds dropped *live* candidates too)
    live_m = live[jnp.minimum(cd_m, n - 1)] & ve_m
    dg = jnp.take_along_axis(scores_d, jnp.minimum(cd_m, n - 1), axis=1)
    cand = jnp.where(live_m, rs_m + dg, -jnp.inf)

    # ---- merge ----------------------------------------------------------
    dup = (di[:, :, None] == cd_m[:, None, :]) & live_m[:, None, :]
    dv = jnp.where(dup.any(-1), -jnp.inf, dv)
    all_v = jnp.concatenate([cand, dv], axis=1)
    all_i = jnp.concatenate([cd_m, di], axis=1)
    score_bits = jax.lax.bitcast_convert_type(all_v, jnp.int32).astype(jnp.int64)
    rank = (score_bits << 32) + (jnp.int64(0xFFFFFFFF) - all_i.astype(jnp.int64))
    _, fidx = jax.lax.top_k(rank, k)
    fv = jnp.take_along_axis(all_v, fidx, axis=1)
    fids = jnp.take_along_axis(all_i, fidx, axis=1)

    totals_lb = dense_count + (live_m & (dg <= 0) & (rs_m > 0)).sum(
        axis=1, dtype=jnp.int32
    )
    # every dropped candidate matches (run_sum > 0) but may already be in
    # dense_count; the spread [lb, lb + dropped] brackets the true total
    if M < C:
        dropped = (ve_all[:, M:] & (rs_all[:, M:] > 0)).sum(axis=1, dtype=jnp.int32)
    else:
        dropped = jnp.zeros((Q,), jnp.int32)
    kth = fv[:, k - 1]
    exact = (dropped_best + ub_dense < kth) | jnp.isneginf(dropped_best)
    return fv, fids, totals_lb, exact, dropped


class _RawChunks:
    """Unsynchronized per-chunk device outputs of a chunked batch run.

    Deliberately NOT a flat device array: any eager device op issued on
    not-yet-ready outputs (a concatenate, even a [:Q] slice) acts as a
    dispatch barrier under remote runtimes — measured to serialize
    multi-group batches ~6x. Stitching therefore happens host-side in
    numpy after ONE device_get of everything (tuple(self) or np.asarray
    via __iter__/resolve)."""

    def __init__(self, chunk_outs: list, Q: int, n_out: int):
        self.chunk_outs = chunk_outs
        self.Q = Q
        self.n_out = n_out
        self._resolved: tuple | None = None

    def resolve(self) -> tuple:
        """-> n_out numpy arrays, padding stripped. One device round-trip,
        memoized (indexed access must not re-fetch everything)."""
        if self._resolved is None:
            self._resolved = self.resolve_all([self])[0]
        return self._resolved

    # iterating (or tuple-unpacking) a result resolves it: keeps the
    # `v, i, t = bs.run(...)` call sites working unchanged
    def __iter__(self):
        return iter(self.resolve())

    def __getitem__(self, j):
        return self.resolve()[j]

    @staticmethod
    def stitch(chunks: list, Q: int, n_out: int) -> tuple:
        """Host-side assembly of fetched chunk outputs: concat + strip
        the tail padding. THE single copy of this contract."""
        if len(chunks) == 1:
            return tuple(np.asarray(o)[:Q] for o in chunks[0][:n_out])
        return tuple(
            np.concatenate([np.asarray(c[j]) for c in chunks])[:Q]
            for j in range(n_out)
        )

    @staticmethod
    def resolve_all(raws: list["_RawChunks"]) -> list[tuple]:
        """Resolve several raw results with a single device round-trip."""
        host = jax.device_get([r.chunk_outs for r in raws])
        return [
            _RawChunks.stitch(chunks, r.Q, r.n_out)
            for r, chunks in zip(raws, host)
        ]


class BatchTermSearcher:
    """Compiled-plan cache for batched term-disjunction queries against one
    ShardSearcher's device pack."""

    # fast-path candidate budget: the post-cut dense gather is [Q, M] at
    # ~30ns/element (~32ms per 512-query chunk at 2048). 2048 covers the
    # full candidate set of most real queries (sum of sparse-term dfs),
    # making the cut a no-op — and a no-op cut is provably exact, which is
    # what keeps the rerun rate (the expensive path) low
    FAST_M = 2048
    # query-chunk budget: cap the materialized [Qc, N] f32 score matrix.
    # 2 GB => 512-query chunks on a 1M-doc shard — measured to be the
    # per-chunk sweet spot: doubling the chunk to 1024 made per-chunk time
    # ~2.7x (superlinear top_k/sort behavior at [1024, N]), a net loss
    SCORE_BYTES_BUDGET = 1 << 31  # 2 GB

    def __init__(self, searcher):
        self.searcher = searcher
        self._cache = {}

    def plan(
        self,
        fld: str,
        queries: list[list[tuple[str, float]]],
        k: int,
        *,
        pad_ts: int | None = None,
        pad_b: int | None = None,
    ) -> BatchPlan:
        """queries: per query a list of (term, boost) on field `fld`.
        pad_ts/pad_b force the padded (sparse-term, block) shape so bucketed
        callers share compiled executables across batches."""
        from .scoring import bm25_idf

        pack = self.searcher.pack
        k = min(max(k, 1), max(pack.num_docs, 1))
        V = pack.dense_tfn.shape[0] if pack.dense_tfn is not None else 0
        Q = len(queries)
        doc_count = pack.field_stats.get(fld, {}).get("doc_count") or pack.num_docs
        max_ts, max_b = 1, 1
        has_impact = True
        parsed = []
        for terms in queries:
            dense, sparse = [], []
            for term, boost in terms:
                w = 0.0
                s0, nb, df = pack.term_blocks(fld, term)
                if df > 0:
                    w = boost * bm25_idf(doc_count, df)
                dr = pack.dense_row_of(fld, term)
                if dr is not None:
                    dense.append((dr, w))
                elif nb > 0:
                    isc = pack.impact_wscale(fld, term)
                    if isc is None:
                        has_impact = False
                    sparse.append((s0, nb, w, w * (isc or 0.0)))
                    max_b = max(max_b, nb)
            max_ts = max(max_ts, len(sparse))
            parsed.append((dense, sparse))
        B = pad_b or (1 << (max_b - 1).bit_length())
        if pad_ts:
            max_ts = max(max_ts, pad_ts)
        W = np.zeros((Q, V), np.float32)
        rows = np.zeros((Q, max_ts, B), np.int32)
        ws = np.zeros((Q, max_ts), np.float32)
        iws = np.zeros((Q, max_ts), np.float32)
        td_max = max((len(d) for d, _ in parsed), default=1) or 1
        Td = 1 << (max(td_max, 4) - 1).bit_length()
        dense_rows = np.zeros((Q, Td), np.int32)
        dense_w = np.zeros((Q, Td), np.float32)
        for qi, (dense, sparse) in enumerate(parsed):
            for ti, (dr, w) in enumerate(dense):
                W[qi, dr] += w
                dense_rows[qi, ti] = dr
                dense_w[qi, ti] = w
            for ti, (s0, nb, w, iw) in enumerate(sparse):
                rows[qi, ti, :nb] = np.arange(s0, s0 + nb)
                ws[qi, ti] = w
                iws[qi, ti] = iw
        dense_only = V > 0 and all(not sparse for _, sparse in parsed)
        return BatchPlan(W, rows, ws, k, dense_only,
                         dense_rows=dense_rows, dense_w=dense_w,
                         impact_w=iws if has_impact else None)

    def _chunk_q(self, Q: int) -> int:
        """Power-of-two chunk width: caps the materialized [Qc, N] f32 score
        matrix at SCORE_BYTES_BUDGET (no small-Q floor: on a huge shard the
        budget wins) and bounds the compiled-shape family — every batch size
        maps onto {1, 2, 4, ...} wide executables with tail padding."""
        n = max(self.searcher.pack.num_docs, 1)
        budget = max(1, self.SCORE_BYTES_BUDGET // (4 * n))
        pow2_floor = 1 << (budget.bit_length() - 1)
        if Q >= pow2_floor:
            return pow2_floor
        # whole batch fits one chunk: round Q up to pow2 (tail-padded)
        return 1 << max(Q - 1, 0).bit_length() if Q > 1 else 1

    def _run_chunked(self, kernel, map_key, plan: BatchPlan, n_out: int):
        """Run a traceable kernel(dev, extras, W, sr, sw) over uniform
        [qc, ...] chunks of the plan, one compiled executable shared by all
        chunks.

        Constraints (measured on real hardware):
          - the materialized [qc, N] score matrix must stay under
            SCORE_BYTES_BUDGET, so the query axis is chunked;
          - chunks upload as per-chunk host slices, NOT device-side slices
            of one big array: any eager device op on a not-yet-ready
            buffer (a slice included) acts as a dispatch barrier under
            remote runtimes and serializes the whole batch;
          - for the same reason the outputs return UNRESOLVED
            (_RawChunks): no concatenate/[:Q] happens on device — callers
            stitch host-side after one device_get;
          - a `lax.map` over chunks (single dispatch) was tried and is
            SLOWER: the scan serializes against XLA's inter-dispatch
            pipelining and compiles 5-10x longer."""
        Q = plan.W.shape[0]
        qc = self._chunk_q(Q)
        pad = (-Q) % qc
        arrs = [plan.W, plan.sparse_rows, plan.sparse_weights]
        if map_key[0] == "dense_tiered":
            # the tiered kernel rescores against the per-query (tier row,
            # weight) pairs, so they ride along as chunked operands
            arrs += [plan.dense_rows, plan.dense_w]
        if pad:
            arrs = [np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
                    for a in arrs]
        cache_key = ("chunk", map_key, qc)
        fn = self._cache.get(cache_key)
        if fn is None:
            fn = jax.jit(kernel)
            self._cache[cache_key] = fn
        if map_key[0] == "fast":
            extras = self._fast_extras(map_key[-1])
        elif map_key[0] == "dense_tiered":
            extras = self._tiered_extras()
        else:
            extras = {}
        dev = self.searcher.dev
        first = tuple(jnp.asarray(a[:qc]) for a in arrs)
        from ..monitoring.xla_introspect import check_dispatch

        # PR 12: the chunk executable vs its own compiled cost analysis
        # (one capture per chunk shape; all chunks share the executable)
        check_dispatch(
            "batched.disjunction", fn, (dev, extras, *first),
            fields={"queries": qc, "num_docs": self.searcher.pack.num_docs,
                    "rows": int(np.prod(plan.sparse_rows[:qc].shape))})
        outs = [
            fn(dev, extras, *(jnp.asarray(a[i : i + qc]) for a in arrs))
            for i in range(0, Q + pad, qc)
        ]
        return _RawChunks(outs, Q, n_out)

    def run(self, fld: str, plan: BatchPlan):
        """-> (scores [Q,k], docids [Q,k], totals [Q]) on device (async).

        Chunks the query axis so the materialized [Qc, N] score matrix stays
        under SCORE_BYTES_BUDGET (a 4096-query batch over a 1M-doc shard
        would otherwise need 15.3 GB of HBM for scores alone)."""
        if plan.dense_only:
            # whole batch lives in the dense tier: fused Pallas scan+topk —
            # scores never leave VMEM (ops/kernels.py)
            from .kernels import scan_topk

            dev = self.searcher.dev
            return scan_topk(
                jnp.asarray(plan.W), dev["dense_tfn"], dev["live"], plan.k
            )
        Ts, B = plan.sparse_rows.shape[1], plan.sparse_rows.shape[2]
        pack = self.searcher.pack
        avgdl = pack.avgdl(fld)
        has_norms = fld in self.searcher.ctx.has_norms
        k = plan.k

        def kernel(dev, extras, W, sr, sw):
            return batch_term_disjunction(
                dev, (Ts, B, k), W, sr, sw,
                avgdl=avgdl, num_docs=pack.num_docs, has_norms=has_norms,
            )

        return self._run_chunked(
            kernel, ("exact", Ts, B, k, fld), plan, 3
        )

    def _fast_extras(self, bf16: bool) -> dict:
        """Fast-path device arrays, kept OUT of searcher.dev: mutating the
        shared dev dict would change its pytree structure and force every
        already-compiled executable that takes dev as an argument to
        retrace (per-query searchers, the exact batch path). Each precision
        mode gets its own fixed-keys dict (stable treedef per compiled fn),
        and the bf16 tier copy (~half the dense tier's HBM again) is only
        materialized if a bf16 call actually happens."""
        attr = "_extras_bf16" if bf16 else "_extras_f32"
        extras = getattr(self, attr, None)
        if extras is None:
            extras = {}
            dev = self.searcher.dev
            if "dense_tfn" in dev:
                if bf16:
                    extras["dense_bf16"] = dev["dense_tfn"].astype(jnp.bfloat16)
                    extras["rowmax_bf16"] = jnp.max(
                        extras["dense_bf16"].astype(jnp.float32), axis=1
                    )
                else:
                    extras["rowmax"] = jnp.max(dev["dense_tfn"], axis=1)
            setattr(self, attr, extras)
        return extras

    def _tiered_extras(self) -> dict:
        """Split-bf16 (hi, lo) copies of the dense tier for the tiered
        selection kernel — kept out of searcher.dev for the same treedef
        reasons as _fast_extras."""
        extras = getattr(self, "_extras_tiered", None)
        if extras is None:
            from .kernels import split_bf16

            dev = self.searcher.dev
            hi, lo = jax.jit(split_bf16)(dev["dense_tfn"])
            extras = {"dense_hi": hi, "dense_lo": lo}
            self._extras_tiered = extras
        return extras

    def run_fast(self, fld: str, plan: BatchPlan, *, bf16: bool = False, M: int | None = None):
        """Throughput path -> (scores [Q,k], docids [Q,k], totals_lb [Q],
        exact [Q], dropped [Q]) on device. See batch_term_disjunction_fast
        for the totals/exactness contract; callers needing guaranteed-exact
        results re-run flagged queries with M = C."""
        dev = self.searcher.dev
        if plan.dense_only:
            from .fused import rank_topk
            from .kernels import (
                EPS_TIERED, KB_TIERED, fused_topk_enabled, scan_topk_xla,
                tiered_candidates,
            )

            k = plan.k
            if (fused_topk_enabled() and k <= KB_TIERED
                    and plan.dense_rows is not None):
                # tiered path (ES_TPU_FUSED_TOPK default): split-bf16
                # selection with a running in-VMEM top-KB on TPU, then the
                # canonical f32 rescore of the survivors against the f32
                # tier — flagged queries (margin test) escalate to the
                # exact scan via msearch's rerun loop
                kb = min(max(KB_TIERED, k), self.searcher.pack.num_docs)
                Td = plan.dense_rows.shape[1]

                def dense_kernel(dv, extras, W, sr, sw, dr, dw):
                    sel_v, sel_i, totals = tiered_candidates(
                        W, extras["dense_hi"], extras["dense_lo"],
                        dv["live"], kb,
                        transform="identity", count_positive=True,
                    )
                    cand_ok = jnp.isfinite(sel_v)
                    dg = dv["dense_tfn"][
                        dr[:, :, None], sel_i[:, None, :]]  # [Qc, Td, kb]
                    resc = jnp.sum(dw[:, :, None] * dg, axis=1)
                    resc = jnp.where(cand_ok & (resc > 0), resc, -jnp.inf)
                    v, i_ = rank_topk(resc, sel_i, min(k, kb))
                    am_kernel = sel_v[:, -1]
                    am_resc = jnp.min(
                        jnp.where(cand_ok, resc, jnp.inf), axis=1)
                    rk = v[:, -1]
                    bound = am_kernel + EPS_TIERED * jnp.abs(am_kernel)
                    safe = (jnp.isneginf(am_kernel) | (rk > bound)
                            | (rk == am_resc))
                    return (v, i_, totals, safe,
                            jnp.zeros(v.shape[0], jnp.int32))

                return self._run_chunked(
                    dense_kernel, ("dense_tiered", k, kb, Td), plan, 5)

            # chunked XLA matmul+top_k fallback (ES_TPU_FUSED_TOPK=0 or
            # k beyond the selection width): the [Qc, N] score
            # materialization stays under SCORE_BYTES_BUDGET
            def dense_kernel(dv, extras, W, sr, sw):
                N = dv["dense_tfn"].shape[1]
                v, i_, t = scan_topk_xla(
                    W,
                    dv["dense_tfn"],
                    dv["live"],
                    jnp.zeros((N,), jnp.float32),
                    jnp.zeros((W.shape[0],), jnp.float32),
                    k=k,
                    transform="identity",
                    count_positive=True,
                )
                ones = jnp.ones(v.shape[0], bool)
                return v, i_, t, ones, jnp.zeros(v.shape[0], jnp.int32)

            return self._run_chunked(dense_kernel, ("dense", k), plan, 5)
        Ts, B = plan.sparse_rows.shape[1], plan.sparse_rows.shape[2]
        M = min(M or self.FAST_M, Ts * B * BLOCK)
        pack = self.searcher.pack
        avgdl = pack.avgdl(fld)
        has_norms = fld in self.searcher.ctx.has_norms
        k = plan.k

        def kernel(dv, extras, W, sr, sw):
            return batch_term_disjunction_fast(
                dv, extras, (Ts, B, k, M), W, sr, sw,
                avgdl=avgdl, num_docs=pack.num_docs, has_norms=has_norms,
                bf16=bf16,
            )

        return self._run_chunked(
            kernel, ("fast", Ts, B, k, M, fld, bf16), plan, 5
        )

    def impact_usable(self) -> bool:
        """The impact tier serves this searcher's sparse terms: routing
        enabled (ES_TPU_IMPACT) and the quantized code blocks resident."""
        from .scoring import impact_enabled

        return impact_enabled() and "impact_codes" in self.searcher.dev

    def run_impact(self, fld: str, plan: BatchPlan, *, M: int | None = None):
        """Impact-tier throughput arm (BM25S) -> the run_fast output
        contract (scores, docids, totals_lb, exact, dropped) on device.

        Two stages, both ahead of the shared candidate tail:
          1. sparse.impact_gather — ops/kernels.impact_gather fetches the
             query terms' quantized code blocks and dequantizes with one
             per-term scalar (Pallas scalar-prefetch arm on TPU, XLA row
             gather elsewhere). No tf, no doc length, no idf: ~6 bytes
             per posting (4 docid + 1-2 code) instead of 12, zero
             arithmetic beyond one multiply.
          2. sparse.impact_sum — fast_topk_from_candidates: the identical
             sort/run-sum/cut/dense-merge machinery of run_fast, so the
             exactness proof and totals contract carry over verbatim
             ('exact' = exact for the impact score function; the
             quantization error bound is index/pack.py's documented
             model, asserted by tests/test_impact.py)."""
        dev = self.searcher.dev
        if plan.dense_only or plan.impact_w is None or "impact_codes" not in dev:
            return self.run_fast(fld, plan, M=M)
        from .kernels import impact_gather

        Ts, B = plan.sparse_rows.shape[1], plan.sparse_rows.shape[2]
        C = Ts * B * BLOCK
        M = min(M or self.FAST_M, C)
        k = plan.k
        n = self.searcher.pack.num_docs
        Q = plan.W.shape[0]
        qc = self._chunk_q(Q)
        pad = (-Q) % qc
        rows_flat = plan.sparse_rows.reshape(Q, Ts * B)
        w_flat = np.repeat(plan.impact_w, B, axis=1)  # [Q, Ts*B]
        arrs = [plan.W, rows_flat, w_flat]
        if pad:
            arrs = [np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
                    for a in arrs]
        Wa, rows_a, w_a = arrs
        fn1 = self._cache.get("impact_gather")
        if fn1 is None:
            fn1 = self._cache["impact_gather"] = jax.jit(
                lambda dv, r, w: impact_gather(
                    dv["impact_codes"], dv["post_docids"], r, w))
        key2 = ("impact_sum", k, M)
        fn2 = self._cache.get(key2)
        if fn2 is None:
            def tail(dv, extras, W_, cd, cs):
                return fast_topk_from_candidates(
                    dv, extras, (k, M), W_, cd, cs, num_docs=n)

            fn2 = self._cache[key2] = jax.jit(tail)
        extras = self._fast_extras(False)
        from ..monitoring.xla_introspect import check_dispatch
        from ..telemetry import time_kernel

        code_bytes = int(np.dtype(dev["impact_codes"].dtype).itemsize)
        check_dispatch(
            "sparse.impact_gather", fn1,
            (dev, jnp.asarray(rows_a[:qc]), jnp.asarray(w_a[:qc])),
            fields={"queries": qc, "rows": qc * Ts * B,
                    "code_bytes": code_bytes})
        cands = []
        for i in range(0, Q + pad, qc):
            cands.append(fn1(dev, jnp.asarray(rows_a[i: i + qc]),
                             jnp.asarray(w_a[i: i + qc])))
        with time_kernel("sparse.impact_gather", tier="impact", queries=Q,
                         rows=Q * Ts * B, code_bytes=code_bytes):
            jax.block_until_ready(cands)
        check_dispatch(
            "sparse.impact_sum", fn2,
            (dev, extras, jnp.asarray(Wa[:qc]), *cands[0]),
            fields={"queries": qc, "num_docs": n, "cands": M})
        outs = [
            fn2(dev, extras, jnp.asarray(Wa[i: i + qc]), cd, cs)
            for (cd, cs), i in zip(cands, range(0, Q + pad, qc))
        ]
        return _RawChunks(outs, Q, 5)

    def search(self, fld: str, queries: list[list[tuple[str, float]]], k: int = 10):
        out = self.run(fld, self.plan(fld, queries, k))
        if isinstance(out, _RawChunks):
            return out.resolve()
        return jax.device_get(out)  # dense-only fused path returns arrays

    def plan_bucketed(
        self, fld: str, queries: list[list[tuple[str, float]]], k: int
    ) -> list[tuple[np.ndarray, BatchPlan]]:
        """Split a batch into shape-homogeneous groups before padding.

        One global plan pads every query to the batch's worst case (max
        sparse-term count x max posting blocks); a single long-postings
        query makes all Q queries pay its candidate width in the sort and
        gather stages. Bucketing by power-of-two (Ts, B) keeps each group's
        C = Ts*B*128 proportional to its own heaviest member — the batch
        analog of the reference running each query's own WAND iterator
        rather than one worst-case loop (Lucene per-query scorers).

        -> list of (original query indices, BatchPlan); compiled shapes are
        shared across batches with the same bucket structure.
        """
        pack = self.searcher.pack
        shapes = []
        for terms in queries:
            ts, maxb = 0, 0
            for term, _ in terms:
                if pack.dense_row_of(fld, term) is not None:
                    continue
                _, nb, df = pack.term_blocks(fld, term)
                if nb > 0:
                    ts += 1
                    maxb = max(maxb, nb)
            # buckets: Ts pow2, B in 4x steps from 8. The sparse sort/scan
            # cost per query is proportional to Ts*B, so queries must not
            # pay a heavier query's padding; executable dispatches are
            # effectively free once compiled, so more groups only cost
            # one-time compiles (persisted in the XLA cache).
            bb = 8
            while bb < maxb:
                bb *= 4
            shapes.append(
                ((1 << max(ts - 1, 0).bit_length()) if ts else 0,
                 bb if maxb else 0)
            )
        groups: dict[tuple, list[int]] = {}
        for qi, sh in enumerate(shapes):
            groups.setdefault(sh, []).append(qi)
        out = []
        for (ts_b, b_b), idxs in sorted(groups.items()):
            sub = [queries[i] for i in idxs]
            out.append(
                (
                    np.asarray(idxs, np.int64),
                    self.plan(fld, sub, k,
                              pad_ts=ts_b or None, pad_b=b_b or None),
                )
            )
        return out

    def _fused_searcher(self, k):
        """Cached FusedTermSearcher when the pack/k qualify, else None."""
        from .fused import FusedTermSearcher

        if not FusedTermSearcher.usable(self.searcher.pack, k):
            return None
        fs = getattr(self, "_fused", None)
        if fs is None:
            fs = self._fused = FusedTermSearcher(self)
        return fs

    @staticmethod
    def wave_q_tier(q: int) -> int:
        """The compiled batch tier a q-query wave pads to: the next power
        of two (the same {1, 2, 4, ...} executable family `_chunk_q` and
        `plan_bucketed` already key their compiled-plan caches on). The
        serving front end pads coalesced waves to this tier so steady-
        state traffic reuses a small family of compiled programs, and
        reports q / wave_q_tier(q) as the wave's device occupancy."""
        return 1 << max(q - 1, 0).bit_length() if q > 1 else 1

    def msearch_coalesced(self, fld, groups, k: int = 10, **kw):
        """Coalesced msearch for the serving front end: pack several
        callers' query lists into ONE batched dispatch and de-interleave
        the result rows per caller.

        groups: list of per-request query lists (each a list of
        [(term, boost)] queries). -> list of per-group (scores, ids,
        totals, exact) numpy tuples, in group order.

        Each query's result row is byte-identical to running its group
        alone: per-row computations are independent (matmul rows, per-row
        sorts/top-k), bucketed plan shapes derive from each query's OWN
        terms, and chunk padding appends zero-weight queries that
        contribute exact 0.0 to nothing — so coalescing changes only
        which executable tier the batch pads to, never any row's bytes
        (asserted by tests/test_serving.py)."""
        flat = [q for g in groups for q in g]
        if not flat:
            return [(np.zeros((0, k), np.float32), np.zeros((0, k), np.int64),
                     np.zeros((0,), np.int64), np.ones((0,), bool))
                    for _ in groups]
        scores, ids, totals, exact = self.msearch(fld, flat, k, **kw)
        out, pos = [], 0
        for g in groups:
            n = len(g)
            out.append((scores[pos:pos + n], ids[pos:pos + n],
                        totals[pos:pos + n], exact[pos:pos + n]))
            pos += n
        return out

    def msearch_many(self, fld, batches, k: int = 10):
        """Pipelined multi-batch msearch (serving-concurrency regime):
        every batch dispatches before any fetch. Falls back to sequential
        msearch when the fused path is unavailable."""
        fs = self._fused_searcher(k)
        if fs is not None:
            return fs.msearch_many(fld, batches, k)
        return [self.msearch(fld, qs, k) for qs in batches]

    def msearch(
        self,
        fld: str,
        queries: list[list[tuple[str, float]]],
        k: int = 10,
        *,
        fast: bool = True,
        bf16: bool = False,
        track_total_hits: int = 10_000,
    ):
        """Bucketed batch search -> (scores [Q,k], docids [Q,k], totals [Q],
        first_pass_exact [Q]) as numpy, stitched back to input order.

        fast=True uses the candidate-cut path and re-runs (with the cut
        disabled) any query whose top-k exactness proof failed OR whose
        total-hits bracket straddles track_total_hits, so top-k docs are
        ALWAYS exact and totals satisfy the reference's track_total_hits
        contract: exact below the threshold, lower bound at/above it
        (reference behavior: TotalHits.Relation / ContextIndexSearcher
        hit-count thresholds). first_pass_exact reports which queries were
        proven exact WITHOUT the rerun — the fast path's hit rate.

        Missing-hit columns carry -inf scores (when fewer than k docs
        match, and when k was clamped to the doc count)."""
        arm = "exact"
        if fast:
            # PR 18: eligible arms (same gates as before — fused needs a
            # usable FusedTermSearcher, impact a servable impact tier)
            # route through the execution planner: static priority
            # fused > impact > fast while cold, argmin of predicted
            # walls once the kernel EMAs are warm
            from ..planner import execution_planner

            fs = self._fused_searcher(k)
            n_docs = self.searcher.pack.num_docs
            cands = []
            if fs is not None:
                cands.append(("fused", "fused.pallas_scan",
                              {"k": k, **fs._cost_fields(len(queries))}))
            if self.impact_usable():
                cands.append(("impact", "sparse.impact_sum",
                              {"queries": len(queries), "k": k,
                               "num_docs": n_docs}))
            cands.append(("exact", "batched.disjunction",
                          {"queries": len(queries), "k": k,
                           "num_docs": n_docs}))
            arm = execution_planner().choose_arm("batched.msearch", cands)
            if arm == "fused":
                from ..telemetry import profile_event, time_kernel

                profile_event("tier", tier="fused", queries=len(queries))
                with time_kernel("fused.msearch", tier="fused",
                                 queries=len(queries), k=k):
                    return fs.msearch(fld, queries, k)
        Q = len(queries)
        use_impact = arm == "impact"
        scores = np.full((Q, k), -np.inf, np.float32)
        ids = np.zeros((Q, k), np.int64)
        totals = np.zeros((Q,), np.int64)
        exact = np.ones((Q,), bool)
        pending: list[np.ndarray] = []
        parts = []

        def _run_first(plan):
            if not fast:
                return self.run(fld, plan)
            if use_impact and plan.impact_w is not None and not plan.dense_only:
                return self.run_impact(fld, plan)
            return self.run_fast(fld, plan, bf16=bf16)

        for idxs, plan in self.plan_bucketed(fld, queries, k):
            parts.append((idxs, _run_first(plan)))
        # resolve every group with ONE device round-trip, and only after
        # every group was dispatched (no intermediate eager ops: those act
        # as dispatch barriers under remote runtimes). Plain-array groups
        # (the dense-only fused path under fast=False) join the same fetch.
        from ..telemetry import profile_event, time_kernel

        tier = ("impact" if use_impact else "fast") if fast else "exact"
        profile_event("tier", tier=tier, queries=Q)
        raws = [p.chunk_outs if isinstance(p, _RawChunks) else p
                for _, p in parts]
        if use_impact:
            # the impact arm's candidate tail: the gather stage already
            # synced under its own sparse.impact_gather span (run_impact)
            with time_kernel("sparse.impact_sum", tier="impact", queries=Q,
                             k=k, num_docs=self.searcher.pack.num_docs):
                host = jax.device_get(raws)
        else:
            with time_kernel("batched.disjunction",
                             tier=tier, queries=Q, k=k,
                             num_docs=self.searcher.pack.num_docs):
                host = jax.device_get(raws)
        parts = [
            (idxs, _RawChunks.stitch(h, p.Q, p.n_out)
             if isinstance(p, _RawChunks) else h)
            for (idxs, p), h in zip(parts, host)
        ]
        for idxs, out in parts:
            kk = out[0].shape[1]
            scores[idxs, :kk] = out[0]
            ids[idxs, :kk] = out[1]
            totals[idxs] = out[2]
            if len(out) > 3:
                topk_ok = out[3]
                totals_ok = (out[4] == 0) | (out[2] >= track_total_hits)
                ok = topk_ok & totals_ok
                exact[idxs] = ok
                if not ok.all():
                    pending.append(idxs[~ok])
        rerun_m = 4 * self.FAST_M
        while pending:
            # escalate the candidate budget for flagged queries (4x per
            # round, up to M = C where the cut disappears and the result is
            # provably exact with exact sparse-only totals) — reusing the
            # fast-path program family instead of compiling the legacy path
            redo = np.concatenate(pending)
            pending = []
            profile_event("tier", tier="exact_escalation",
                          queries=int(redo.shape[0]))
            rerun_parts = []
            exact_parts = []
            for idxs, plan in self.plan_bucketed(
                fld, [queries[i] for i in redo], k
            ):
                if plan.dense_only:
                    # a tiered-selection flag has no candidate budget to
                    # widen — escalate straight to the exact scan path
                    exact_parts.append((idxs, self.run(fld, plan)))
                    continue
                C = plan.sparse_rows.shape[1] * plan.sparse_rows.shape[2] * BLOCK
                M = min(rerun_m, C)
                if use_impact and plan.impact_w is not None:
                    rerun = self.run_impact(fld, plan, M=M)
                else:
                    rerun = self.run_fast(fld, plan, bf16=bf16, M=M)
                rerun_parts.append((idxs, M >= C, rerun))
            for idxs, out in exact_parts:
                ev, ei, et = [np.asarray(x) for x in (
                    out.resolve() if isinstance(out, _RawChunks)
                    else jax.device_get(out))]
                done = redo[idxs]
                scores[done, : ev.shape[1]] = ev
                ids[done, : ev.shape[1]] = ei
                totals[done] = et
            resolved = _RawChunks.resolve_all([r for _, _, r in rerun_parts])
            for (idxs, uncut, _), (ev, ei, et, eok, edrop) in zip(
                rerun_parts, resolved
            ):
                ok = eok & ((edrop == 0) | (et >= track_total_hits))
                if uncut:
                    ok[:] = True
                done = idxs[ok]
                scores[redo[done], : ev.shape[1]] = ev[ok]
                ids[redo[done], : ev.shape[1]] = ei[ok]
                totals[redo[done]] = et[ok]
                if not ok.all():
                    pending.append(redo[idxs[~ok]])
            rerun_m *= 4
        return scores, ids, totals, exact
