"""Vectorized civil-calendar math on epoch-millis columns.

The reference implements calendar rounding host-side per value
(reference behavior: server/.../common/Rounding.java — date_histogram
calendar_interval month/quarter/year). On TPU we decompose epoch days into
(year, month, day) with Howard Hinnant's civil-from-days algorithm — pure
integer arithmetic, branch-free, vectorizes over the whole column.
"""

from __future__ import annotations

import jax.numpy as jnp

MS_PER_DAY = 86_400_000


def civil_from_millis(ms: jnp.ndarray):
    """epoch millis (int64, UTC) -> (year, month 1..12, day 1..31), int64."""
    days = jnp.floor_divide(ms, MS_PER_DAY)
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = jnp.floor_divide(5 * doy + 2, 153)  # [0, 11]
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1  # [1, 31]
    m = mp + 3 - 12 * (mp // 10)  # [1, 12]
    y = y + (mp // 10)
    return y, m, d


def month_index_from_millis(ms: jnp.ndarray) -> jnp.ndarray:
    """epoch millis -> months since year 0 (y*12 + m-1); monotone in time."""
    y, m, _ = civil_from_millis(ms)
    return y * 12 + (m - 1)


def days_from_civil(y: int, m: int, d: int) -> int:
    """Host-side inverse (scalar): civil date -> epoch days."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def millis_of_month_index(idx: int) -> int:
    """Host-side: month index (y*12+m-1) -> epoch millis of month start."""
    y, m = divmod(idx, 12)
    return days_from_civil(y, m + 1, 1) * MS_PER_DAY
