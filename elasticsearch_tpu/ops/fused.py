"""Fused batched BM25: bf16 dense matmul + one-hot MXU sparse-add + in-kernel
top-K' + exact match counts, followed by a canonical f32 rescore.

This replaces the round-2 `_msearch` hot path, whose XLA composition paid two
taxes this kernel removes (measured on a v5e through the remote runtime):

  - `lax.top_k` on a [512, 1M] score matrix costs ~1.25 s — three orders of
    magnitude over the HBM roofline. Here top-K' selection runs inside the
    doc-tile scan against a VMEM accumulator (buffered merge, below).
  - per-element gathers/scatters run on the TPU scalar core (~15-30 ns/elem).
    The sparse tail (CSR postings below the dense-tier df threshold) is
    instead ACCUMULATED INTO THE SCORE TILES BY ONE-HOT MATMULS: candidate
    windows, sorted by (query-subtile, docid), are DMA'd per tile and
    expanded to
        At[p, q] = weight_p * (query_p == q)     [P, QSUB]
        D [p, n] = (docid_p - tile_base == n)    [P, TILE_N]
    so `scores_tile += At.T @ D` performs a segmented scatter-add on the
    MXU. Duplicate (query, doc) candidates sum automatically, which deletes
    the old path's per-(query,doc) run-sum machinery (sort + cummax scan),
    and dense+sparse overlap resolves by ordinary addition instead of a
    candidate-list merge.

The dense-tier matmul runs OUTSIDE the kernel: XLA's [512,896]x[896,1M] bf16
matmul is ~2 ms materialized, and the [Qc, N] bf16 score matrix it writes is
~1 GB of HBM traffic (~2.5 ms) — cheap, unlike its f32 top_k. Totals are
exact: a live lane matches iff its combined score is > 0 (every BM25 term
weight is > 0 — reference behavior: Lucene BM25Similarity idf > 0), and
rounding preserves sign, so the in-kernel count of positive live lanes is
the reference's exact hit count (better than the reference's own default,
which stops counting at 10k — TotalHits.Relation.GREATER_THAN_OR_EQUAL_TO).

Selection in bf16 perturbs near-ties, so the kernel's output is a
CANDIDATE SET, not the result: `canonical_rescore` recomputes each
winner's score in f32 with one shared function used by every path, and the
final ranking is (rescored score desc, docid asc). A per-query safety test
flags queries whose kth rescored score is not provably above anything the
bf16 pass could have excluded; flagged queries re-run on the legacy exact
path. Pattern ties (docs with identical (tf, dl) profiles — common under
quantized norms) produce bit-identical scores in both precisions, so the
kernel's docid tie-break already orders them correctly; the safety test
treats an exact kth==K'th rescored tie as safe for that reason.

SPMD note (PR 10, closed PR 11): these Pallas kernels are custom calls
XLA's GSPMD partitioner cannot shard — but manual partitioning needs no
partitioner, so the sharded consumer
(`parallel/sharded._FusedShardedMsearch.msearch_merged_begin`) runs the
pipeline inside a shard_map region EMBEDDED in the one compiled pjit
program (`parallel/spmd.manual_shard_region`), feeding the on-device
all-gather top-k merge in the same program. The standalone shard_map +
host-merge form survives only as the legacy-execution-model / parity-
oracle route; there is no `ES_TPU_SPMD` arm matrix for the fused tier.

Round-4 restructure (the round-3 bottleneck was ~3,900 grid steps of fixed
sequencing/DMA-issue cost plus per-step tiered top-K' accumulator merges of
up to ~40 VPU reduce rounds — the MXU was <3% busy, BENCH_NOTES.md): the
kernel no longer maintains a cross-step top-K' accumulator at all. Each
grid step covers a WIDE doc tile (TILE_N=4096; 4x fewer steps) and emits
only that tile's top-T candidates (T unrolled reduce rounds); the global
top-K' merge happens OUTSIDE the kernel as one small `lax.top_k` over the
[Q, njc*T] per-tile candidates. Losing a true top-K' entry is detectable
after the fact: if a tile contributed fewer than T of the final K' winners,
its T-th candidate ranks below the K'-th winner, so everything that tile
dropped ranks below the K'-th winner too — hence the exact flag "some tile
saturated its T slots among the K' winners", which composes with the same
rerun escalation as the window-overflow flag. T is sized so saturation is
~never hit at bench shapes (P[>=5 of the top-32 in one 4096-doc tile of
244] ~ 6e-5 per query under exchangeable doc placement). The one-hot
scatter keeps its measured-best 1024-doc granularity (FINE_N): each coarse
step processes its 4 fine sub-windows with exact fori_loop row bounds from
the scalar-prefetched pointers, replacing round 3's unrolled
every-row-gated window walk.

Reference behavior replaced: the DAAT BulkScorer loop + TopScoreDocCollector
(reference: search/internal/ContextIndexSearcher.java:411-431) and the
default hit-count threshold semantics (search/query/QueryPhase.java).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..index.pack import BLOCK

KB = 64  # rescored candidate set size (top-K'); final k must be <= KB
# (round 5: widened 32 -> 64 together with the 2-pass dense tier — the
# deeper candidate margin is what keeps the cheaper selection's flag
# rate negligible: measured 5th-pct relative gap between the 10th and
# 64th dense score is 2.3e-2 vs the 2-pass error bound of 8e-3)
# geometry defaults from the round-4 sweep on a v5e (BENCH_NOTES.md):
# tile 8192 x qsub 256 measured 4.24x the C1 baseline model vs 3.6x for
# 4096x128 — fewer grid steps win until VPU/matmul work dominates
TILE_N = 8192  # coarse doc tile: one grid step scores [QSUB, TILE_N]
FINE_N = 1024  # one-hot scatter + window-pointer granularity (measured best)
TILE_T = 5  # per-tile candidates kept (see saturation flag, module doc)
QSUB = 256  # query sub-tile rows per grid step (2 MXU row blocks)
QC = 512  # fused query-chunk width
# max docs a fused shard may hold (docid bit budget of the window sort key)
MAX_DOCS_FUSED = (1 << 21) - 2 * TILE_N
# relative slack of the split-bf16 SELECTION tier vs the canonical f32
# rescore. The dense tier runs TWO logical passes (Wh@T16 + Wh@T16lo):
# the tier side carries ~15 mantissa bits while the query-weight side is
# bf16-truncated, so the error is dominated by |W - Wh| ~ 2^-9 relative —
# measured max 7.4e-3 on bench-shaped operands at 1M docs; 8e-3 is the
# bound the safety flag uses. (Round 4 ran three passes at 2e-4; round 5
# trades the third [Qc,N] matmul pass — ~7.7 ms/chunk — for a deeper
# KB=64 candidate margin, which the measured k10..k64 gap covers.) The
# split MUST be built by integer masking: the runtime compiles with
# --xla_allow_excess_precision=true, which lets XLA elide
# f32->bf16->f32 round-trips, so `t - bf16(t)` folds to zero and an
# astype-based split silently degenerates to one bf16 pass (measured).
EPS_SPLIT = 8e-3


def _mask_hi(t):
    """Truncate to the top 16 bits (sign+exp+7-bit mantissa): an exactly
    bf16-representable f32 that XLA cannot constant-fold away."""
    bits = jax.lax.bitcast_convert_type(t, jnp.int32)
    return jax.lax.bitcast_convert_type(
        bits & jnp.int32(-65536), jnp.float32
    )


_I0 = np.int32(0)  # index-map constant: python ints trace to i64 under x64


def fused_enabled() -> str:
    """'0' | 'auto' | 'force' — force enables on CPU (interpret, tests)."""
    return os.environ.get("ES_TPU_FUSED", "auto")


def fused_topk_enabled() -> bool:
    """ES_TPU_FUSED_TOPK (default on): run the dense-tier matmul INSIDE the
    Pallas kernel, so the [Qc, N] score matrix lives only as per-tile VMEM
    transients and the running top-t selection never round-trips HBM.
    '0' reverts to the round-5 out-of-kernel matmul (scores materialized
    in HBM, kernel reads tiles of them)."""
    return os.environ.get("ES_TPU_FUSED_TOPK", "auto") != "0"


def _key_bits(n_pad: int, qsub: int, nsub: int):
    qb = int(np.log2(qsub))
    db = max(1, int(np.ceil(np.log2(max(n_pad + 1, 2)))))
    sb = qb + db
    nsb = max(1, int(np.ceil(np.log2(max(nsub, 2)))))
    if sb + nsb > 31:
        raise ValueError("fused window key overflow: shard too large")
    return qb, db, sb


def _topk_rounds(cand_v, cand_i, k):
    """Exact top-k of a candidate row-set by (value desc, id asc): k unrolled
    (max, argmin-id, mask) rounds — VPU reduce/selects, no sort. Same
    contract as ops.kernels._merge_topk."""
    out_v, out_i = [], []
    big = jnp.int32(2**31 - 1)
    for _ in range(k):
        vmax = jnp.max(cand_v, axis=1, keepdims=True)
        ismax = cand_v == vmax
        imin = jnp.min(jnp.where(ismax, cand_i, big), axis=1, keepdims=True)
        out_v.append(vmax)
        out_i.append(imin)
        cand_v = jnp.where(ismax & (cand_i == imin), -jnp.inf, cand_v)
    return jnp.concatenate(out_v, axis=1), jnp.concatenate(out_i, axis=1)


def _cfg_tile() -> int:
    """Coarse tile width; env-overridable for geometry sweeps."""
    return int(os.environ.get("ES_TPU_FUSED_TILE", TILE_N))


def auto_tile_matmul(vp2: int, qsub: int) -> int:
    """Tile width for the in-kernel-matmul mode: the double-buffered
    [vp2, tile] bf16 tier block + f32 sacc + dense transient must fit the
    ~64MB scoped VMEM budget with headroom for the window blocks. At the
    bench shape (V=896 -> vp2=1792, qsub=256) this lands on 4096."""
    budget = 40 * 1024 * 1024
    fixed = 2 * qsub * vp2 * 2  # double-buffered [qsub, vp2] weight block
    per_col = 2 * vp2 * 2 + 8 * qsub  # tier (x2 buffers) + sacc + dense
    tile = (budget - fixed) // max(per_col, 1)
    return max(FINE_N, min(TILE_N, (tile // FINE_N) * FINE_N))


def _cfg_qsub() -> int:
    """Query sub-tile rows per grid step; env-overridable for sweeps."""
    return int(os.environ.get("ES_TPU_FUSED_QSUB", QSUB))


def tile_t_for(njc: int) -> int:
    """Per-tile candidate count. A tile's share of the top-K' is
    ~Binomial(KB, 1/njc) under exchangeable doc placement, so t is sized
    mean + 5*sigma-ish + slack to keep the saturation-flag rate negligible
    (t=11 at njc=5 measured ~20% flagged; this formula gives 23 there and
    6 at njc=245). t = KB+1 can never flag or lose (a tile holding the
    whole top-K' still keeps K'+1 candidates)."""
    t = int(os.environ.get("ES_TPU_FUSED_T", 0))
    if t > 0:
        return t
    if njc <= 1:
        return KB + 1
    mu = KB / njc
    import math

    return max(TILE_T, min(KB + 1, math.ceil(mu + 5 * math.sqrt(mu) + 4)))


def _fused_kernel(
    ptr_ref,  # scalar prefetch [nsub*(njf+1)] i32 exact fine window starts
    ptrb_ref,  # scalar prefetch [nsub*(njc+1)] i32 coarse window block idx
    *refs,
    # matmul=False refs: (scores [QSUB, tile_n] bf16|f32, live [1, tile_n]
    #   f32, keya/keyb/vala/valb [bud, 128] i32, cv [1, QSUB, t] f32,
    #   ci [1, QSUB, t] i32, ot [QSUB, 1] f32, of [QSUB, 1] f32,
    #   sacc VMEM [QSUB, tile_n] f32, cnt/ovf VMEM [QC, 1] f32)
    # matmul=True: scores is replaced by (w [QSUB, Vp2] bf16 split-bf16
    #   query weights [Wh | Wh], tstack [Vp2, tile_n] bf16 [T16; T16lo]):
    #   the dense tile is computed HERE on the MXU, so the [Qc, N] score
    #   matrix never exists outside VMEM (ES_TPU_FUSED_TOPK tentpole)
    t, tile_n, fine_n, bud, qsub, qb, db, sb, njc, njf, matmul,
):
    if matmul:
        (w_ref, tier_ref, live_ref, keya_ref, keyb_ref, vala_ref, valb_ref,
         cv_ref, ci_ref, ot_ref, of_ref, sacc, cnt, ovf) = refs
    else:
        (scores_ref, live_ref, keya_ref, keyb_ref, vala_ref, valb_ref,
         cv_ref, ci_ref, ot_ref, of_ref, sacc, cnt, ovf) = refs
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((j == 0) & (i == 0))
    def _():
        cnt[:] = jnp.zeros_like(cnt)
        ovf[:] = jnp.zeros_like(ovf)

    # ---- candidate window: two consecutive bud-row blocks ----------------
    # One coarse step owns the sorted-entry range [ptr[i, j*fine],
    # ptr[i, (j+1)*fine]) — contiguous because the sort key is
    # (subtile | docid | qlow). The pipeline streams the two bud-row blocks
    # around its start; rows are walked with EXACT fori_loop bounds per
    # fine sub-tile (no per-row gating), and per-entry masks handle block
    # edges, foreign subtiles, and sentinel padding. A range outside the
    # 2*bud resident rows loses its tail -> overflow flag -> rerun.
    fine = tile_n // fine_n
    wrow0 = ptrb_ref[i * (njc + 1) + j] * bud
    qrow = jax.lax.broadcasted_iota(jnp.int32, (qsub, 128), 0)
    nrow = jax.lax.broadcasted_iota(jnp.int32, (fine_n, 128), 0)
    one = jnp.float32(1.0)
    zero = jnp.float32(0.0)
    dn = (((1,), (1,)), ((), ()))
    sacc[...] = jnp.zeros_like(sacc)
    lost = jnp.bool_(False)
    for f in range(fine):
        basef = i * (njf + 1) + j * fine + f
        start = ptr_ref[basef]
        end = ptr_ref[basef + 1]
        # >> 7 == // 128: Mosaic's scalar floor_divide lowering recurses
        # infinitely under x64 (measured; shifts lower cleanly)
        ra = jnp.maximum((start >> 7) - wrow0, 0)
        rb_need = ((end + 127) >> 7) - wrow0
        two_bud = np.int32(2 * bud)
        rb = jnp.minimum(jnp.maximum(rb_need, ra), two_bud)
        lost = lost | (rb_need > two_bud)
        base_doc = (j * fine + f) * fine_n
        col0 = f * fine_n  # static python int: pl.ds lowers it as a literal

        # ---- one-hot expansion: the MXU as a segmented scatter-add ------
        def _row(key_ref, val_ref, off_r, c):
            key = key_ref[pl.ds(c - off_r, 1), :]  # [1, 128]
            val = jax.lax.bitcast_convert_type(
                val_ref[pl.ds(c - off_r, 1), :], jnp.float32
            )
            qlow = key & (qsub - 1)
            doc = jax.lax.shift_right_logical(
                key, jnp.int32(qb)
            ) & ((1 << db) - 1)
            off = doc - base_doc
            inwin = (
                (jax.lax.shift_right_logical(key, jnp.int32(sb)) == i)
                & (off >= 0)
                & (off < fine_n)
            )
            At = jnp.where((qrow == qlow) & inwin, val, zero)  # [qsub, 128]
            D = jnp.where((nrow == off) & inwin, one, zero).astype(
                jnp.bfloat16
            )  # [fine_n, 128]
            # split-bf16 weights (masked — see EPS_SPLIT note): hi + lo
            # carries ~15 mantissa bits through two bf16 MXU passes with
            # f32 accumulation, keeping selection within EPS_SPLIT of the
            # canonical f32 rescore
            Ahf = _mask_hi(At)
            Ah = Ahf.astype(jnp.bfloat16)
            Al = (At - Ahf).astype(jnp.bfloat16)
            sacc[:, pl.ds(col0, fine_n)] += jax.lax.dot_general(
                Ah, D, dn, preferred_element_type=jnp.float32
            ) + jax.lax.dot_general(
                Al, D, dn, preferred_element_type=jnp.float32
            )  # [qsub, fine_n]

        jax.lax.fori_loop(
            ra, jnp.minimum(rb, bud),
            lambda c, _, : _row(keya_ref, vala_ref, 0, c) or 0, 0,
        )
        jax.lax.fori_loop(
            jnp.maximum(ra, bud), rb,
            lambda c, _, : _row(keyb_ref, valb_ref, bud, c) or 0, 0,
        )

    if matmul:
        # 2-pass split-bf16 selection fused with the scan: [Wh | Wh] @
        # [T16; T16lo] accumulates Wh@T16 + Wh@T16lo in f32 on the MXU —
        # same EPS_SPLIT error contract as the out-of-kernel form, but the
        # [QSUB, tile_n] result is a VMEM transient, not HBM traffic
        dense = jax.lax.dot_general(
            w_ref[:], tier_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        dense = scores_ref[:].astype(jnp.float32)
    lv = live_ref[0:1, :] > 0
    total = dense + sacc[...]
    total = jnp.where(lv & (total > 0), total, -jnp.inf)
    ids = j * tile_n + jax.lax.broadcasted_iota(jnp.int32, total.shape, 1)

    rs = pl.ds(i * qsub, qsub)
    cnt[rs] += jnp.sum(
        total > 0, axis=1, keepdims=True, dtype=jnp.float32
    )
    ovf[rs] += jnp.broadcast_to(lost.astype(jnp.float32), (qsub, 1))

    # ---- per-tile top-t: the ONLY selection done in-kernel ---------------
    tv, ti = _topk_rounds(total, ids, t)
    cv_ref[_I0] = tv
    ci_ref[_I0] = ti

    @pl.when(j == njc - 1)
    def _():
        ot_ref[:] = cnt[rs]
        of_ref[:] = ovf[rs]


@functools.partial(
    jax.jit,
    static_argnames=("t", "tile_n", "fine_n", "bud", "qsub", "interpret"),
)
def fused_tile_candidates(
    scores,  # [Qc, Npad] bf16 | f32 dense-tier scores (padding cols = 0),
    #         OR None with (w, tstack) set: the matmul runs in-kernel
    live,  # [1, Npad] f32 (0 for dead/padding)
    keys,  # [Gpad/128, 128] i32 sorted window keys; rows % bud == 0, with
    #       >= 2*bud trailing sentinel rows (key = int32 max)
    vals,  # [Gpad/128, 128] i32 f32-bits of the per-posting partial scores
    ptr,  # [nsub*(njf+1)] i32 window starts (entry index) into keys/vals
    w=None,  # [Qc, Vp2] bf16 [Wh | Wh] split query weights (matmul mode)
    tstack=None,  # [Vp2, Npad] bf16 [T16; T16lo] stacked tier (matmul mode)
    *,
    t,
    bud,
    tile_n=TILE_N,
    fine_n=FINE_N,
    qsub=QSUB,
    interpret=False,
):
    """-> (cand_v [Qc, njc*t] f32, cand_i [Qc, njc*t] i32, totals [Qc] i32,
    window_lost [Qc] bool). Per-tile top-t candidates by split-bf16
    selection (see EPS_SPLIT); totals exact. The global merge + saturation
    flag happen in the caller. With (w, tstack) instead of scores, the
    dense matmul happens inside the kernel per doc tile (the
    ES_TPU_FUSED_TOPK default): one grid step streams a [Vp2, tile_n] tier
    block and a [qsub, Vp2] weight block through the MXU instead of
    reading a precomputed score tile from HBM."""
    matmul = scores is None
    if matmul:
        qc, vp2 = w.shape
        n_pad = tstack.shape[1]
    else:
        qc, n_pad = scores.shape
    assert qc % qsub == 0 and n_pad % tile_n == 0 and tile_n % fine_n == 0
    nsub = qc // qsub
    njc = n_pad // tile_n
    njf = n_pad // fine_n
    fine = tile_n // fine_n
    qb, db, sb = _key_bits(n_pad, qsub, nsub)
    kernel = functools.partial(
        _fused_kernel,
        t=t, tile_n=tile_n, fine_n=fine_n, bud=bud, qsub=qsub,
        qb=qb, db=db, sb=sb, njc=njc, njf=njf, matmul=matmul,
    )
    nblk = keys.shape[0] // bud
    # coarse window start block (units of bud rows), from the fine ptr
    coarse_start = ptr.reshape(nsub, njf + 1)[:, ::fine]
    ptrb = jnp.minimum(
        coarse_start.reshape(-1) // 128 // bud, nblk - 2
    ).astype(jnp.int32)
    if matmul:
        score_specs = [
            pl.BlockSpec((qsub, vp2), lambda j, i, *_: (i, _I0)),
            pl.BlockSpec((vp2, tile_n), lambda j, i, *_: (_I0, j)),
        ]
        score_ops = (w, tstack)
    else:
        score_specs = [pl.BlockSpec((qsub, tile_n), lambda j, i, *_: (i, j))]
        score_ops = (scores,)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(njc, nsub),
        in_specs=score_specs + [
            pl.BlockSpec((1, tile_n), lambda j, i, *_: (_I0, j)),
            pl.BlockSpec(
                (bud, 128),
                lambda j, i, ptr, ptrb: (ptrb[i * (njc + 1) + j], _I0),
            ),
            pl.BlockSpec(
                (bud, 128),
                lambda j, i, ptr, ptrb: (ptrb[i * (njc + 1) + j] + 1, _I0),
            ),
            pl.BlockSpec(
                (bud, 128),
                lambda j, i, ptr, ptrb: (ptrb[i * (njc + 1) + j], _I0),
            ),
            pl.BlockSpec(
                (bud, 128),
                lambda j, i, ptr, ptrb: (ptrb[i * (njc + 1) + j] + 1, _I0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, qsub, t), lambda j, i, *_: (j, i, _I0)),
            pl.BlockSpec((1, qsub, t), lambda j, i, *_: (j, i, _I0)),
            pl.BlockSpec((qsub, 1), lambda j, i, *_: (i, _I0)),
            pl.BlockSpec((qsub, 1), lambda j, i, *_: (i, _I0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((qsub, tile_n), jnp.float32),
            pltpu.VMEM((qc, 1), jnp.float32),
            pltpu.VMEM((qc, 1), jnp.float32),
        ],
    )
    cv, ci, ot, of = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((njc, qc, t), jnp.float32),
            jax.ShapeDtypeStruct((njc, qc, t), jnp.int32),
            jax.ShapeDtypeStruct((qc, 1), jnp.float32),
            jax.ShapeDtypeStruct((qc, 1), jnp.float32),
        ],
        # v5e has 128MB of physical VMEM; Mosaic's default 16MB scoped
        # budget double-counts per-region transients
        compiler_params=(
            None if interpret else pltpu.CompilerParams(
                vmem_limit_bytes=64 * 1024 * 1024
            )
        ),
        interpret=interpret,
    )(ptr, ptrb, *score_ops, live, keys, keys, vals, vals)
    cv = jnp.swapaxes(cv, 0, 1).reshape(qc, njc * t)
    ci = jnp.swapaxes(ci, 0, 1).reshape(qc, njc * t)
    return cv, ci, ot[:, 0].astype(jnp.int32), of[:, 0] > 0


# ---------------------------------------------------------------------------
# canonical rescore: THE score function both precisions rank by
# ---------------------------------------------------------------------------


def canonical_rescore(
    tier,  # [V, Npad] f32 dense tfn rows (or None)
    dense_rows,  # [Q, Td] i32 (pad row 0 with weight 0)
    dense_w,  # [Q, Td] f32
    row_q,  # [R] i32 owner query of each CSR block row
    docids,  # [R, BLOCK] i32 gathered postings (pad: docid >= n)
    parts,  # [R, BLOCK] f32 per-posting partial scores
    cand_i,  # [Q, KB] i32 kernel winners
    cand_ok,  # [Q, KB] bool valid lanes
):
    """Exact f32 score of each candidate, computed identically by every path:
    dense part by per-(query, dense-term, winner) tier lookups summed in plan
    order; sparse part by comparison-reduce over the gathered posting rows
    and a one-hot f32 matmul segment-sum over block rows. Each (term, doc)
    contributes at most one posting, so the inner reductions add exact zeros
    everywhere but one slot and the result does not depend on padding."""
    Q, kb = cand_i.shape
    if tier is not None and dense_rows.shape[1] > 0:
        dg = tier[dense_rows[:, :, None], cand_i[:, None, :]]  # [Q, Td, KB]
        dsum = jnp.sum(dense_w[:, :, None] * dg, axis=1)
    else:
        dsum = jnp.zeros((Q, kb), jnp.float32)
    if docids.shape[0] > 1:
        win_row = cand_i[row_q]  # [R, KB] winners of each row's owner query
        eq = docids[:, :, None] == win_row[:, None, :]
        row_sum = jnp.sum(
            jnp.where(eq, parts[:, :, None], 0.0), axis=1
        )  # [R, KB]
        qrow = jax.lax.broadcasted_iota(jnp.int32, (Q, docids.shape[0]), 0)
        onehot = (qrow == row_q[None, :]).astype(jnp.float32)
        # [Q, R] @ [R, KB]: segment-sum of row contributions by owner query.
        # Each (q, winner) cell receives <= one nonzero per sparse term.
        ssum = jnp.matmul(onehot, row_sum, precision=jax.lax.Precision.HIGHEST)
    else:
        ssum = jnp.zeros((Q, kb), jnp.float32)
    return jnp.where(cand_ok, dsum + ssum, -jnp.inf)


# ---------------------------------------------------------------------------
# host planning + device pipeline
# ---------------------------------------------------------------------------


class FusedPlan:
    """Host-side per-chunk inputs. Block-row-major: instead of the legacy
    [Q, Ts, B] padded layout (~84% padding at Zipf query mixes), the sparse
    side is one flat list of REAL CSR block rows with an owner query and a
    term weight per row — no per-query shape bucketing at all. R and Td pad
    to powers of two so every batch reuses a tiny compiled-shape family."""

    __slots__ = ("W", "rows", "row_q", "row_w", "dense_rows", "dense_w",
                 "k", "nreal")

    def __init__(self, W, rows, row_q, row_w, dense_rows, dense_w, k,
                 nreal=0):
        self.W = W
        self.rows = rows
        self.row_q = row_q
        self.row_w = row_w
        self.dense_rows = dense_rows
        self.dense_w = dense_w
        self.k = k
        self.nreal = nreal


def plan_fused(pack, fld, queries, k, qc=QC):
    """queries: per query a list of (term, boost); -> FusedPlan padded to
    qc query rows."""
    from .scoring import bm25_idf

    V = pack.dense_tfn.shape[0] if pack.dense_tfn is not None else 0
    Q = len(queries)
    doc_count = pack.field_stats.get(fld, {}).get("doc_count") or pack.num_docs
    rows_l, rowq_l, roww_l = [], [], []
    dense_l = []
    td_max = 1
    for qi, terms in enumerate(queries):
        dlist = []
        for term, boost in terms:
            s0, nb, df = pack.term_blocks(fld, term)
            if df <= 0:
                continue
            w = boost * bm25_idf(doc_count, df)
            dr = pack.dense_row_of(fld, term)
            if dr is not None:
                dlist.append((dr, w))
            elif nb > 0:
                rows_l.append(np.arange(s0, s0 + nb, dtype=np.int32))
                rowq_l.append(np.full(nb, qi, np.int32))
                roww_l.append(np.full(nb, w, np.float32))
        dense_l.append(dlist)
        td_max = max(td_max, len(dlist))
    nreal = sum(len(r) for r in rows_l)
    # quantize R in pow2 steps: every distinct R is a fresh XLA compile
    # (~15s through the remote compile service, persistent-cached), and
    # Zipf batches flap across boundaries often enough to thrash a finer
    # quantization. (4x steps — the round-3 choice — left the device
    # sorting ~2x more entries than real on average; the sort is a top-3
    # chunk cost, so the extra compile variants pay for themselves.)
    R = 64
    while R < nreal:
        R *= 2
    rows = np.zeros(R, np.int32)  # row 0 of the pack = all-padding block
    row_q = np.zeros(R, np.int32)
    row_w = np.zeros(R, np.float32)
    if nreal:
        rows[:nreal] = np.concatenate(rows_l)
        row_q[:nreal] = np.concatenate(rowq_l)
        row_w[:nreal] = np.concatenate(roww_l)
    Td = 1 << (max(td_max, 4) - 1).bit_length()
    dense_rows = np.zeros((qc, Td), np.int32)
    dense_w = np.zeros((qc, Td), np.float32)
    for qi, dlist in enumerate(dense_l):
        for ti, (dr, w) in enumerate(dlist):
            dense_rows[qi, ti] = dr
            dense_w[qi, ti] = w
    # W ([qc, V] dense query weights) is NOT materialized host-side:
    # the pipeline rebuilds it on device from (dense_rows, dense_w)
    return FusedPlan(None, rows, row_q, row_w, dense_rows, dense_w, k,
                     nreal=nreal)


def _fused_pipeline(
    fa,  # device dict: tier16/tier32 [V, n_pad], live [1, n_pad], post_*
    avgdl,  # () f32 — a TRACED arg: baking this per-pack float into the
    #         HLO caused a fresh ~200 s remote compile per shard in the
    #         C5 bench (every shard's avgdl differs slightly)
    rows, row_q, row_w, dense_rows, dense_w,
    *,
    k, n, n_pad, has_norms, k1, b, bud, t, tile_n, interpret,
    qsub=QSUB,
    inkernel=False,
):
    """One fused chunk, fully on device. -> (v [Q,k], i, totals, flags)."""
    qc = dense_rows.shape[0]
    # the dense query-weight matrix is ~99.6% zeros (<= Td terms of V per
    # query): build it ON DEVICE from the tiny (dense_rows, dense_w)
    # pairs instead of shipping [Qc, V] f32 through the tunnel — the
    # upload was the dominant batch cost (round 5: ~1.8 MB x 8 chunks at
    # ~100 MB/s tunnel bandwidth). Duplicate dense terms of one query
    # sum, exactly like the host-side accumulation did.
    V = fa["tier32"].shape[0]
    W = jnp.sum(
        jax.nn.one_hot(dense_rows, V, dtype=jnp.float32)
        * dense_w[:, :, None],
        axis=1,
    )
    R = rows.shape[0]
    nsub = qc // qsub
    njf = n_pad // FINE_N
    njc = n_pad // tile_n
    qb, db, sb = _key_bits(n_pad, qsub, nsub)

    # phase A: gather CSR block rows, per-posting partial scores
    docids = fa["post_docids"][rows]  # [R, BLOCK]
    tfs = fa["post_tfs"][rows]
    if has_norms:
        dls = fa["post_dls"][rows]
        denom = tfs + k1 * (1.0 - b + b * dls / avgdl)
    else:
        denom = tfs + k1
    parts = row_w[:, None] * tfs / denom  # [R, BLOCK]; pad lanes -> 0

    # window sort key: (query subtile | docid | query low bits)
    q2 = row_q[:, None]
    key = (
        ((q2 >> qb) << sb)
        | (docids << qb)
        | (q2 & (qsub - 1))
    )
    # padding lanes (docid >= n, tf == 0) take the sentinel key: without
    # this they all fall into the LAST doc tile's window (docid == n is in
    # range) and their ~30% mass overflows it, flagging every query
    key = jnp.where(docids >= n, jnp.int32(2**31 - 1), key)
    skey, sval = jax.lax.sort(
        (key.reshape(-1), parts.reshape(-1)), num_keys=1
    )
    bounds = (
        (jnp.arange(nsub, dtype=jnp.int32)[:, None] << sb)
        | (jnp.arange(njf + 1, dtype=jnp.int32)[None, :] * FINE_N << qb)
    )
    ptr = jnp.searchsorted(skey, bounds.reshape(-1)).astype(jnp.int32)
    bude = bud * 128
    pad_n = 2 * bude + (-(skey.shape[0] + 2 * bude)) % bude
    sent = jnp.full((pad_n,), jnp.int32(2**31 - 1))
    keys2 = jnp.concatenate([skey, sent]).reshape(-1, 128)
    vals2 = jnp.concatenate(
        [jax.lax.bitcast_convert_type(sval, jnp.int32), sent]
    ).reshape(-1, 128)

    # dense SELECTION tier, 2-pass split-bf16 (Wh@T16 + Wh@T16lo as one
    # stacked matmul): the tier side keeps ~15 mantissa bits; the
    # remaining error is the bf16 truncation of the query weights
    # (~2^-9 relative, EPS_SPLIT bounds it at 8e-3) — covered by the
    # KB=64 candidate margin + canonical rescore + safety flag. Round
    # 4's third pass (Wl@T16, 2e-4 error) cost ~7.7 ms/chunk of pure
    # MXU time for precision the wider margin makes redundant.
    Wh = _mask_hi(W).astype(jnp.bfloat16)
    if "tier16_stack" in fa:
        W2 = jnp.concatenate([Wh, Wh], axis=1)  # [Qc, 2V]
        vp2 = fa["tier16_stack"].shape[0]
        if vp2 > W2.shape[1]:  # stack rows are lane-padded (see _arrays)
            W2 = jnp.pad(W2, ((0, 0), (0, vp2 - W2.shape[1])))
        if inkernel:
            # ES_TPU_FUSED_TOPK default: the dense matmul runs inside the
            # kernel per doc tile; no [Qc, N] score array exists at all
            cv, ci, totals, wlost = fused_tile_candidates(
                None, fa["live"], keys2, vals2, ptr,
                w=W2, tstack=fa["tier16_stack"],
                t=t, bud=bud, tile_n=tile_n, qsub=qsub, interpret=interpret,
            )
            scores = None
        else:
            scores = jnp.matmul(
                W2, fa["tier16_stack"], preferred_element_type=jnp.float32,
            )
    else:
        scores = (
            jnp.matmul(Wh, fa["tier16"], preferred_element_type=jnp.float32)
            + jnp.matmul(
                Wh, fa["tier16_lo"], preferred_element_type=jnp.float32
            )
        )
    if scores is not None:
        cv, ci, totals, wlost = fused_tile_candidates(
            scores, fa["live"], keys2, vals2, ptr,
            t=t, bud=bud, tile_n=tile_n, qsub=qsub, interpret=interpret,
        )

    # global top-K' over the per-tile candidates. An i64 (score, docid)
    # rank-key top_k over the WIDE candidate matrix costs ~13 ms/chunk;
    # instead: f32 top_k by value with a 16-deep margin (~3 ms), then the
    # exact i64 rank order within that margin set. Docid-order selection
    # can only go wrong if a bit-identical value-tie cluster at the K'-th
    # value extends past the margin (pattern ties are common in Zipf
    # corpora — value-boundary ties alone flagged 20-27% of smoke
    # queries); that residue is flagged (tie_clip) and escalates.
    kb_eff = min(KB, cv.shape[1])
    m_eff = min(kb_eff + 16, cv.shape[1])
    mv, sel = jax.lax.top_k(cv, m_eff)
    mi = jnp.take_along_axis(ci, sel, axis=1)
    kv, ki = rank_topk(mv, mi, kb_eff)
    cand_ok = kv > -jnp.inf
    vstar = kv[:, kb_eff - 1 : kb_eff]
    n_at_vstar = jnp.sum(cv == vstar, axis=1)
    n_in_margin = jnp.sum(mv == vstar, axis=1)
    tie_clip = jnp.isfinite(vstar[:, 0]) & (n_at_vstar > n_in_margin)

    # saturation flag: if a tile contributed >= t of the K' winners it may
    # have dropped entries that also belonged in the K' set (module doc
    # has the proof sketch)
    tiles = ki // tile_n
    same_tile = (
        (tiles[:, :, None] == tiles[:, None, :])
        & cand_ok[:, :, None]
        & cand_ok[:, None, :]
    )
    sat = jnp.any(
        cand_ok & (jnp.sum(same_tile, axis=2) >= t), axis=1
    ) | tie_clip

    # canonical rescore + final ranking + safety test
    resc = canonical_rescore(
        fa["tier32"], dense_rows, dense_w, row_q, docids, parts, ki, cand_ok
    )
    v, i = rank_topk(resc, ki, k)
    am_kernel = kv[:, -1]
    am_resc = jnp.min(jnp.where(cand_ok, resc, jnp.inf), axis=1)
    rk = v[:, k - 1]
    bound = am_kernel + EPS_SPLIT * jnp.abs(am_kernel)
    safe = jnp.isneginf(am_kernel) | (rk > bound) | (rk == am_resc)
    return v, i, totals, wlost | sat | ~safe


class FusedTermSearcher:
    """Batched `_msearch` over one shard pack through the fused kernel.

    Wraps a BatchTermSearcher for planning metadata and as the last-resort
    fallback; chunks query batches to QC rows; flagged queries escalate
    bf16 -> f32 scores -> legacy path. All chunks of a call resolve with one
    device round-trip (remote-runtime dispatch-barrier discipline, see
    ops/batched._RawChunks)."""

    def __init__(self, bts):
        self.bts = bts  # BatchTermSearcher
        self.searcher = bts.searcher
        self._cache = {}
        self._fa = None
        self._fa_live_of = None
        # geometry snapshot: taken ONCE here so a mid-process env change
        # (ES_TPU_FUSED_TILE/QSUB/T sweeps) can never mismatch a cached
        # compiled pipeline against freshly padded arrays (ADVICE r4 #3)
        self._tile_n = _cfg_tile()
        self._qsub = _cfg_qsub()
        self._t_env = int(os.environ.get("ES_TPU_FUSED_T", 0))
        # in-kernel matmul mode (ES_TPU_FUSED_TOPK, default ON): needs the
        # stacked tier layout, and a tile width whose tier block fits VMEM
        pack = self.searcher.pack
        V = pack.dense_tfn.shape[0] if pack.dense_tfn is not None else 0
        self._vp2 = -(-2 * V // 128) * 128  # lane-padded [T16; T16lo] rows
        if (fused_topk_enabled() and V
                and os.environ.get("ES_TPU_FUSED_TILE") is None):
            self._tile_n = min(
                self._tile_n, auto_tile_matmul(self._vp2, self._qsub))
        n_pad = -(-pack.num_docs // self._tile_n) * self._tile_n
        self._use_stack = (
            os.environ.get("ES_TPU_FUSED_STACK", "1") != "0"
            and self._vp2 * n_pad * 2 <= 6 * 1024**3
        )
        self._inkernel = fused_topk_enabled() and self._use_stack and V > 0

    @staticmethod
    def usable(pack, k) -> bool:
        mode = fused_enabled()
        if mode == "0" or pltpu is None:
            return False
        if pack.dense_tfn is None:
            return False
        if not (0 < k <= 16) or pack.num_docs > MAX_DOCS_FUSED:
            return False
        if mode == "force":
            return True
        return (
            jax.default_backend() == "tpu"
            and pack.num_docs >= 4 * FINE_N
        )

    def _arrays(self):
        dev = self.searcher.dev
        tile_n = self._tile_n
        n = self.searcher.pack.num_docs
        n_pad = ((n + tile_n - 1) // tile_n) * tile_n
        padw = n_pad - n
        if self._fa is None:
            # HBM budget: the f32 tier stays SHARED with the legacy path
            # (unpadded — the rescore only gathers from it); only the
            # bf16 hi/lo pair is padded for the matmul. One fused jit so
            # the padded f32 intermediate is a transient, not a resident.
            self._fa = {
                "tier32": dev["dense_tfn"],
                "post_docids": dev["post_docids"],
                "post_tfs": dev["post_tfs"],
                "post_dls": dev["post_dls"],
            }
            V = dev["dense_tfn"].shape[0]
            # [vp2, n_pad] stacked tier [T16; T16lo] (rows lane-padded to
            # 128 so the in-kernel matmul's blocks tile cleanly) -> ONE
            # dense matmul per chunk (out-of-kernel mode) or the kernel's
            # per-tile operand (in-kernel mode, ES_TPU_FUSED_TOPK); gate
            # on the stack staying inside a 16 GB chip alongside tier32,
            # postings, and per-execution score workspaces. Built by ONE
            # jit straight from the f32 tier so the hi/lo parts never
            # materialize as separate resident arrays (peak = tier32 +
            # stack, not + 2 intermediate copies).
            use_stack = self._use_stack
            rpad = self._vp2 - 2 * V

            @jax.jit
            def split(t):
                tp = jnp.pad(t, ((0, 0), (0, padw)))
                hif = _mask_hi(tp)
                hi = hif.astype(jnp.bfloat16)
                lo = (tp - hif).astype(jnp.bfloat16)
                if use_stack:
                    st = jnp.concatenate([hi, lo], axis=0)
                    return (jnp.pad(st, ((0, rpad), (0, 0))),)
                return hi, lo

            if use_stack:
                (self._fa["tier16_stack"],) = split(dev["dense_tfn"])
            else:
                hi, lo = split(dev["dense_tfn"])
                self._fa["tier16"] = hi
                self._fa["tier16_lo"] = lo
        # tiered refresh re-ships dev["live"] (StackedSearcher.update_live)
        # — rebuild the padded copy whenever the device buffer changes so a
        # long-lived fused searcher never scores deleted docs. The cache
        # key is the buffer OBJECT (held, so its id cannot be recycled).
        if self._fa_live_of is not dev["live"]:
            self._fa["live"] = jnp.pad(
                dev["live"].astype(jnp.float32), (0, padw)
            )[None, :]
            self._fa_live_of = dev["live"]
        return self._fa

    def _compiled_scan(self, fld, C, R, Td, k, nreal, interpret):
        """One EXECUTABLE for a whole C-chunk batch: lax.scan runs the
        per-chunk pipeline sequentially inside a single program, so the
        remote runtime's per-execution overhead (~30-100 ms on programs
        touching multi-GB operands — BENCH_NOTES.md, measured again in
        round 5 as the entire 34 ms/chunk wall-vs-device gap) is paid
        once per BATCH instead of once per chunk."""
        pack = self.searcher.pack
        n = pack.num_docs
        tile_n = self._tile_n
        qsub = self._qsub
        n_pad = ((n + tile_n - 1) // tile_n) * tile_n
        njc = n_pad // tile_n
        t = self._t_env if self._t_env > 0 else tile_t_for(njc)
        # window sizing follows the REAL posting count (R counts padded
        # slots — up to ~40% at Zipf loads, which doubles the budget for
        # nothing), quantized in pow2 steps so batch-to-batch jitter cannot
        # flap the compile key; floor 2048 entries: [bud, 128] blocks need
        # >= 8 sublanes
        nreal_q = 1 << max(nreal - 1, 1).bit_length()
        mean_win = max(1, nreal_q * BLOCK // ((QC // qsub) * njc))
        bude = min(
            64 * 1024, max(2048, 1 << (2 * mean_win - 1).bit_length())
        )
        bud = bude // 128
        key = (fld, C, R, Td, k, interpret, bud, tile_n, qsub, t,
               self._inkernel)
        fn = self._cache.get(key)
        from ..monitoring.device import note_executable_cache

        note_executable_cache("fused_scan", fn is not None)
        if fn is None:
            kw = dict(
                k=k, n=n, n_pad=n_pad,
                has_norms=fld in self.searcher.ctx.has_norms,
                k1=1.2, b=0.75,
                bud=bud, t=t, tile_n=tile_n, qsub=qsub,
                interpret=interpret, inkernel=self._inkernel,
            )

            def scan_pipeline(fa, avgdl, rows, row_q, row_w, dr, dw):
                def body(carry, xs):
                    return carry, _fused_pipeline(fa, avgdl, *xs, **kw)

                _, outs = jax.lax.scan(
                    body, 0, (rows, row_q, row_w, dr, dw))
                return outs

            fn = jax.jit(scan_pipeline)
            self._cache[key] = fn
        return fn

    def _dispatch_batch(self, fld, queries, k):
        """Plan + launch one query batch WITHOUT fetching: chunks are
        planned, padded to one (R, Td) envelope, and executed as ONE
        scanned program (_compiled_scan). Returns (idxs, device outs)
        for _collect_batch."""
        Q = len(queries)
        idxs = [np.arange(s, min(s + QC, Q)) for s in range(0, Q, QC)]
        # planning is serial host work ahead of the ONE dispatch; across
        # a multi-batch wave (msearch_many) batch k+1's planning overlaps
        # batch k's device execution because dispatch does not block
        plans = [plan_fused(self.searcher.pack, fld,
                            [queries[i] for i in qidx], k)
                 for qidx in idxs]
        C = len(plans)
        R = max(p.rows.shape[0] for p in plans)
        Td = max(p.dense_rows.shape[1] for p in plans)
        nreal = max(p.nreal for p in plans)

        def _padr(a, width):
            return np.pad(a, [(0, width - a.shape[0])] + [(0, 0)] * (
                a.ndim - 1))

        rows = np.stack([_padr(p.rows, R) for p in plans])
        row_q = np.stack([_padr(p.row_q, R) for p in plans])
        row_w = np.stack([_padr(p.row_w, R) for p in plans])
        dr = np.stack([
            np.pad(p.dense_rows, ((0, 0), (0, Td - p.dense_rows.shape[1])))
            for p in plans])
        dw = np.stack([
            np.pad(p.dense_w, ((0, 0), (0, Td - p.dense_w.shape[1])))
            for p in plans])
        interpret = jax.default_backend() != "tpu"
        fn = self._compiled_scan(fld, C, R, Td, k, nreal, interpret)
        outs = fn(self._arrays(),
                  np.float32(self.searcher.pack.avgdl(fld)),
                  rows, row_q, row_w, dr, dw)
        return idxs, outs

    @staticmethod
    def _collect_batch(Q, k, idxs, host):
        scores = np.full((Q, k), -np.inf, np.float32)
        ids = np.zeros((Q, k), np.int64)
        totals = np.zeros((Q,), np.int64)
        flagged = np.zeros((Q,), bool)
        v, i, t, fl = host
        for ci, qidx in enumerate(idxs):
            nq = len(qidx)
            scores[qidx] = v[ci][:nq]
            ids[qidx] = i[ci][:nq]
            totals[qidx] = t[ci][:nq]
            flagged[qidx] = fl[ci][:nq]
        return scores, ids, totals, flagged

    def _cost_fields(self, queries_n: int) -> dict:
        """Shape fields of one fused pass for the cost model
        (monitoring/costmodel): dense-tier geometry + corpus size."""
        pack = self.searcher.pack
        V = pack.dense_tfn.shape[0] if pack.dense_tfn is not None else 0
        tile_n = self._tile_n
        n_pad = -(-pack.num_docs // tile_n) * tile_n
        return {"v": V, "num_docs": n_pad,
                "queries": -(-queries_n // QC) * QC}

    def _run_pass(self, fld, queries, k):
        """One fused pass over all queries -> (v, i, t, flagged_bool)."""
        from ..telemetry import time_kernel

        idxs, outs = self._dispatch_batch(fld, queries, k)
        with time_kernel("fused.pallas_scan", tier="fused", k=k,
                         **self._cost_fields(len(queries))):
            host = jax.device_get(outs)
        return self._collect_batch(len(queries), k, idxs, host)

    def msearch_many(self, fld, batches, k=10):
        """Pipelined multi-batch msearch: EVERY batch's scanned program is
        dispatched before any result is fetched, so the remote runtime's
        fixed per-execution overhead (~300 ms/batch through the tunnel,
        round-5 measurement) amortizes across the wave — the serving
        regime of a node answering concurrent _msearch requests (same
        discipline as StackedSearcher.search_batch for aggs). Returns a
        list of msearch-style (scores, ids, totals, first_pass_ok)
        tuples, escalation included."""
        from ..telemetry import time_kernel

        disp = [self._dispatch_batch(fld, qs, k) for qs in batches]
        with time_kernel("fused.pallas_scan", tier="fused", k=k,
                         **self._cost_fields(sum(len(b) for b in batches))):
            hosts = jax.device_get([outs for _idxs, outs in disp])
        out = []
        for qs, (idxs, _), host in zip(batches, disp, hosts):
            raw = self._collect_batch(len(qs), k, idxs, host)
            out.append(self._finish(fld, qs, k, *raw))
        return out

    def msearch(self, fld, queries, k=10):
        """-> (scores [Q,k], docids [Q,k], totals [Q] exact,
        first_pass_ok [Q]) numpy, in input order. Top-k is always the
        canonical f32 ranking; flagged queries (window overflow, or a
        top-k boundary the split-precision pass cannot separate) re-run
        on the legacy exact path, so results never depend on the fused
        pass. The split-bf16 selection keeps the flag rate near zero."""
        scores, ids, totals, flagged = self._run_pass(fld, queries, k)
        return self._finish(fld, queries, k, scores, ids, totals, flagged)

    def _finish(self, fld, queries, k, scores, ids, totals, flagged):
        """Escalate flagged queries on the legacy exact path."""
        first_ok = ~flagged
        if flagged.any():
            from ..telemetry import profile_event

            still = np.nonzero(flagged)[0]
            profile_event("tier", tier="exact_escalation",
                          queries=int(still.shape[0]))
            # legacy exact path (independent machinery). Its final scores
            # equal the canonical values only up to ulps; ranking
            # differences at that level are accepted. The plan pads to a
            # FIXED (Ts, B) envelope: flagged queries are rare (~1e-3),
            # and letting each handful mint its own (Ts, B) bucket costs
            # a fresh multi-minute XLA compile mid-serving.
            flagged_qs = [queries[i] for i in still]
            pack = self.searcher.pack
            max_ts = max(
                (sum(1 for t, _ in q
                     if pack.dense_row_of(fld, t) is None)
                 for q in flagged_qs),
                default=1,
            )
            max_b = max(
                (pack.term_blocks(fld, t)[1]
                 for q in flagged_qs for t, _ in q
                 if pack.dense_row_of(fld, t) is None), default=1)
            from ..telemetry import time_kernel

            with time_kernel("batched.escalation", tier="exact_escalation",
                             queries=int(still.shape[0]), k=k,
                             num_docs=pack.num_docs):
                sv, si, st = [
                    np.asarray(x)
                    for x in self.bts.run(
                        fld,
                        self.bts.plan(
                            fld, flagged_qs, k,
                            pad_ts=1 << (max(max_ts, 4) - 1).bit_length(),
                            pad_b=max(32,
                                      1 << (max(max_b, 1) - 1).bit_length()),
                        ),
                    )
                ]
            scores[still, : sv.shape[1]] = sv
            ids[still, : sv.shape[1]] = si
            totals[still] = st
        return scores, ids, totals, first_ok


def rank_topk(values, ids, k):
    """(score desc, docid asc) exact order via one int64 rank-key top_k.
    values must be >= 0 or -inf (IEEE bit-pattern order trick)."""
    score_bits = jax.lax.bitcast_convert_type(values, jnp.int32).astype(jnp.int64)
    rank = (score_bits << 32) + (jnp.int64(0xFFFFFFFF) - ids.astype(jnp.int64))
    _, sel = jax.lax.top_k(rank, k)
    return (
        jnp.take_along_axis(values, sel, axis=1),
        jnp.take_along_axis(ids, sel, axis=1),
    )
