"""Pallas TPU kernels for the scoring hot loop.

The reference's innermost hot loop is Lucene's `BulkScorer.score` — a
doc-at-a-time pull iterator feeding a top-k heap (reference behavior:
search/internal/ContextIndexSearcher.java:411-431). The TPU inversion keeps
the FLOPs on the MXU and the heap in VMEM:

    fused_scan_topk:  grid over doc tiles; per step a [TILE_B, D] x [D, TILE_N]
    matmul (MXU) produces a tile of scores, which updates a running
    (score desc, docid asc) top-k held in VMEM scratch. TPU grids execute
    sequentially on a core, so the scratch accumulator is race-free — the
    Pallas analog of Lucene's per-segment collector state.

Two input modes share the merge machinery:
  - matmul mode: q [B, D] against mat_t [D, N] — serves batched dense-tier
    BM25 (q = per-query term weights, mat_t = dense tfn rows) and exact kNN
    scans (q = query vectors, mat_t = transposed doc vectors).
  - streamed mode: precomputed scores [B, N] — a bandwidth-optimal top-k
    + match-count pass replacing sort-based `lax.top_k`.

Why fusion matters: materializing [B, N] f32 scores for a 4k-query batch over
a 1M-doc shard is ~16 GB of HBM traffic before top-k even starts; the fused
kernel keeps scores in VMEM and writes only [B, k].

The kernel reproduces the exact result order of ops/scoring.top_k_with_total:
score descending, docid ascending on ties, -inf for dead lanes. On non-TPU
backends `scan_topk` dispatches to an XLA reference implementation with
identical semantics (tests compare both, running the kernel in interpret
mode).

Sharded execution (PR 11): these kernels are custom calls GSPMD cannot
partition, so sharded callers run them inside shard_map manual regions
embedded in the one compiled SPMD program
(`parallel/spmd.manual_shard_region`) — per-shard shapes reach the
kernel exactly as the single-device path builds them, and the
surrounding program (all-gather top-k merge) stays GSPMD. No caller
pins the XLA arm for partitionability anymore.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_I0 = np.int32(0)  # index-map constant: python ints trace to i64 under x64

try:  # pltpu import works on CPU too (needed for interpret-mode tests)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_I32_MAX = np.int32(2**31 - 1)


MAX_FUSED_K = 128  # beyond this the unrolled merge loses to sort-based top_k


def _pick_tiles(B: int, D: int, N: int, k: int) -> tuple[int, int] | None:
    """Choose (TILE_B, TILE_N) fitting q + mat + scratch in ~10MB of VMEM.
    None when nothing fits (caller falls back to the XLA path)."""
    tile_b = 128 if B > 8 else 8
    budget = 10 * 1024 * 1024
    # bytes per step ~ 2*(q block + mat block) for double buffering
    for tile_n in (512, 256, 128):
        need = 2 * 4 * (tile_b * D + D * tile_n) + 4 * tile_b * (2 * k + tile_n)
        if need <= budget:
            return tile_b, tile_n
    return None


def _merge_topk(vals, idxs, acc_v, acc_i, k):
    """One merge round: running top-k + a tile of candidates -> new top-k.

    k unrolled (max, argmin-id, mask) rounds over [TB, k + TILE_N]; every op
    is a VPU reduction/select, no sort. Tie-break: lowest docid wins among
    equal scores, matching Lucene's TopScoreDocCollector order.
    """
    cand_v = jnp.concatenate([acc_v, vals], axis=1)
    cand_i = jnp.concatenate([acc_i, idxs], axis=1)
    out_v, out_i = [], []
    for _ in range(k):
        vmax = jnp.max(cand_v, axis=1, keepdims=True)
        ismax = cand_v == vmax
        imin = jnp.min(jnp.where(ismax, cand_i, _I32_MAX), axis=1, keepdims=True)
        out_v.append(vmax)
        out_i.append(imin)
        cand_v = jnp.where(ismax & (cand_i == imin), -jnp.inf, cand_v)
    return jnp.concatenate(out_v, axis=1), jnp.concatenate(out_i, axis=1)


def _apply_transform(dots, transform, auxd_row, auxq_col):
    """Map raw dots to _score space (see ops/vector.py conventions)."""
    if transform == "identity":
        return dots
    if transform == "cosine":
        # auxd = 1/||d||, auxq = 1/||q||
        return (1.0 + dots * auxd_row[None, :] * auxq_col) / 2.0
    if transform == "dot_product":
        return (1.0 + dots) / 2.0
    if transform == "l2_norm":
        # auxd = ||d||^2, auxq = ||q||^2
        l2 = jnp.maximum(auxd_row[None, :] - 2.0 * dots + auxq_col, 0.0)
        return 1.0 / (1.0 + l2)
    if transform == "max_inner_product":
        return jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
    raise ValueError(f"unknown transform [{transform}]")


def _scan_topk_kernel(
    q_ref, m_ref, live_ref, auxd_ref, auxq_ref,
    ov_ref, oi_ref, ot_ref,
    acc_v, acc_i, cnt,
    *, k, tile_n, transform, count_positive, matmul,
):
    j = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_v[:] = jnp.full_like(acc_v, -jnp.inf)
        acc_i[:] = jnp.zeros_like(acc_i)
        cnt[:] = jnp.zeros_like(cnt)

    if matmul:
        # HIGHEST: full-f32 MXU passes for bit-parity with the unfused path
        dots = jnp.dot(
            q_ref[:], m_ref[:],
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    else:
        dots = m_ref[:]
    scores = _apply_transform(dots, transform, auxd_ref[0, :], auxq_ref[:])
    ids = j * tile_n + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    ok = live_ref[0, :] > 0
    scores = jnp.where(ok[None, :], scores, -jnp.inf)
    if count_positive:
        # BM25 match semantics: score <= 0 means "no matching term" (all term
        # weights are > 0), so such lanes are not hits and not candidates
        scores = jnp.where(scores > 0, scores, -jnp.inf)
        cnt[:] += (scores > 0).astype(jnp.float32)
    else:
        cnt[:] += jnp.broadcast_to(ok[None, :], scores.shape).astype(jnp.float32)
    new_v, new_i = _merge_topk(scores, ids, acc_v[:], acc_i[:], k)
    acc_v[:] = new_v
    acc_i[:] = new_i

    @pl.when(j == nn - 1)
    def _():
        ov_ref[:] = acc_v[:]
        oi_ref[:] = acc_i[:]
        ot_ref[:] = jnp.sum(cnt[:], axis=1, keepdims=True).astype(jnp.int32)


def _pad_to(x, mult, axis, value):
    size = x.shape[axis]
    rem = (-size) % mult
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(
    jax.jit,
    static_argnames=("k", "transform", "count_positive", "interpret", "tiles"),
)
def _scan_topk_pallas(
    q, mat_t, live, aux_doc, aux_q,
    *, k, transform, count_positive, interpret, tiles,
):
    matmul = q is not None
    B = q.shape[0] if matmul else mat_t.shape[0]
    D = q.shape[1] if matmul else 1
    N = mat_t.shape[1]
    tile_b, tile_n = tiles
    if matmul:
        qp = _pad_to(q, tile_b, 0, 0.0)
        mp = _pad_to(mat_t, tile_n, 1, 0.0)
    else:
        qp = jnp.zeros((pl.cdiv(B, tile_b) * tile_b, 1), jnp.float32)
        mp = _pad_to(_pad_to(mat_t, tile_b, 0, 0.0), tile_n, 1, 0.0)
    livep = _pad_to(live.astype(jnp.float32)[None, :], tile_n, 1, 0.0)
    auxdp = _pad_to(aux_doc[None, :], tile_n, 1, 0.0)
    auxqp = _pad_to(aux_q[:, None], tile_b, 0, 0.0)
    Bp = qp.shape[0] if matmul else mp.shape[0]
    Np = mp.shape[1]
    nb, nn = Bp // tile_b, Np // tile_n

    kernel = functools.partial(
        _scan_topk_kernel,
        k=k, tile_n=tile_n, transform=transform,
        count_positive=count_positive, matmul=matmul,
    )
    m_spec = (
        pl.BlockSpec((D, tile_n), lambda i, j: (_I0, j))
        if matmul
        else pl.BlockSpec((tile_b, tile_n), lambda i, j: (i, j))
    )
    out_v, out_i, out_t = pl.pallas_call(
        kernel,
        grid=(nb, nn),
        in_specs=[
            pl.BlockSpec((tile_b, qp.shape[1]), lambda i, j: (i, _I0)),
            m_spec,
            pl.BlockSpec((1, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((1, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((tile_b, 1), lambda i, j: (i, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((tile_b, k), lambda i, j: (i, _I0)),
            pl.BlockSpec((tile_b, k), lambda i, j: (i, _I0)),
            pl.BlockSpec((tile_b, 1), lambda i, j: (i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, k), jnp.float32),
            jax.ShapeDtypeStruct((Bp, k), jnp.int32),
            jax.ShapeDtypeStruct((Bp, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_b, k), jnp.float32),
            pltpu.VMEM((tile_b, k), jnp.int32),
            pltpu.VMEM((tile_b, tile_n), jnp.float32),
        ],
        interpret=interpret,
    )(qp, mp, livep, auxdp, auxqp)
    return out_v[:B], out_i[:B], out_t[:B, 0]


@functools.partial(
    jax.jit, static_argnames=("k", "transform", "count_positive")
)
def scan_topk_xla(q, mat_t, live, aux_doc, aux_q, *, k, transform, count_positive):
    """XLA reference with identical semantics (and the non-TPU fast path).
    Jitted: callers outside a trace (e.g. the batched dense-only dispatch)
    must not fall back to eager per-op execution."""
    dots = (
        jnp.matmul(q, mat_t, precision=jax.lax.Precision.HIGHEST)
        if q is not None
        else mat_t
    )
    auxq = aux_q[:, None] if aux_q.ndim == 1 else aux_q
    scores = _apply_transform(dots, transform, aux_doc, auxq)
    scores = jnp.where(live[None, :] > 0, scores, -jnp.inf)
    if count_positive:
        scores = jnp.where(scores > 0, scores, -jnp.inf)
        totals = jnp.sum(scores > 0, axis=1, dtype=jnp.int32)
    else:
        totals = jnp.broadcast_to(
            jnp.sum(live > 0, dtype=jnp.int32), (scores.shape[0],)
        )
    top_v, top_i = jax.lax.top_k(scores, k)
    return top_v, top_i.astype(jnp.int32), totals


# auto mode switches to the fused kernel when materializing [B, N] scores
# would cost more HBM traffic than this threshold — below it XLA's own
# matmul+top_k fusion wins (measured on real hardware)
PALLAS_SCORE_BYTES_THRESHOLD = 1 << 31  # 2 GB


def fused_topk_enabled() -> bool:
    """ES_TPU_FUSED_TOPK (default on): route large matmul+top-k scans
    through the tiered split-bf16 selection + f32 rescore path instead of
    f32-HIGHEST matmuls / XLA TopK. '0' reverts every wired call site."""
    return os.environ.get("ES_TPU_FUSED_TOPK", "auto") != "0"


def _mask_hi(t):
    """Truncate f32 to its top 16 bits (exactly bf16-representable) by
    integer masking — an astype round-trip constant-folds away under
    --xla_allow_excess_precision (see ops/fused.py EPS_SPLIT note)."""
    bits = jax.lax.bitcast_convert_type(t, jnp.int32)
    return jax.lax.bitcast_convert_type(bits & jnp.int32(-65536), jnp.float32)


def split_bf16(mat: jax.Array) -> tuple[jax.Array, jax.Array]:
    """f32 matrix -> (hi, lo) bf16 pair carrying ~15 mantissa bits: the
    selection-tier layout of the tiered scan (hi = masked top 16 bits,
    lo = exact residual truncated to bf16)."""
    hif = _mask_hi(mat)
    return hif.astype(jnp.bfloat16), (mat - hif).astype(jnp.bfloat16)


# relative slack of tiered split-bf16 selection vs the f32 rescore: the
# query side is bf16-truncated (~2^-9 per element) while the mat side
# carries ~15 mantissa bits — same regime as ops/fused.EPS_SPLIT, with
# margin for the transform's score-space amplification
EPS_TIERED = 2e-2
# selection width: candidates carried to the f32 rescore (the KB-64
# margin discipline of ops/fused.py)
KB_TIERED = 64


def _tiered_scan_kernel(
    q_ref, mh_ref, ml_ref, live_ref, auxd_ref, auxq_ref,
    ov_ref, oi_ref, ot_ref,
    acc_v, acc_i, cnt,
    *, kb, tile_n, transform, count_positive,
):
    """Per doc tile: split-bf16 matmul on the MXU (f32 accumulation) +
    running top-kb selection in VMEM — the tiered arm of _scan_topk_kernel
    (which runs 6-pass f32 HIGHEST for bit-parity; this arm trades that
    for ~3x fewer MXU passes and rescores survivors outside)."""
    j = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_v[:] = jnp.full_like(acc_v, -jnp.inf)
        acc_i[:] = jnp.zeros_like(acc_i)
        cnt[:] = jnp.zeros_like(cnt)

    dn = (((1,), (0,)), ((), ()))
    dots = jax.lax.dot_general(
        q_ref[:], mh_ref[:], dn, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        q_ref[:], ml_ref[:], dn, preferred_element_type=jnp.float32
    )
    scores = _apply_transform(dots, transform, auxd_ref[0, :], auxq_ref[:])
    ids = j * tile_n + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    ok = live_ref[0, :] > 0
    scores = jnp.where(ok[None, :], scores, -jnp.inf)
    if count_positive:
        # sign survives the split-bf16 rounding (BM25: every product is
        # >= 0), so the tiered counts equal the exact counts
        scores = jnp.where(scores > 0, scores, -jnp.inf)
        cnt[:] += (scores > 0).astype(jnp.float32)
    else:
        cnt[:] += jnp.broadcast_to(ok[None, :], scores.shape).astype(
            jnp.float32)
    new_v, new_i = _merge_topk(scores, ids, acc_v[:], acc_i[:], kb)
    acc_v[:] = new_v
    acc_i[:] = new_i

    @pl.when(j == nn - 1)
    def _():
        ov_ref[:] = acc_v[:]
        oi_ref[:] = acc_i[:]
        ot_ref[:] = jnp.sum(cnt[:], axis=1, keepdims=True).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("kb", "transform", "count_positive", "interpret",
                     "tiles"),
)
def _tiered_candidates_pallas(
    qh, mat_hi, mat_lo, live, aux_doc, aux_q,
    *, kb, transform, count_positive, interpret, tiles,
):
    B, D = qh.shape
    N = mat_hi.shape[1]
    tile_b, tile_n = tiles
    qp = _pad_to(qh, tile_b, 0, 0)
    mhp = _pad_to(mat_hi, tile_n, 1, 0)
    mlp = _pad_to(mat_lo, tile_n, 1, 0)
    livep = _pad_to(live.astype(jnp.float32)[None, :], tile_n, 1, 0.0)
    auxdp = _pad_to(aux_doc[None, :], tile_n, 1, 0.0)
    auxqp = _pad_to(aux_q[:, None], tile_b, 0, 0.0)
    Bp, Np = qp.shape[0], mhp.shape[1]
    nb, nn = Bp // tile_b, Np // tile_n
    kernel = functools.partial(
        _tiered_scan_kernel,
        kb=kb, tile_n=tile_n, transform=transform,
        count_positive=count_positive,
    )
    out_v, out_i, out_t = pl.pallas_call(
        kernel,
        grid=(nb, nn),
        in_specs=[
            pl.BlockSpec((tile_b, D), lambda i, j: (i, _I0)),
            pl.BlockSpec((D, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((D, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((1, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((1, tile_n), lambda i, j: (_I0, j)),
            pl.BlockSpec((tile_b, 1), lambda i, j: (i, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((tile_b, kb), lambda i, j: (i, _I0)),
            pl.BlockSpec((tile_b, kb), lambda i, j: (i, _I0)),
            pl.BlockSpec((tile_b, 1), lambda i, j: (i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, kb), jnp.float32),
            jax.ShapeDtypeStruct((Bp, kb), jnp.int32),
            jax.ShapeDtypeStruct((Bp, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_b, kb), jnp.float32),
            pltpu.VMEM((tile_b, kb), jnp.int32),
            pltpu.VMEM((tile_b, tile_n), jnp.float32),
        ],
        interpret=interpret,
    )(qp, mhp, mlp, livep, auxdp, auxqp)
    return out_v[:B], out_i[:B], out_t[:B, 0]


@functools.partial(
    jax.jit, static_argnames=("kb", "transform", "count_positive")
)
def _tiered_candidates_xla(
    qh, mat_hi, mat_lo, live, aux_doc, aux_q,
    *, kb, transform, count_positive,
):
    """XLA arm with the same selection semantics (non-TPU fast path; the
    kernel arm is bit-comparable up to f32 accumulation order)."""
    dots = (
        jnp.matmul(qh, mat_hi, preferred_element_type=jnp.float32)
        + jnp.matmul(qh, mat_lo, preferred_element_type=jnp.float32)
    )
    auxq = aux_q[:, None] if aux_q.ndim == 1 else aux_q
    scores = _apply_transform(dots, transform, aux_doc, auxq)
    scores = jnp.where(live[None, :] > 0, scores, -jnp.inf)
    if count_positive:
        scores = jnp.where(scores > 0, scores, -jnp.inf)
        totals = jnp.sum(scores > 0, axis=1, dtype=jnp.int32)
    else:
        totals = jnp.broadcast_to(
            jnp.sum(live > 0, dtype=jnp.int32), (scores.shape[0],)
        )
    sel_v, sel_i = jax.lax.top_k(scores, kb)
    return sel_v, sel_i.astype(jnp.int32), totals


def tiered_candidates(
    q: jax.Array,  # [B, D] f32 query rows (weights / query vectors)
    mat_hi: jax.Array,  # [D, N] bf16 hi tier (split_bf16)
    mat_lo: jax.Array,  # [D, N] bf16 lo tier
    live: jax.Array,  # [N] mask
    kb: int,
    *,
    transform: str = "identity",
    aux_doc: jax.Array | None = None,
    aux_q: jax.Array | None = None,
    count_positive: bool = True,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Tiered selection pass -> (sel_v [B, kb], sel_i [B, kb], totals [B]).

    sel_v are SELECTION scores (split-bf16, within ~EPS_TIERED of f32);
    callers must f32-rescore the sel_i candidates and apply the margin
    safety test (see ops/vector.knn_topk / ops/batched dense tiered path)
    before treating the ranking as exact. totals are exact (live counts,
    or sign-exact positive counts — see the kernel comment)."""
    B, N = q.shape[0], mat_hi.shape[1]
    kb = max(1, min(kb, N))
    if aux_doc is None:
        aux_doc = jnp.zeros((N,), jnp.float32)
    if aux_q is None:
        aux_q = jnp.zeros((B,), jnp.float32)
    qh = _mask_hi(q).astype(jnp.bfloat16)
    tiles = (
        _pick_tiles(B, q.shape[1], N, kb) if kb <= MAX_FUSED_K else None
    )
    if interpret is None:
        if not use_pallas(score_bytes=4 * B * N) or tiles is None:
            return _tiered_candidates_xla(
                qh, mat_hi, mat_lo, live, aux_doc, aux_q,
                kb=kb, transform=transform, count_positive=count_positive,
            )
        interpret = jax.default_backend() != "tpu"
    if tiles is None:
        return _tiered_candidates_xla(
            qh, mat_hi, mat_lo, live, aux_doc, aux_q,
            kb=kb, transform=transform, count_positive=count_positive,
        )
    return _tiered_candidates_pallas(
        qh, mat_hi, mat_lo, live, aux_doc, aux_q,
        kb=kb, transform=transform, count_positive=count_positive,
        interpret=bool(interpret), tiles=tiles,
    )


# ---------------------------------------------------------------------------
# impact-tier gather (BM25S): the sparse arm of the batched disjunction
# as a pure gather+dequant — block rows of quantized impact codes are
# fetched and scaled by one per-row weight; no tf/dl/avgdl math exists
# anywhere downstream of the index build. Two arms like ann/kernels.py:
# a Pallas kernel whose scalar-prefetched row ids drive the code-block
# DMA through BlockSpec index maps, and an XLA gather with identical
# semantics for non-TPU backends.
# ---------------------------------------------------------------------------

_IMPACT_G = 8  # gathered block rows per grid step (DMA granularity)


def _impact_gather_kernel(rows_ref, w_ref, *refs, g):
    """refs = g code blocks + g docid blocks + (out_scores, out_ids)."""
    os_ref, oi_ref = refs[-2], refs[-1]
    for i in range(g):
        c_ref = refs[i]
        d_ref = refs[g + i]
        os_ref[0, i, :] = w_ref[0, i] * c_ref[0, :].astype(jnp.float32)
        oi_ref[0, i, :] = d_ref[0, :]


@functools.partial(jax.jit, static_argnames=("g", "interpret"))
def _impact_gather_pallas(codes, docids, rows, row_w, *, g, interpret):
    Q, R = rows.shape  # R is a multiple of g (caller pads with row 0)
    block = codes.shape[1]
    kernel = functools.partial(_impact_gather_kernel, g=g)

    def _row_spec(arr, gi):
        return pl.BlockSpec(
            (1, block), lambda q, j, r, _gi=gi: (r[q, j * g + _gi], _I0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Q, R // g),
        in_specs=(
            [pl.BlockSpec((1, g), lambda q, j, r: (q, j))]
            + [_row_spec(codes, gi) for gi in range(g)]
            + [_row_spec(docids, gi) for gi in range(g)]
        ),
        out_specs=[
            pl.BlockSpec((1, g, block), lambda q, j, r: (q, j, _I0)),
            pl.BlockSpec((1, g, block), lambda q, j, r: (q, j, _I0)),
        ],
    )
    out_s, out_i = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Q, R, block), jnp.float32),
            jax.ShapeDtypeStruct((Q, R, block), jnp.int32),
        ],
        interpret=interpret,
    )(rows, row_w, *([codes] * g), *([docids] * g))
    return out_i.reshape(Q, R * block), out_s.reshape(Q, R * block)


@jax.jit
def _impact_gather_xla(codes, docids, rows, row_w):
    """XLA arm: identical semantics (row gathers are the fast gather
    class on TPU too — see ops/scoring.term_score_blocks)."""
    Q, R = rows.shape
    block = codes.shape[1]
    scores = row_w[:, :, None] * codes[rows].astype(jnp.float32)
    return (docids[rows].reshape(Q, R * block),
            scores.reshape(Q, R * block))


def impact_gather(
    codes: jax.Array,   # [num_blocks, BLOCK] u16|i8 impact codes
    docids: jax.Array,  # [num_blocks, BLOCK] i32 (pad: num_docs)
    rows: jax.Array,    # [Q, R] i32 flat block rows (0-padded, row 0 dead)
    row_w: jax.Array,   # [Q, R] f32 dequant weight (boost·idf·ubf/qmax)
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """-> (ids [Q, R·BLOCK] i32, scores [Q, R·BLOCK] f32): the flattened
    per-lane candidates of a batch of impact-tier disjunctions. Padding
    rows (row 0, weight 0) emit docid == num_docs at score 0 — dead lanes
    for every downstream consumer."""
    Q, R = rows.shape
    block = codes.shape[1]
    g = min(_IMPACT_G, max(R, 1))
    pad = (-R) % g
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
        row_w = jnp.pad(row_w, ((0, 0), (0, pad)))
    pallas_ok = pltpu is not None
    if interpret is None:
        if not use_pallas(score_bytes=Q * (R + pad) * block * 8) or not pallas_ok:
            return _impact_gather_xla(codes, docids, rows, row_w)
        interpret = jax.default_backend() != "tpu"
    if not pallas_ok:
        return _impact_gather_xla(codes, docids, rows, row_w)
    return _impact_gather_pallas(
        codes, docids, rows, row_w, g=g, interpret=bool(interpret))


def use_pallas(score_bytes: int | None = None) -> bool:
    flag = os.environ.get("ES_TPU_PALLAS", "auto")
    if flag == "0":
        return False
    if flag in ("1", "force"):
        return True
    if jax.default_backend() != "tpu":
        return False
    if score_bytes is None:
        return True
    return score_bytes >= PALLAS_SCORE_BYTES_THRESHOLD


def scan_topk(
    q: jax.Array | None,  # [B, D] f32 or None (streamed mode)
    mat_t: jax.Array,  # [D, N] f32 (matmul mode) | [B, N] scores (streamed)
    live: jax.Array,  # [N] bool/float mask
    k: int,
    *,
    transform: str = "identity",
    aux_doc: jax.Array | None = None,  # [N] per-doc transform input
    aux_q: jax.Array | None = None,  # [B] per-query transform input
    count_positive: bool = True,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """-> (top_v [B, k] f32, top_i [B, k] i32, totals [B] i32).

    totals counts `score > 0 & live` when count_positive (BM25 match
    semantics: all term weights > 0) else counts live lanes (kNN candidate
    counts).
    """
    B = q.shape[0] if q is not None else mat_t.shape[0]
    N = mat_t.shape[1]
    k = max(1, min(k, N))
    if aux_doc is None:
        aux_doc = jnp.zeros((N,), jnp.float32)
    if aux_q is None:
        aux_q = jnp.zeros((B,), jnp.float32)
    D = q.shape[1] if q is not None else 1
    tiles = _pick_tiles(B, D, N, k) if k <= MAX_FUSED_K else None
    if interpret is None:
        if not use_pallas(score_bytes=4 * B * N) or tiles is None:
            return scan_topk_xla(
                q, mat_t, live, aux_doc, aux_q,
                k=k, transform=transform, count_positive=count_positive,
            )
        interpret = jax.default_backend() != "tpu"
    if tiles is None:  # explicit interpret request but shape won't fit
        return scan_topk_xla(
            q, mat_t, live, aux_doc, aux_q,
            k=k, transform=transform, count_positive=count_positive,
        )
    return _scan_topk_pallas(
        q, mat_t, live, aux_doc, aux_q,
        k=k, transform=transform, count_positive=count_positive,
        interpret=bool(interpret), tiles=tiles,
    )
