"""Device-side BM25 scoring ops over blocked-CSR postings.

TPU-first inversion of the reference's hot loop (reference behavior:
search/internal/ContextIndexSearcher.java:411-431 — per-segment
`BulkScorer.score` pulling doc-at-a-time postings through BM25 and a top-k
heap). Here the same math runs data-parallel:

    gather postings blocks -> vectorized BM25 over [B, 128] lanes
    -> scatter-add into a dense per-doc score accumulator -> lax.top_k

The dense accumulator has N+1 slots; slot N is a dead slot that absorbs all
padding lanes (padding docids == N), so no masking branches exist anywhere in
the kernel. Scoring is exact (no early termination); block-max pruning is a
later optimization that *filters the block list* host/device-side rather than
branching inside the kernel (SURVEY.md hard part #2).

BM25 formula parity (Lucene 9 BM25Similarity, wired as ES's default at
server/.../index/similarity/SimilarityService.java:43-58):

    idf(t)  = ln(1 + (docCount - df + 0.5) / (df + 0.5))
    tfn     = tf / (tf + k1 * (1 - b + b * dl / avgdl))   [norms present]
    tfn     = tf / (tf + k1)                              [norms omitted]
    score   = boost * idf * tfn

with dl the 1-byte-quantized doc length (index/smallfloat.py) and avgdl the
exact sumTotalTermFreq/docCount. k1=1.2, b=0.75 defaults.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

DEAD_SLOT_PAD = 1  # dense accumulators are sized N + 1


def bm25_idf(doc_count: int, df: int) -> float:
    """Host-side idf — THE single BM25 idf implementation: query planning
    (query/nodes, ops/batched) and the impact-tier weight derivation all
    source this function, so dfs-stats overrides flow identically into
    every scoring path. doc_count = docs with >=1 term in the field."""
    if df <= 0:
        return 0.0
    return math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))


def impact_enabled() -> bool:
    """ES_TPU_IMPACT routing for the eager impact-scored sparse tier
    (BM25S): 'auto' (default) engages on TPU backends only — the CPU
    tier-1 suite keeps exercising the exact BM25 reference paths —
    '1'/'force' engages everywhere (tests, bench A/B arms), '0' disables.
    The tier is selection-complete but quantized (see index/pack.py error
    model); explain / scripted similarity / non-default k1,b escalate to
    the exact path regardless of this flag."""
    import os

    import jax as _jax

    mode = os.environ.get("ES_TPU_IMPACT", "auto")
    if mode == "0":
        return False
    if mode in ("1", "force"):
        return True
    return _jax.default_backend() == "tpu"


def impact_term_scores(
    impact_codes: jax.Array,  # [num_blocks, BLOCK] u16|i8 codes
    post_docids: jax.Array,  # [num_blocks, BLOCK] int32 (pad: num_docs)
    rows: jax.Array,  # [B] int32 block rows for this term (0-padded)
    wscale: jax.Array,  # scalar f32: boost * idf * ubf / qmax
    num_docs: int,
) -> tuple[jax.Array, jax.Array]:
    """Impact-tier scoring of one term: a pure gather+sum. No tf, no doc
    length, no avgdl, no division — the code IS the (quantized) BM25
    contribution, dequantized by one per-term scalar multiply.

    Returns (scores[N+1] f32, match[N+1] bool) with identical padding /
    dead-slot semantics to term_score_blocks (codes of padding lanes are
    0, and tf > 0 postings always carry code >= 1)."""
    codes = impact_codes[rows]  # [B, 128]
    docids = post_docids[rows]
    block_scores = wscale * codes.astype(jnp.float32)
    flat_ids = docids.reshape(-1)
    scores = jnp.zeros(num_docs + DEAD_SLOT_PAD, jnp.float32).at[flat_ids].add(
        block_scores.reshape(-1), mode="drop"
    )
    match = jnp.zeros(num_docs + DEAD_SLOT_PAD, bool).at[flat_ids].set(
        (codes > 0).reshape(-1), mode="drop"
    )
    return scores, match


def term_score_blocks(
    post_docids: jax.Array,  # [num_blocks, BLOCK] int32
    post_tfs: jax.Array,  # [num_blocks, BLOCK] float32
    post_dls: jax.Array,  # [num_blocks, BLOCK] float32 (dl per posting)
    rows: jax.Array,  # [B] int32 block rows for this term (0-padded)
    weight: jax.Array,  # scalar f32: boost * idf
    avgdl: jax.Array | float,  # scalar
    num_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
    has_norms: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Score one term's postings blocks.

    The doc length rides IN the postings block (`post_dls`), so BM25 is pure
    FMA over gathered rows — no random-access norms gather, which profiling
    shows is ~100x slower than row gathers on TPU.

    Returns (scores[N+1] f32, match[N+1] bool). Padding lanes (docid == N,
    tf == 0) score exactly 0 and scatter into the dead slot.
    """
    docids = post_docids[rows]  # [B, 128]
    tfs = post_tfs[rows]  # [B, 128]
    dls = post_dls[rows] if has_norms else None
    return score_posting_arrays(
        docids, tfs, dls, weight, avgdl, num_docs,
        k1=k1, b=b, has_norms=has_norms,
    )


def score_posting_arrays(
    docids: jax.Array,  # [B, BLOCK] int32 (pad: num_docs)
    tfs: jax.Array,  # [B, BLOCK] float32 (pad: 0)
    dls: jax.Array | None,  # [B, BLOCK] float32 (None when has_norms=False)
    weight: jax.Array,
    avgdl: jax.Array | float,
    num_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
    has_norms: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Score explicit posting arrays (the tail of term_score_blocks; also
    the execution form of WAND-pruned synthetic blocks, where surviving
    postings were compacted host-side — query/wand.prune_postings)."""
    if has_norms:
        denom = tfs + k1 * (1.0 - b + b * dls / avgdl)
    else:
        denom = tfs + k1
    # tf==0 padding -> 0/k1' = 0
    block_scores = weight * tfs / denom
    flat_ids = docids.reshape(-1)
    scores = jnp.zeros(num_docs + DEAD_SLOT_PAD, jnp.float32).at[flat_ids].add(
        block_scores.reshape(-1), mode="drop"
    )
    match = jnp.zeros(num_docs + DEAD_SLOT_PAD, bool).at[flat_ids].set(
        (tfs > 0).reshape(-1), mode="drop"
    )
    return scores, match


def dense_term_scores(
    tfn_row: jax.Array,  # [N] f32 precomputed tf/(tf + K) for this term
    weight: jax.Array,  # scalar f32: boost * idf
    num_docs: int,
) -> tuple[jax.Array, jax.Array]:
    """Score one dense-tier term (df above the dense threshold).

    High-df terms are stored as dense tfn rows ([V_dense, N] in the pack);
    scoring is a pure elementwise scale — no gather, no scatter. tfn > 0
    iff tf > 0, so the row doubles as the match bitmap.
    """
    n1 = num_docs + DEAD_SLOT_PAD
    scores = jnp.zeros(n1, jnp.float32).at[:num_docs].set(weight * tfn_row)
    match = jnp.zeros(n1, bool).at[:num_docs].set(tfn_row > 0)
    return scores, match


def _fused_scan_engages(n: int, k: int) -> bool:
    """The exact predicate top_k_with_total uses to pick the streamed
    Pallas scan over sort-based lax.top_k — exposed so profiling can
    attribute which selection tier a compiled plan actually ran."""
    import os

    import jax as _jax

    mode = os.environ.get("ES_TPU_FUSED_TOPK", "auto")
    from .kernels import MAX_FUSED_K

    if mode == "0" or k > MAX_FUSED_K or n < 8:
        return False
    if mode == "force":
        return True
    return _jax.default_backend() == "tpu" and n >= (1 << 18)


def topk_mode(n: int, k: int) -> str:
    """-> "fused_scan" | "xla_topk": the selection tier for (n, k)."""
    return "fused_scan" if _fused_scan_engages(n, k) else "xla_topk"


def top_k_with_total(
    scores: jax.Array,  # [N+1] f32
    match: jax.Array,  # [N+1] bool
    live: jax.Array,  # [N] bool
    k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Global top-k by (score desc, docid asc) + exact total hit count.

    `lax.top_k` breaks score ties by lowest index, and index == docid, which
    reproduces Lucene's (score, docid) tie-break order exactly
    (reference behavior: TopScoreDocCollector via
    search/query/QueryPhaseCollectorManager.java:416).

    Behind ES_TPU_FUSED_TOPK (default on), large-corpus selection runs as
    the streamed Pallas scan (ops/kernels.scan_topk streamed mode: one
    bandwidth-bound pass holding the running top-k in VMEM) instead of
    sort-based `lax.top_k` — identical (score desc, docid asc) order and
    identical totals, so every per-query searcher (executor, the sharded
    scatter/gather, C2's exhaustive fallback arm) rides the fused path.
    'force' engages it on CPU through the interpreter (tests).

    PR 11 note: callers tracing sharded bodies no longer pin the XLA arm
    (`force_xla` is gone) — pjit shard bodies run inside embedded
    shard_map manual regions (parallel/spmd.manual_shard_region), where
    the Pallas scan is legal because nothing asks GSPMD to partition it.
    """
    import os

    n = live.shape[0]
    ok = match[:n] & live
    if _fused_scan_engages(n, k):
        force = os.environ.get("ES_TPU_FUSED_TOPK", "auto") == "force"
        on_tpu = jax.default_backend() == "tpu"
        from .kernels import scan_topk

        v, i, t = scan_topk(
            None, scores[:n][None, :], ok, k,
            count_positive=False,
            interpret=(not on_tpu) if force else False,
        )
        return v[0], i[0], t[0]
    total = jnp.sum(ok, dtype=jnp.int32)
    masked = jnp.where(ok, scores[:n], -jnp.inf)
    top_scores, top_ids = jax.lax.top_k(masked, k)
    return top_scores, top_ids, total
