"""Dense-vector similarity scoring on the MXU.

The reference serves kNN through Lucene HNSW graph search with SIMD scoring
(reference behavior: index/mapper/vectors/DenseVectorFieldMapper.java:101
similarity functions; search/vectors/KnnVectorQueryBuilder.java:54). On TPU,
shard-sized exact scan IS the fast path: one [N, D] @ [D] matmul on the
systolic array beats a pointer-chasing graph walk, returns exact (not
approximate) neighbors, and vectorizes over query batches for free.

Score functions match the reference's `_score` conventions:
    cosine:             (1 + cos(q, d)) / 2
    dot_product:        (1 + q . d) / 2
    l2_norm:            1 / (1 + ||q - d||^2)
    max_inner_product:  d<0 -> 1/(1-d), else d+1
"""

from __future__ import annotations

import jax.numpy as jnp


def knn_scores(
    vectors: jnp.ndarray,  # [N, D] float32
    sq_norms: jnp.ndarray,  # [N] float32 (precomputed ||d||^2)
    qvec: jnp.ndarray,  # [D] float32
    similarity: str,
) -> jnp.ndarray:
    """-> [N] float32 similarity scores (ES _score convention)."""
    dots = vectors @ qvec
    if similarity == "cosine":
        qn = jnp.sqrt(jnp.sum(qvec * qvec))
        dn = jnp.sqrt(sq_norms)
        cos = dots / jnp.maximum(dn * qn, 1e-30)
        return (1.0 + cos) / 2.0
    if similarity == "dot_product":
        return (1.0 + dots) / 2.0
    if similarity == "l2_norm":
        qsq = jnp.sum(qvec * qvec)
        l2sq = jnp.maximum(sq_norms - 2.0 * dots + qsq, 0.0)
        return 1.0 / (1.0 + l2sq)
    if similarity == "max_inner_product":
        return jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
    raise ValueError(f"unknown similarity [{similarity}]")
