"""Dense-vector similarity scoring on the MXU.

The reference serves kNN through Lucene HNSW graph search with SIMD scoring
(reference behavior: index/mapper/vectors/DenseVectorFieldMapper.java:101
similarity functions; search/vectors/KnnVectorQueryBuilder.java:54). On TPU,
shard-sized exact scan IS the fast path: one [N, D] @ [D] matmul on the
systolic array beats a pointer-chasing graph walk, returns exact (not
approximate) neighbors, and vectorizes over query batches for free.

Score functions match the reference's `_score` conventions:
    cosine:             (1 + cos(q, d)) / 2
    dot_product:        (1 + q . d) / 2
    l2_norm:            1 / (1 + ||q - d||^2)
    max_inner_product:  d<0 -> 1/(1-d), else d+1
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def knn_scores(
    vectors: jnp.ndarray,  # [N, D] float32
    sq_norms: jnp.ndarray,  # [N] float32 (precomputed ||d||^2)
    qvec: jnp.ndarray,  # [D] float32
    similarity: str,
) -> jnp.ndarray:
    """-> [N] float32 similarity scores (ES _score convention)."""
    dots = vectors @ qvec
    if similarity == "cosine":
        qn = jnp.sqrt(jnp.sum(qvec * qvec))
        dn = jnp.sqrt(sq_norms)
        cos = dots / jnp.maximum(dn * qn, 1e-30)
        return (1.0 + cos) / 2.0
    if similarity == "dot_product":
        return (1.0 + dots) / 2.0
    if similarity == "l2_norm":
        qsq = jnp.sum(qvec * qvec)
        l2sq = jnp.maximum(sq_norms - 2.0 * dots + qsq, 0.0)
        return 1.0 / (1.0 + l2sq)
    if similarity == "max_inner_product":
        return jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
    raise ValueError(f"unknown similarity [{similarity}]")


def kmeans_ivf(vectors, nlist: int, iters: int = 8):
    """Host-driven k-means for the IVF partition index (the TPU-native ANN
    replacing the reference's HNSW graphs, index/codec/vectors/ — a graph
    walk is pointer-chasing; nprobe-partitioned brute force is MXU-shaped).

    -> (centroids [C, D] f32, assign [N] int32). Runs the Lloyd iterations
    as jax matmuls (device-accelerated when one is present)."""
    import numpy as np

    vecs = jnp.asarray(vectors, jnp.float32)
    N, D = vecs.shape
    C = max(1, min(nlist, N))
    # deterministic strided init over the corpus
    init_idx = (jnp.arange(C) * (N // C)).astype(jnp.int32)
    centroids = vecs[init_idx]
    for _ in range(iters):
        # argmin ||v-c||^2 == argmax v.c - ||c||^2/2
        logits = vecs @ centroids.T - 0.5 * jnp.sum(centroids * centroids, axis=1)[None, :]
        assign = jnp.argmax(logits, axis=1)
        sums = jnp.zeros((C, D), jnp.float32).at[assign].add(vecs)
        counts = jnp.zeros((C,), jnp.float32).at[assign].add(1.0)
        centroids = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), centroids
        )
    logits = vecs @ centroids.T - 0.5 * jnp.sum(centroids * centroids, axis=1)[None, :]
    assign = jnp.argmax(logits, axis=1)
    return np.asarray(centroids), np.asarray(assign, np.int32)


def build_ivf(vectors, has_value, nlist: int):
    """-> dict(centroids, order, part_start, max_part) partition index over
    the present vectors; None when the corpus is too small to help."""
    import numpy as np

    present = np.flatnonzero(has_value)
    if len(present) < 4 * max(nlist, 1) or nlist <= 1:
        return None
    centroids, assign = kmeans_ivf(vectors[present], nlist)
    C = centroids.shape[0]
    order_local = np.argsort(assign, kind="stable")
    order = present[order_local].astype(np.int32)  # partition-sorted docids
    sizes = np.bincount(assign, minlength=C)
    part_start = np.zeros(C + 1, np.int64)
    np.cumsum(sizes, out=part_start[1:])
    return {
        "centroids": centroids.astype(np.float32),
        "order": order,
        "part_start": part_start.astype(np.int32),
        "max_part": int(sizes.max()),
    }


def ivf_candidates(
    ivf_centroids,  # [C, D] f32
    ivf_order,  # [NV] int32 partition-sorted docids (padded with -1)
    ivf_part_start,  # [C+1] int32
    qvec,  # [D]
    nprobe: int,
    max_part: int,
):
    """-> (cand_ids [nprobe*max_part] int32 with -1 padding). Probes the
    nprobe closest partitions by centroid distance."""
    C = ivf_centroids.shape[0]
    logits = ivf_centroids @ qvec - 0.5 * jnp.sum(
        ivf_centroids * ivf_centroids, axis=1
    )
    _, probe = jax.lax.top_k(logits, min(nprobe, C))
    starts = ivf_part_start[probe]  # [P]
    ends = ivf_part_start[probe + 1]
    offs = jnp.arange(max_part, dtype=jnp.int32)[None, :]
    idx = starts[:, None] + offs  # [P, max_part]
    valid = idx < ends[:, None]
    idx = jnp.clip(idx, 0, ivf_order.shape[0] - 1)
    ids = jnp.where(valid, ivf_order[idx], -1)
    return ids.reshape(-1)
