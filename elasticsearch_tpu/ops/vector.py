"""Dense-vector similarity scoring on the MXU.

The reference serves kNN through Lucene HNSW graph search with SIMD scoring
(reference behavior: index/mapper/vectors/DenseVectorFieldMapper.java:101
similarity functions; search/vectors/KnnVectorQueryBuilder.java:54). On TPU,
shard-sized exact scan IS the fast path: one [N, D] @ [D] matmul on the
systolic array beats a pointer-chasing graph walk, returns exact (not
approximate) neighbors, and vectorizes over query batches for free.

Score functions match the reference's `_score` conventions:
    cosine:             (1 + cos(q, d)) / 2
    dot_product:        (1 + q . d) / 2
    l2_norm:            1 / (1 + ||q - d||^2)
    max_inner_product:  d<0 -> 1/(1-d), else d+1
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _aux_for(similarity: str, sq_norms, qvecs):
    """(aux_doc [N], aux_q [B]) per the kernels._apply_transform contract."""
    if similarity == "cosine":
        aux_doc = 1.0 / jnp.maximum(jnp.sqrt(sq_norms), 1e-30)
        aux_q = 1.0 / jnp.maximum(
            jnp.sqrt(jnp.sum(qvecs * qvecs, axis=1)), 1e-30)
        return aux_doc, aux_q
    if similarity == "l2_norm":
        return sq_norms, jnp.sum(qvecs * qvecs, axis=1)
    return None, None


@functools.partial(jax.jit, static_argnames=("similarity",))
def _rescore_knn(qvecs, vectors, cand_i, cand_ok, aux_doc, aux_q,
                 similarity: str):
    """Exact f32 scores of the selection candidates: row-gather of the
    candidate doc vectors (contiguous rows — the TPU-friendly gather
    shape) + one einsum, then the shared transform."""
    from .kernels import _apply_transform

    cv = jnp.take(vectors, jnp.maximum(cand_i, 0), axis=0)  # [B, KB, D]
    dots = jnp.einsum(
        "bd,bkd->bk", qvecs, cv, precision=jax.lax.Precision.HIGHEST
    )
    auxd = (jnp.take(aux_doc, jnp.maximum(cand_i, 0))
            if aux_doc is not None else jnp.zeros_like(dots))
    auxq = (aux_q[:, None] if aux_q is not None
            else jnp.zeros((dots.shape[0], 1), jnp.float32))
    if similarity == "cosine":
        scores = (1.0 + dots * auxd * auxq) / 2.0
    elif similarity == "l2_norm":
        l2 = jnp.maximum(auxd - 2.0 * dots + auxq, 0.0)
        scores = 1.0 / (1.0 + l2)
    else:
        scores = _apply_transform(dots, similarity, jnp.zeros(()), 0.0)
    return jnp.where(cand_ok, scores, -jnp.inf)


_KNN_EPS = 2e-2  # selection-vs-rescore relative margin (kernels.EPS_TIERED)


class TieredKnnScanner:
    """Exact-kNN scan through the tiered split-bf16 kernel: per doc tile a
    bf16 matmul pair (hi+lo — ~15 mantissa bits on the corpus side) feeds
    a running in-VMEM top-KB selection; survivors are rescored in f32 and
    re-ranked (score desc, docid asc). Queries whose top-k cannot be
    separated from anything the selection could have dropped (margin test,
    same discipline as ops/fused) fall back to the f32-HIGHEST scan_topk
    path, so results are always exact. This replaces 6-pass f32-HIGHEST
    scoring of the full corpus — the 1.9% MFU of VERDICT weak #6 — with
    2 bf16 passes + a [B, KB, D] rescore."""

    def __init__(self, vectors, sq_norms, similarity: str, live=None,
                 kb: int | None = None, interpret: bool | None = None,
                 ann: dict | None = None, ann_tier: str = "int8"):
        from .kernels import KB_TIERED, split_bf16

        self.similarity = similarity
        self.vectors = jnp.asarray(vectors, jnp.float32)  # [N, D]
        self.sq_norms = jnp.asarray(sq_norms, jnp.float32)
        N = self.vectors.shape[0]
        self.live = (jnp.ones((N,), bool) if live is None
                     else jnp.asarray(live))
        self.kb = kb or KB_TIERED
        self.interpret = interpret
        mat_t = self.vectors.T  # [D, N]
        self.mat_hi, self.mat_lo = jax.jit(split_bf16)(mat_t)
        self.mat_t = mat_t  # exact fallback operand
        # tier selection: an ANN index (ann/index.build_ann output)
        # promotes the scan to probe + quantized gather-scan + rescore;
        # exact tiers above stay the fallback (and serve ann=None)
        self.ann = None
        if ann is not None:
            from ..ann import AnnSearcher

            self.ann = AnnSearcher(
                ann, vectors, sq_norms, similarity, live=live,
                tier=ann_tier, interpret=interpret)

    def search(self, qvecs, k: int, *, nprobe: int | None = None,
               num_candidates: int | None = None):
        """-> (scores [B, k], ids [B, k], totals [B], first_pass_ok [B])
        numpy; exact (flagged queries re-run on the f32 scan). With an
        ANN tier the candidate SET is approximate (recall governed by
        nprobe) while returned scores stay exact f32; first_pass_ok is
        then all-true — no escalation pass runs."""
        import numpy as np

        from ..telemetry import time_kernel
        from .kernels import scan_topk, tiered_candidates

        if self.ann is not None:
            v, i, t = self.ann.search(
                qvecs, k, nprobe=nprobe, num_candidates=num_candidates)
            return v, i, t, np.ones(v.shape[0], bool)
        qvecs = jnp.asarray(qvecs, jnp.float32)
        B, D = qvecs.shape
        N = self.vectors.shape[0]
        kb = max(self.kb, k)
        k_eff = min(k, kb)
        # the timed window spans dispatch THROUGH fetch: on an async
        # backend compute overlaps dispatch, so a fetch-only window would
        # undercount the kernel and report impossible >1 MFU
        with time_kernel("vector.knn_tiered", tier="fused", queries=B,
                         dims=D, num_docs=N, kb=kb, k=k):
            aux_doc, aux_q = _aux_for(self.similarity, self.sq_norms, qvecs)
            sel_v, sel_i, totals = tiered_candidates(
                qvecs, self.mat_hi, self.mat_lo, self.live, kb,
                transform=self.similarity, aux_doc=aux_doc, aux_q=aux_q,
                count_positive=False, interpret=self.interpret,
            )
            cand_ok = jnp.isfinite(sel_v)
            resc = _rescore_knn(
                qvecs, self.vectors, sel_i, cand_ok, aux_doc, aux_q,
                self.similarity,
            )
            # exact (score desc, docid asc): ascending sort on (-score, id)
            neg, ids = jax.lax.sort(
                (jnp.where(cand_ok, -resc, jnp.inf), sel_i), num_keys=2
            )
            v = -neg[:, :k_eff]
            i = ids[:, :k_eff]
            # margin safety: the k-th rescored score must clear everything
            # the selection pass could have dropped (bounded by the kb-th
            # selection score inflated by the split error), or the
            # selection must have kept every candidate (kb-th lane empty /
            # rescored-min tie)
            sel_kb = sel_v[:, -1]
            am_resc = jnp.min(jnp.where(cand_ok, resc, jnp.inf), axis=1)
            rk = v[:, k_eff - 1]
            bound = sel_kb + _KNN_EPS * jnp.abs(sel_kb) + 1e-6
            safe = jnp.isneginf(sel_kb) | (rk > bound) | (rk == am_resc)
            # np.array (copy): device_get can hand back read-only views,
            # and the flagged-query fallback writes rows in place
            v, i, totals, safe = (np.array(x) for x in
                                  jax.device_get((v, i, totals, safe)))
        if k > k_eff:
            pad = ((0, 0), (0, k - k_eff))
            v = np.pad(v, pad, constant_values=-np.inf)
            i = np.pad(i, pad)
        if not safe.all():
            flagged = np.nonzero(~safe)[0]
            from ..monitoring.xla_introspect import check_dispatch
            from .kernels import scan_topk_xla

            # PR 12: the f32 matmul+top-k scan is the dense-matmul parity
            # anchor of the XLA cross-check — the executed XLA arm (the
            # CPU/escalation route of scan_topk) lowered against the
            # analytic knn_scan_cost
            check_dispatch(
                "vector.knn_scan", scan_topk_xla,
                (qvecs[flagged], self.mat_t, self.live,
                 aux_doc if aux_doc is not None
                 else jnp.zeros((N,), jnp.float32),
                 aux_q[flagged] if aux_q is not None
                 else jnp.zeros((int(flagged.shape[0]),), jnp.float32)),
                kwargs={"k": k, "transform": self.similarity,
                        "count_positive": False},
                fields={"queries": int(flagged.shape[0]), "dims": D,
                        "num_docs": N, "k": k})
            with time_kernel("vector.knn_scan", tier="exact_escalation",
                             queries=int(flagged.shape[0]), dims=D,
                             num_docs=N, k=k):
                fv, fi, _ft = scan_topk(
                    qvecs[flagged], self.mat_t, self.live, k,
                    transform=self.similarity, aux_doc=aux_doc,
                    aux_q=None if aux_q is None else aux_q[flagged],
                    count_positive=False, interpret=self.interpret,
                )
                fv, fi = np.asarray(fv), np.asarray(fi)
            v[flagged] = fv
            i[flagged] = fi
        return v, i, np.asarray(totals), safe


def knn_scores(
    vectors: jnp.ndarray,  # [N, D] float32
    sq_norms: jnp.ndarray,  # [N] float32 (precomputed ||d||^2)
    qvec: jnp.ndarray,  # [D] float32
    similarity: str,
) -> jnp.ndarray:
    """-> [N] float32 similarity scores (ES _score convention)."""
    dots = vectors @ qvec
    if similarity == "cosine":
        qn = jnp.sqrt(jnp.sum(qvec * qvec))
        dn = jnp.sqrt(sq_norms)
        cos = dots / jnp.maximum(dn * qn, 1e-30)
        return (1.0 + cos) / 2.0
    if similarity == "dot_product":
        return (1.0 + dots) / 2.0
    if similarity == "l2_norm":
        qsq = jnp.sum(qvec * qvec)
        l2sq = jnp.maximum(sq_norms - 2.0 * dots + qsq, 0.0)
        return 1.0 / (1.0 + l2sq)
    if similarity == "max_inner_product":
        return jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
    raise ValueError(f"unknown similarity [{similarity}]")


def kmeans_ivf(vectors, nlist: int, iters: int = 8):
    """k-means for the IVF partition index (the TPU-native ANN replacing
    the reference's HNSW graphs, index/codec/vectors/ — a graph walk is
    pointer-chasing; nprobe-partitioned brute force is MXU-shaped).

    -> (centroids [C, D] f32, assign [N] int32).

    PR 15 (ROADMAP item 2): the Lloyd loop runs as ONE jitted device
    program — matmul+argmin assignment waves under lax.while_loop with
    an on-device convergence exit (index/device_build.kmeans_device) —
    instead of the per-iteration eager dispatches that made kmeans ~97%
    of the r11 ANN build wall."""
    from ..index.device_build import kmeans_device

    centroids, assign, _iters_run = kmeans_device(vectors, nlist,
                                                  iters=iters)
    return centroids, assign


# build_ivf / ivf_candidates (the host-side probe layout) were promoted
# to the device-resident ANN subsystem in PR 7: see ann/index.build_ann
# (padded cluster tiles + quantized tiers) and ann/kernels (the batched
# gather-scan the old per-query host gather became).
