from .stacked import StackedPack, build_stacked_pack
from .sharded import StackedSearcher, make_mesh
from .spmd import (
    PACK_PARTITION_RULES,
    match_partition_rules,
    maybe_init_distributed,
    spmd_mode,
)

__all__ = [
    "StackedPack", "build_stacked_pack", "StackedSearcher", "make_mesh",
    "PACK_PARTITION_RULES", "match_partition_rules",
    "maybe_init_distributed", "spmd_mode",
]
