from .stacked import StackedPack, build_stacked_pack
from .sharded import StackedSearcher, make_mesh

__all__ = ["StackedPack", "build_stacked_pack", "StackedSearcher", "make_mesh"]
