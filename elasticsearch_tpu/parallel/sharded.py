"""Sharded query execution over a TPU mesh.

The scatter/gather that the reference does with async per-shard RPCs
(reference behavior: AbstractSearchAsyncAction.java:301 fan-out,
SearchPhaseController.java:232 `TopDocs.merge`, coordinator agg reduce) is
here a single SPMD program: `shard_map` over a `Mesh(("shards",))` runs the
identical per-shard scoring body on every device, and the global top-k merge
is a `lax.top_k` over the gathered [S, k] partials — XLA lowers the gather to
ICI collectives. Tie-break order (score desc, shard asc, local docid asc)
falls out of flat-index ordering, matching Lucene's merge.

On a single device (e.g. one TPU chip benching an 8-shard index) the same
body runs under `vmap` over the shard axis instead — same math, no mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.scoring import top_k_with_total
from ..query.dsl import parse_query
from ..utils.jax_env import shard_map
from ..utils.errors import IllegalArgumentError
from ..query.nodes import ExecContext, QueryNode
from .stacked import StackedPack


def wand_gate_min_rows() -> int:
    """Resolved WAND profitability gate: minimum total CSR block rows for
    the two-pass pruned plan to engage. The single source of truth —
    bench.py's crossover reporting reads THIS, so a retuned default can
    never desynchronize the bench from production. Derivation: the
    exhaustive batched kernel clears ~1-2G postings/s while the pruned
    plan pays an extra device round trip + host posting prune, so pruning
    pays only once a query's CSR postings are of order 10^7 (~10^5 block
    rows) — see BENCH_NOTES.md C2."""
    return int(os.environ.get("ES_TPU_WAND_MIN_ROWS", 100_000))


import functools


def _impact_codes_device(tfs, dls, k_base, k_slope, scale_inv, *,
                         qmax, dtype):
    """Device twin of index/pack.impact_codes_host (asserted equal by
    tests/test_impact.py): derive the quantized impact code blocks from
    the resident postings — ONE elementwise pass at refresh, so dfs-stat
    drift (stats_override under tiered refresh) re-norms the impact tier
    without a host rebuild or re-transfer (the refresh_dense_tfn
    discipline applied to the sparse tier). PR 15: the kernel itself
    moved to index/device_build (shared with the build-time device
    quantization path)."""
    from ..index.device_build import impact_codes_device

    return impact_codes_device(tfs, dls, k_base, k_slope, scale_inv,
                               qmax=qmax, dtype=dtype)


def make_mesh(num_shards: int) -> Mesh | None:
    """Mesh over the first num_shards devices; None -> single-device vmap.
    Delegates to parallel/spmd.make_mesh (which adds the pjit-mode
    replica axis and the multi-process stretch wiring)."""
    from .spmd import make_mesh as _mk

    return _mk(num_shards)


def _stack_shard_params(per_shard: list):
    """Stack per-shard param pytrees; ragged 1-D int32 leaves (postings block
    rows) are padded with the reserved row 0 to the max bucket size."""
    import jax.tree_util as jtu

    leaves_list = [jtu.tree_leaves(p) for p in per_shard]
    treedef = jtu.tree_structure(per_shard[0])
    stacked = []
    for leaf_group in zip(*leaves_list):
        shapes = {np.shape(x) for x in leaf_group}
        if len(shapes) == 1:
            stacked.append(np.stack([np.asarray(x) for x in leaf_group]))
        else:
            arrs = [np.asarray(x) for x in leaf_group]
            if any(a.ndim != 1 for a in arrs):
                raise ValueError("cannot stack ragged non-1D shard params")
            width = max(a.shape[0] for a in arrs)
            out = np.zeros((len(arrs), width), arrs[0].dtype)
            for i, a in enumerate(arrs):
                out[i, : a.shape[0]] = a
            stacked.append(out)
    return jtu.tree_unflatten(treedef, stacked)


def stacked_to_device(sp: StackedPack, mesh: Mesh | None) -> dict:
    """[S, ...] arrays -> device as a SHARDED PYTREE.

    The host tree is built first (numpy leaves), then every leaf ships
    via `jax.device_put` with the NamedSharding produced by the
    partition-rule table (spmd.match_partition_rules over leaf names) —
    the GSPMD discipline SNIPPETS [1][2] apply to params pytrees. A pack
    component whose name matches no rule is a hard error at upload, not
    a silently replicated array. mesh=None keeps plain `jnp.asarray`.

    PR 13: the upload is a profiled build stage (`build.device_put`, the
    host→device transfer the item-2 device builders will mostly delete)
    and counts a kind="refresh" host transition, so background merges
    get the same transition budget the serving waves hold (≤1+1/wave)."""
    from ..monitoring.refresh_profile import build_stage
    from ..telemetry import host_transition
    from ..utils.jax_env import ensure_x64

    ensure_x64()
    host_transition("refresh")
    # the host-tree assembly (numpy staging copies) is upload prep —
    # charged to the device_put stage, not the profile residual
    with build_stage("build.device_put", nbytes=sp.nbytes()):
        host = _stacked_host_tree(sp)
        if mesh is None:
            import jax.tree_util as jtu

            return jtu.tree_map(jnp.asarray, host)
        from .spmd import shard_put

        return shard_put(host, mesh)


def _stacked_host_tree(sp: StackedPack) -> dict:
    """The pack pytree with host (numpy) leaves — the input of the
    partition-rule matching; leaf PATHS here are the rule vocabulary."""
    put = np.asarray
    dev = {
        "post_docids": put(sp.post_docids),
        "post_tfs": put(sp.post_tfs),
        "post_dls": put(sp.post_dls),
        "norms": {f: put(a) for f, a in sp.norms.items()},
        "text_has": {f: put(a) for f, a in sp.text_present.items()},
        "dv_int": {},
        "dv_float": {},
        "dv_ord": {},
        "dv_mv": {},
        "dv_int_ord": {},
        "live": put(sp.live),
        "vec": {},
        "vec_has": {},
    }
    for f, col in sp.stacked_docvalues.items():
        key = {"int": "dv_int", "float": "dv_float", "ord": "dv_ord"}[col.kind]
        vals = col.values if col.kind != "ord" else col.values.astype(np.int64)
        dev[key][f] = (put(vals), put(col.has_value))
        if col.uniq_ords is not None:
            dev["dv_int_ord"][f] = put(col.uniq_ords)
        if col.mv_pair_docs is not None:
            dev["dv_mv"][f] = (put(col.mv_pair_docs), put(col.mv_pair_ords))
    dev["vec_sq"] = {}
    dev["vec_ann"] = {}
    for f, vc in sp.vectors.items():
        dev["vec"][f] = put(vc.values)
        dev["vec_has"][f] = put(vc.has_value)
        dev["vec_sq"][f] = put((vc.values * vc.values).sum(axis=-1).astype(np.float32))
        if vc.ann is not None:
            from ..ann import ann_to_device

            dev["vec_ann"][f] = ann_to_device(vc.ann, vc.values, put)
    if getattr(sp, "dense_tf", None) is not None:
        dev["dense_tf"] = put(sp.dense_tf)
    if sp.pos_keys is not None:
        dev["pos_keys"] = put(sp.pos_keys)
    return dev


@dataclass
class StackedResult:
    doc_shards: np.ndarray  # [<=k] int32 shard of each hit
    doc_ids: np.ndarray  # [<=k] int32 local docid within the shard
    scores: np.ndarray  # [<=k] float32
    total: int
    max_score: float | None
    aggregations: dict | None = None
    # "eq" for exhaustive runs; "gte" when block-max pruning made the
    # total a lower bound (reference: hits.total.relation)
    total_relation: str = "eq"


def _copy_stacked_result(res: StackedResult) -> StackedResult:
    """Defensive copy for cache store/serve: the engine mutates results in
    place (rescore reorders, pipeline aggs rewrite the agg tree), so the
    cached original must never be handed out by reference."""
    import copy as _copy

    out = StackedResult(
        res.doc_shards.copy(), res.doc_ids.copy(), res.scores.copy(),
        res.total, res.max_score, _copy.deepcopy(res.aggregations),
        res.total_relation,
    )
    ws = getattr(res, "wand_stats", None)
    if ws is not None:
        out.wand_stats = dict(ws)
    return out


def _stacked_result_nbytes(res: StackedResult) -> int:
    n = int(res.doc_shards.nbytes + res.doc_ids.nbytes
            + res.scores.nbytes) + 256
    if res.aggregations:
        try:
            n += len(json.dumps(res.aggregations, default=str))
        except Exception:  # noqa: BLE001 - estimate only
            n += 4096
    return n


class StackedSearcher:
    """Multi-shard searcher: one mesh-resident stacked pack + compiled plans.

    Scores with global term statistics — the reference's
    dfs_query_then_fetch (TransportSearchAction DFS phase /
    search/dfs/DfsPhase.java). The default per-shard-idf query_then_fetch
    mode is intentionally not reproduced: its cross-shard score skew is an
    artifact of distributed nodes, and global stats are free here."""

    def __init__(self, stacked: StackedPack, mesh: Mesh | None = None):
        from .spmd import spmd_mode

        self.sp = stacked
        self.mesh = mesh
        # execution model, resolved at construction (ES_TPU_SPMD):
        #   vmap     — no mesh: plain vmap over the stacked axis
        #   pjit     — GSPMD: vmapped bodies over the sharded pack pytree,
        #              with_sharding_constraint on hot intermediates, the
        #              global merge on-device (ICI all-gather + lax.top_k)
        #   shardmap — legacy per-shard shard_map bodies + host merge
        self._exec = ("vmap" if mesh is None else spmd_mode())
        if mesh is not None and "replicas" in mesh.axis_names \
                and self._exec == "shardmap":
            # the shard_map specs name only "shards"; a replica mesh is a
            # pjit-mode construct
            self._exec = "pjit"
        self.dev = stacked_to_device(stacked, mesh)
        self.ctx = ExecContext(
            num_docs=stacked.n_max,
            avgdl={f: self._avgdl(f) for f in stacked.norms},
            has_norms=frozenset(stacked.norms),
            sharded=True,
        )
        from ..index.pack import BM25_K1, BM25_B

        assert not stacked.dense_dict or (self.ctx.k1, self.ctx.b) == (BM25_K1, BM25_B), (
            "dense-tier packs bake default k1/b; rebuild with dense disabled"
        )
        self._cache: dict = {}
        self._dense_tfn_fn = None
        # shard request cache identity: per-shard epochs so one shard's
        # in-place mutation invalidates only its own entries (plus the
        # whole-searcher merged-result entries), and a dfs-stats epoch for
        # scoring-statistics drift under tiered refresh
        from ..cache import next_searcher_token

        self.cache_token = next_searcher_token()
        self._shard_epochs = [0] * stacked.S
        self._stats_epoch = 0
        self.refresh_dense_tfn()
        self.refresh_impacts()

    # -- shard request cache ----------------------------------------------

    def shard_cache_scope(self, s: int):
        """-> (token, epoch) keying shard `s`'s per-shard cache entries."""
        return ((self.cache_token, s),
                (self._shard_epochs[s], self._stats_epoch))

    def cache_scope(self):
        """-> (token, epoch) for whole-searcher (merged) results; depends
        on every shard's epoch, so any shard bump invalidates it."""
        return ((self.cache_token, -1),
                (tuple(self._shard_epochs), self._stats_epoch))

    def bump_epoch(self, shard: int | None = None, stats: bool = False):
        """Invalidate cached results after an in-place mutation: all
        shards (refresh/delete/merge) or one shard; stats=True also marks
        a dfs-statistics change (stats_override drift)."""
        if shard is None:
            self._shard_epochs = [e + 1 for e in self._shard_epochs]
        else:
            self._shard_epochs[shard] += 1
        if stats:
            self._stats_epoch += 1
        from ..cache import request_cache

        request_cache().invalidate_searcher(self.cache_token, shard=shard)

    def refresh_dense_tfn(self):
        """(Re)compute the scored dense tier dev["dense_tfn"] from the raw
        tf rows + norms + CURRENT per-field avgdl — one elementwise device
        pass, so stat drift (tiered refresh) never rebuilds the tier on the
        host or re-transfers it."""
        if "dense_tf" not in self.dev:
            return
        import itertools

        if self._dense_tfn_fn is None:
            slices = []
            v0 = 0
            for fld, group in itertools.groupby(self.sp.dense_fields):
                c = sum(1 for _ in group)
                slices.append((fld, v0, v0 + c, fld in self.sp.norms))
                v0 += c
            self._dense_slices = slices
            k1, b = self.ctx.k1, self.ctx.b

            def compute(tf, norms, avgdls):
                parts = []
                for i, (fld, a, c, hn) in enumerate(slices):
                    tfa = tf[:, a:c, :]
                    if hn:
                        K = k1 * (1.0 - b + b * norms[fld] / avgdls[i])
                        parts.append(tfa / (tfa + K[:, None, :]))
                    else:
                        parts.append(tfa / (tfa + k1))
                return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)

            self._dense_tfn_fn = jax.jit(compute)
        avgdls = jnp.asarray(
            [max(self._avgdl(fld), 1e-9) for fld, _a, _c, _hn in self._dense_slices],
            jnp.float32,
        )
        self.dev["dense_tfn"] = self._dense_tfn_fn(
            self.dev["dense_tf"], self.dev["norms"], avgdls)

    def _avgdl(self, fld):
        st = self.sp.eff_field_stats.get(fld)
        if not st or st["doc_count"] == 0:
            return 1.0
        return st["sum_dl"] / st["doc_count"]

    def refresh_impacts(self):
        """(Re)derive the impact tier's quantized code blocks on device
        from the CURRENT effective field stats (the length-norm K bakes
        avgdl; idf stays query-time host math, so dfs-df drift needs no
        rebuild at all). Called at construction and after every
        stats_override change (engine tiered refresh); until then the
        stale basis makes impact_serving() False and planning degrades to
        the exact raw-postings path."""
        sp = self.sp
        if sp.impact_meta is None:
            return
        meta = sp.impact_meta
        if (self.ctx.k1, self.ctx.b) != (meta["k1"], meta["b"]):
            # a custom-similarity context cannot serve quantized defaults
            self.dev.pop("impact_codes", None)
            return
        from ..monitoring.refresh_profile import build_stage

        fields = sp.impact_fields
        fld_avgdl = np.array(
            [max(self._avgdl(f), 1e-9) for f in fields] or [1.0], np.float64)
        fld_hn = np.array([f in sp.norms for f in fields] or [False])
        rf = sp.impact_row_field  # [S, nb_max]
        safe = np.maximum(rf, 0)
        hn = fld_hn[safe] & (rf >= 0)
        k1, b = meta["k1"], meta["b"]
        k_base = np.where(hn, k1 * (1.0 - b), k1).astype(np.float32)
        k_slope = np.where(hn, k1 * b / fld_avgdl[safe], 0.0).astype(
            np.float32)
        # the device twin of the pack.py host derivation — same kernel
        # name, basis="device", so the write-path profile shows the
        # host-vs-device split of impact quantization directly
        with build_stage("build.impact_quantize",
                         rows=int(self.sp.S) * int(self.sp.nb_max),
                         code_bytes=2 if meta["dtype"] == "uint16" else 1,
                         basis="device"):
            self.dev["impact_codes"] = _impact_codes_device(
                self.dev["post_tfs"], self.dev["post_dls"],
                jnp.asarray(k_base), jnp.asarray(k_slope),
                jnp.asarray(sp.impact_row_scale_inv),
                qmax=meta["qmax"], dtype=meta["dtype"])
        sp._impact_basis = sp.stats_override

    def update_live(self):
        """Re-ship the live-docs bitmap after host-side flips (tiered
        refresh marks superseded/deleted base docs dead in place). The
        flip changes every shard's visible result set, so the request
        cache epoch bumps here — stale entries become unreachable AND are
        dropped."""
        from ..monitoring.refresh_profile import build_stage
        from ..telemetry import host_transition

        host_transition("refresh")
        with build_stage("build.device_put", nbytes=self.sp.live.nbytes):
            if self.mesh is not None:
                self.dev["live"] = jax.device_put(
                    self.sp.live, NamedSharding(self.mesh, P("shards")))
            else:
                self.dev["live"] = jnp.asarray(self.sp.live)
        self.bump_epoch()

    def _compiled(self, node, key, k, agg_nodes, agg_key):
        cache_key = (key, k, agg_key, self._exec)
        fn = self._cache.get(cache_key)
        if fn is not None:
            return fn
        ctx = self.ctx
        n = self.sp.n_max
        S = self.sp.S
        # a shard can contribute at most n_max hits; the global k may exceed it
        k_local = min(k, n)
        k_global = min(k, S * k_local)

        def shard_body(dev1, par1, agg_par1):
            # PR 11: no force_xla pin — the body runs inside an embedded
            # shard_map manual region, where the streamed Pallas scan is
            # legal (GSPMD never sees the custom call), so the selection
            # tier is the SAME one the single-device path picks
            scores, match = node.device_eval(dev1, par1, ctx)
            ts, ti, tot = top_k_with_total(scores, match, dev1["live"],
                                           k_local)
            agg_out = {}
            if agg_nodes:
                ok = match[:n] & dev1["live"]
                seg = jnp.where(ok, 0, 1).astype(jnp.int32)
                dev_a = {**dev1, "_query_scores": scores[:n]}
                for name, anode in agg_nodes.items():
                    agg_out[name] = anode.device_eval_segmented(
                        dev_a, agg_par1[name], seg, 1, ok, ctx
                    )
            return ts, ti, tot, agg_out

        from .spmd import constrain_shards, manual_shard_region

        region = manual_shard_region(
            shard_body, self.mesh,
            in_specs=(P("shards"), P("shards"), P("shards")))

        def inner(dev, params, agg_params):
            # the constraint pins the [S, ...] outputs shard-local until
            # the merge below forces the all-gather
            return constrain_shards(region(dev, params, agg_params),
                                    self.mesh)

        def run(dev, params, agg_params):
            ts, ti, tot, agg_out = inner(dev, params, agg_params)
            # global merge: flat index order = (score desc, shard asc,
            # local rank asc) — Lucene TopDocs.merge order. In pjit mode
            # the replication constraint IS the ICI all-gather of the
            # per-shard (score, doc) rows; the merged result is
            # replicated, so the host fetch pulls k rows, not S*k.
            from .spmd import constrain

            flat = ts.reshape(-1)
            flat_i = ti.reshape(-1)
            if self._exec == "pjit":
                flat = constrain(flat, self.mesh, P())
                flat_i = constrain(flat_i, self.mesh, P())
            g_scores, g_idx = jax.lax.top_k(flat, k_global)
            g_shard = (g_idx // k_local).astype(jnp.int32)
            g_doc = flat_i[g_idx]
            return g_scores, g_shard, g_doc, tot.sum(), agg_out

        fn = jax.jit(run)
        self._cache[cache_key] = fn
        return fn

    def ensure_runtime_field(self, name: str, rtype: str, script) -> None:
        """Materialize a runtime field as a docvalues column (reference
        behavior: search-request runtime_mappings, mapper/RuntimeField.java —
        script-computed per query; here computed once per unique script and
        cached on the searcher, then visible to queries/aggs/sort like any
        mapped column).

        The script is the expression language (script/expression.py); ES
        `emit(expr)` sources are accepted by unwrapping the emit call."""
        from ..index.pack import DocValuesColumn
        from ..script.expression import compile_script

        if not hasattr(self, "_runtime_fields"):
            self._runtime_fields = {}
            self._runtime_cache = {}       # (name, rtype, src) -> artifacts
            self._runtime_plan_key = {}    # name -> key compiled plans baked
        src = script.get("source") if isinstance(script, dict) else script
        params = (script.get("params") if isinstance(script, dict) else None) or {}
        # params are baked into the compiled expression as constants, so they
        # are part of the field's identity
        cache_key = (name, rtype, src, json.dumps(params, sort_keys=True))
        if self._runtime_fields.get(name) == cache_key:
            return
        if name in self.sp.global_docvalues and name not in self._runtime_fields:
            raise IllegalArgumentError(
                f"runtime field [{name}] shadows a mapped field"
            )
        if rtype not in ("long", "double", "date", "boolean"):
            raise IllegalArgumentError(
                f"runtime field type [{rtype}] is not supported (numeric only)"
            )
        # compiled plans may have baked this field's vocab size / shapes — if
        # the definition changed since they were built, drop all plans
        # (redefinition is rare; a full flush is exact where name-matching
        # heuristics over/under-flush)
        if self._runtime_plan_key.get(name, cache_key) != cache_key:
            self._cache.clear()
        self._runtime_plan_key[name] = cache_key
        cached = self._runtime_cache.get(cache_key)
        if cached is not None:
            self._install_runtime_field(name, cache_key, cached)
            return
        s = src.strip()
        if s.startswith("emit(") and s.endswith(")"):
            s = s[5:-1]
        compiled = compile_script({"source": s, "params": params})
        S = self.sp.S
        n_max = self.sp.n_max
        dtype = np.int64 if rtype in ("long", "date", "boolean") else np.float32
        vals = np.zeros((S, n_max), dtype)
        has = np.zeros((S, n_max), bool)
        for i, p in enumerate(self.sp.shards):
            n = p.num_docs
            if n == 0:
                continue
            env = {}
            h_all = np.ones(n, bool)
            for f in compiled.fields:
                col = p.docvalues.get(f)
                if col is None or col.kind == "ord":
                    env[f] = np.zeros(n, np.float32)
                    h_all &= False
                else:
                    env[f] = np.where(col.has_value, col.values, 0).astype(np.float32)
                    h_all &= col.has_value
            out = np.asarray(compiled.evaluate(env))
            out = np.broadcast_to(out, (n,))
            vals[i, :n] = out.astype(dtype)
            has[i, :n] = h_all
        kind = "int" if dtype == np.int64 else "float"
        g = DocValuesColumn(kind, vals, has)
        present = vals[has]
        if present.size:
            g.vmin = present.min().item()
            g.vmax = present.max().item()
            if kind == "int":
                uniq = np.unique(present)
                g.uniq_values = uniq
                ords = np.full((S, n_max), -1, np.int32)
                ords[has] = np.searchsorted(uniq, vals[has]).astype(np.int32)
                g.uniq_ords = ords
        # per-shard planning view (prepare() reads pack.docvalues)
        pcs = []
        for i, p in enumerate(self.sp.shards):
            pc = DocValuesColumn(kind, vals[i, : p.num_docs], has[i, : p.num_docs])
            pc.vmin, pc.vmax = g.vmin, g.vmax
            if g.uniq_values is not None:
                pc.uniq_values = g.uniq_values
                pc.uniq_ords = g.uniq_ords[i, : p.num_docs]
            pcs.append(pc)
        put = (lambda x: jax.device_put(
            x, NamedSharding(self.mesh, P("shards", *([None] * (np.ndim(x) - 1))))
        )) if self.mesh is not None else jnp.asarray
        key = {"int": "dv_int", "float": "dv_float"}[kind]
        dev_entries = {key: (put(vals), put(has))}
        if g.uniq_ords is not None:
            dev_entries["dv_int_ord"] = put(g.uniq_ords)
        artifacts = {"g": g, "pcs": pcs, "dev": dev_entries}
        if len(self._runtime_cache) >= 16:  # bound memory for one-off scripts
            self._runtime_cache.pop(next(iter(self._runtime_cache)))
        self._runtime_cache[cache_key] = artifacts
        self._install_runtime_field(name, cache_key, artifacts)

    def _install_runtime_field(self, name, cache_key, artifacts) -> None:
        self.sp.stacked_docvalues[name] = artifacts["g"]
        self.sp.global_docvalues[name] = artifacts["g"]
        for p, pc in zip(self.sp.shards, artifacts["pcs"]):
            p.docvalues[name] = pc
        for key, val in artifacts["dev"].items():
            self.dev[key][name] = val
        self._runtime_fields[name] = cache_key

    def remove_runtime_fields(self, names) -> None:
        """Uninstall request-scoped runtime fields after the request
        (reference: runtime_mappings are per-search-request; they must not
        leak into later requests on the same index). Materialized columns
        stay in _runtime_cache so a repeat of the same request reinstalls
        without recomputing."""
        for name in names:
            if not getattr(self, "_runtime_fields", {}).pop(name, None):
                continue
            self.sp.stacked_docvalues.pop(name, None)
            self.sp.global_docvalues.pop(name, None)
            for p in self.sp.shards:
                p.docvalues.pop(name, None)
            for key in ("dv_int", "dv_float", "dv_int_ord"):
                self.dev.get(key, {}).pop(name, None)

    def _compiled_collapse(self, node, key, fld, k):
        """Field collapsing: best hit per field value (reference behavior:
        search/collapse/CollapseBuilder.java + Lucene CollapsingTopDocsCollector).
        Groups = global ordinals of `fld`; docs missing the field share the
        null group. Per shard: scatter-max score per group + lowest-docid
        winner; global: max over shards per group, then top-k groups."""
        cache_key = ("collapse", key, fld, k, self._exec)
        fn = self._cache.get(cache_key)
        if fn is not None:
            return fn
        ctx = self.ctx
        n = self.sp.n_max
        S = self.sp.S

        col = self.sp.global_docvalues.get(fld)
        V = len(col.ord_terms) if (col is not None and col.kind == "ord") else (
            len(col.uniq_values) if (col is not None and col.uniq_values is not None) else 0
        )

        def shard_body(dev1, par1):
            scores, match = node.device_eval(dev1, par1, ctx)
            ok = match[:n] & dev1["live"]
            total = jnp.sum(ok, dtype=jnp.int32)
            s = scores[:n]
            if fld in dev1["dv_ord"]:
                ords, h = dev1["dv_ord"][fld]
                ords = ords.astype(jnp.int32)
            elif fld in dev1["dv_int_ord"]:
                ords, h = dev1["dv_int_ord"][fld], dev1["dv_int"][fld][1]
            else:
                ords = jnp.full(n, -1, jnp.int32)
                h = jnp.zeros(n, bool)
            grp = jnp.where(h & (ords >= 0), ords, V)  # null group = V
            docids = jnp.arange(n, dtype=jnp.int32)
            masked = jnp.where(ok, s, -jnp.inf)
            gmax = jnp.full(V + 1, -jnp.inf, jnp.float32).at[grp].max(masked)
            ismax = ok & (masked == gmax[grp]) & jnp.isfinite(masked)
            # non-winner lanes scatter INT_MAX, which never wins a min
            gdoc = jnp.full(V + 1, 2**31 - 1, jnp.int32).at[grp].min(
                jnp.where(ismax, docids, 2**31 - 1)
            )
            return gmax, gdoc, total

        from .spmd import constrain_shards, manual_shard_region

        region = manual_shard_region(
            shard_body, self.mesh, in_specs=(P("shards"), P("shards")))

        def inner(dev, params):
            return constrain_shards(region(dev, params), self.mesh)

        def run(dev, params):
            gmax, gdoc, tot = inner(dev, params)  # [S, V+1] x2, [S]
            best = jnp.max(gmax, axis=0)  # [V+1]
            # winner shard: lowest shard index among maxima (merge tie-break)
            is_best = gmax == best[None, :]
            shard_sel = jnp.min(
                jnp.where(is_best, jnp.arange(S)[:, None], S), axis=0
            )
            shard_c = jnp.clip(shard_sel, 0, S - 1)
            doc_sel = jnp.take_along_axis(gdoc, shard_c[None, :], axis=0)[0]
            kk = min(k, V + 1)
            top_s, top_g = jax.lax.top_k(jnp.where(jnp.isfinite(best), best, -jnp.inf), kk)
            return (
                top_s, shard_c[top_g], doc_sel[top_g], top_g,
                tot.sum(),
            )

        fn = jax.jit(run)
        self._cache[cache_key] = (fn, V)
        return fn, V

    def search_collapse(self, query, fld: str, size=10, from_=0) -> StackedResult:
        m = self.sp.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        S = self.sp.S
        views = [self.sp.shard_view(s) for s in range(S)]
        per_shard, keys = [], []
        for v in views:
            p, k_ = node.prepare(v)
            per_shard.append(p)
            keys.append(k_)
        params = _stack_shard_params(per_shard)
        k = max(size + from_, 1)
        got = self._compiled_collapse(node, tuple(keys), fld, k)
        fn, V = got
        top_s, top_shard, top_doc, top_g, total = jax.device_get(fn(self.dev, params))
        col = self.sp.global_docvalues.get(fld)
        valid = np.isfinite(top_s)
        res_keys = []
        for g, ok_ in zip(top_g, valid):
            if not ok_:
                continue
            if int(g) >= V or col is None:
                res_keys.append(None)
            elif col.kind == "ord":
                res_keys.append(col.ord_terms[int(g)])
            else:
                res_keys.append(int(col.uniq_values[int(g)]))
        end = max(size + from_, 0)
        out = StackedResult(
            top_shard[valid][from_:end].astype(np.int32),
            top_doc[valid][from_:end].astype(np.int32),
            top_s[valid][from_:end].astype(np.float32),
            int(total),
            float(top_s[0]) if valid.any() else None,
        )
        out.collapse_keys = res_keys[from_:end]
        return out

    def scores_at(self, query, doc_shards: np.ndarray, doc_ids: np.ndarray):
        """Evaluate `query`'s scores at specific (shard, docid) hits — the
        rescore gather (reference behavior: QueryRescorer.java combines
        window scores)."""
        from ..query.nodes import mark_exact

        m = self.sp.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        # rescore windows combine raw scores arithmetically: exact BM25,
        # never the quantized impact tier
        mark_exact(node)
        S = self.sp.S
        views = [self.sp.shard_view(s) for s in range(S)]
        per_shard, keys = [], []
        for v in views:
            p, k_ = node.prepare(v)
            per_shard.append(p)
            keys.append(k_)
        params = _stack_shard_params(per_shard)
        cache_key = ("scores_at", tuple(keys), len(doc_ids), self._exec)
        fn = self._cache.get(cache_key)
        if fn is None:
            ctx = self.ctx
            n = self.sp.n_max

            def shard_body(dev1, par1):
                scores, match = node.device_eval(dev1, par1, ctx)
                return scores[:n], match[:n] & dev1["live"]

            from .spmd import constrain_shards, manual_shard_region

            region = manual_shard_region(
                shard_body, self.mesh, in_specs=(P("shards"), P("shards")))

            def inner(dev, params):
                return constrain_shards(region(dev, params), self.mesh)

            def run(dev, params, sh, di):
                scores, match = inner(dev, params)  # [S, n]
                s = scores[sh, di]
                ok = match[sh, di]
                return jnp.where(ok, s, 0.0), ok

            fn = jax.jit(run)
            self._cache[cache_key] = fn
        s, ok = jax.device_get(
            fn(self.dev, params, jnp.asarray(doc_shards), jnp.asarray(doc_ids))
        )
        return s, ok

    # -- block-max WAND ----------------------------------------------------

    def search_wand(self, node, size: int, from_: int,
                    floor: int = 0) -> StackedResult | None:
        """Two-pass block-max pruned disjunction search; None when the query
        shape doesn't qualify or pruning wouldn't reduce work. The returned
        total is a LOWER bound (total_relation == "gte").

        See query/wand.py for the plan and the soundness argument
        (reference: Lucene block-max WAND via
        search/query/QueryPhaseCollectorManager.java:416; SURVEY §7 hard
        part #2 — skipping becomes block filtering)."""
        out = self.search_wand_batch([dict(node=node, size=size,
                                           from_=from_, floor=floor)])
        return out[0]

    def search_wand_batch(self, requests: list[dict]) -> list:
        """Batched two-pass WAND: every request's pass-1 program launches
        before any θ is fetched, host pruning runs for the whole batch,
        then every pass-2 program launches before any result is fetched —
        two device round trips TOTAL for the batch instead of two per
        query. The plan overhead that round 3 measured as a net slowdown
        at single-query scale (BENCH_NOTES.md C2) amortizes exactly like
        the `_msearch` and agg batch paths. Entries that don't qualify
        (shape, floor, nothing pruned) come back as None; callers run
        those exhaustively (search_batch pipelines them the same way)."""
        states = [
            self._wand_plan(r["node"], r.get("size", 10),
                            r.get("from_", 0), r.get("floor", 0))
            for r in requests
        ]
        from ..telemetry import time_kernel

        live = [s for s in states if s is not None]
        if live:
            with time_kernel("sharded.wand_pass1", tier="wand",
                             requests=len(live)):
                host1 = jax.device_get([s["outs1"] for s in live])
            for s, h in zip(live, host1):
                s["host1"] = h
        wave2 = [s for s in live if self._wand_dispatch2(s)]
        if wave2:
            with time_kernel("sharded.wand_pass2", tier="wand",
                             requests=len(wave2)):
                host2 = jax.device_get([s["outs2"] for s in wave2])
            for s, h in zip(wave2, host2):
                s["host2"] = h
        return [
            self._wand_finalize(s) if s is not None and "host2" in s
            else None
            for s in states
        ]

    def search_pruned_batch(self, requests: list[dict]) -> list:
        """Gate-then-fallback pruned search, batched: block-max WAND for
        every request the profitability gate accepts, exhaustive execution
        for the rest — one batched wave each, so a request never costs
        more than its exhaustive execution plus the (amortized) gate
        check. Semantically this is `search(prune_floor=...)`'s
        gate+fallback decision applied to a whole batch; the engine's
        serving path still runs that decision per query (engine.py
        `search`), while bench.py times THIS batched form so a
        non-engaging batch measures as ~the exhaustive batch, never as a
        no-op (VERDICT r4 weak #2).

        Each request dict: node (QueryNode), size, from_, floor.
        Returns StackedResults; each carries `.wand_engaged`."""
        pruned = self.search_wand_batch(requests)
        fb_idx = [i for i, r in enumerate(pruned) if r is None]
        if fb_idx:
            fb = self.search_batch([
                dict(query=requests[i]["node"],
                     size=requests[i].get("size", 10),
                     from_=requests[i].get("from_", 0))
                for i in fb_idx
            ])
            for i, r in zip(fb_idx, fb):
                pruned[i] = r
        fb_set = set(fb_idx)
        for i, r in enumerate(pruned):
            r.wand_engaged = i not in fb_set
        return pruned

    def _wand_plan(self, node, size: int, from_: int,
                   floor: int = 0) -> dict | None:
        """Host planning + pass-1 launch (no fetch); None = not eligible."""
        from ..index.pack import BM25_K1, BM25_B
        from ..query import wand

        if (self.ctx.k1, self.ctx.b) != (BM25_K1, BM25_B):
            return None
        terms = wand.should_terms(node)
        if terms is None:
            return None
        if floor:
            # exact counting promised up to `floor` hits: prune only when
            # the true total provably reaches it. df counts postings at pack
            # build; docs deleted in place since (tiered refresh) may be
            # among them, so the proven bound is max df - dead docs.
            dead = getattr(self.sp, "dead_count", 0)
            if max(self.sp.eff_global_df.get((t.fld, t.term), 0)
                   for t in terms) - dead < floor:
                return None
        S = self.sp.S
        n = self.sp.n_max
        if n == 0:
            return None
        k = min(max(size + from_, 1), max(n * S, 1))
        views = [self.sp.shard_view(s) for s in range(S)]

        # ---- host planning: per-term/per-shard sorted block upper bounds.
        # All weight-free pieces (ubf order, window maxima) are cached on the
        # pack per (shard, term), so a repeated query's host planning is a
        # couple of dict hits + scalar scaling.
        PASS1_ROWS = 4  # blocks/term/shard scored to seed θ (512 postings)
        ubf_cache = getattr(self.sp, "_wand_ubf", None)
        if ubf_cache is None:
            ubf_cache = self.sp._wand_ubf = {}
        infos = []  # per term: dict(weight, dense_row, rows[s], ubs[s])
        csr_rows_total = 0
        for t in terms:
            params0, _key0 = t.prepare(views[0])  # sets t._dense; global weight
            weight = float(params0[1])
            avgdl = float(params0[2])
            if t._dense:
                infos.append({"dense": int(params0[0]), "weight": weight,
                              "avgdl": avgdl})
                continue
            rows_s, ubs_s, wub_s = [], [], []
            has_norms = t.fld in self.ctx.has_norms
            for s in range(S):
                p = self.sp.shards[s]
                nw = wand.windows_for(p.num_docs)
                ck = (s, t.fld, t.term, round(avgdl, 9), p.num_docs)
                got = ubf_cache.get(ck)
                if got is None:
                    start, count, _df = p.term_blocks(t.fld, t.term)
                    r, u = wand.term_row_ubf(
                        p, start, count, avgdl, has_norms,
                        self.ctx.k1, self.ctx.b,
                    )
                    wu = wand.window_ub_csr(p, r, u, p.num_docs, nw)
                    got = ubf_cache[ck] = (r, u, wu)
                r, u, wu = got
                rows_s.append(r)
                ubs_s.append(weight * u)
                wub_s.append(weight * wu)
                csr_rows_total += len(r)
            infos.append({"dense": None, "weight": weight, "avgdl": avgdl,
                          "rows": rows_s, "ubs": ubs_s, "win": wub_s})
        n_csr = sum(1 for i in infos if i["dense"] is None)
        min_rows = getattr(self, "wand_min_rows", None)
        if min_rows is None:
            # profitability gate (see wand_gate_min_rows): below it the
            # plan is provably net negative at identical results
            min_rows = wand_gate_min_rows()
        if n_csr == 0 or csr_rows_total < min_rows:
            return None  # too few blocks for pruning to pay for two launches

        # per-shard, per-term window-localized upper bounds: win_ub[s][ti] is
        # a [WINDOWS] array of the term's max block score per doc-id window
        # (rare terms bound ~0 over most of doc space — the locality that
        # makes block-max WAND prune; Lucene gets it from per-range maxes)
        dense_win = getattr(self.sp, "_dense_win_tfn", None)
        if dense_win is None:
            dense_win = self.sp._dense_win_tfn = {}
        win_ub = [[None] * len(infos) for _ in range(S)]
        for ti, info in enumerate(infos):
            for s in range(S):
                if info["dense"] is not None:
                    nd = self.sp.shards[s].num_docs
                    nw = wand.windows_for(nd)
                    dk = (s, info["dense"], round(info["avgdl"], 9), nd)
                    got = dense_win.get(dk)
                    if got is None:
                        got = wand.window_tfn_dense(
                            self.sp.dense_tfn_host(info["dense"], s,
                                                   info["avgdl"]), nd, nw)
                        dense_win[dk] = got
                    win_ub[s][ti] = info["weight"] * got
                else:
                    win_ub[s][ti] = info["win"][s]

        def synth(row_lists, inline_lists=None):
            """params + struct keys for the disjunction with each CSR term's
            block rows replaced by row_lists[t][s] (bucketed to a common
            width across shards), or — when inline_lists[t] is set — by
            synthetic posting arrays (docids, tfs, dls) per shard (the
            doc-level pruned form; TermNode 5-tuple params)."""
            per_shard_params, term_keys = [], []
            widths = {}
            for ti, info in enumerate(infos):
                if info["dense"] is not None:
                    continue
                if inline_lists is not None and inline_lists[ti] is not None:
                    widths[ti] = wand.bucket_width(max(
                        inline_lists[ti][s][0].shape[0] for s in range(S)))
                else:
                    widths[ti] = wand.bucket_width(max(
                        len(row_lists[ti][s]) for s in range(S)))
            for s in range(S):
                sp_params = []
                for ti, (t, info) in enumerate(zip(terms, infos)):
                    w = np.float32(info["weight"])
                    ad = np.float32(info["avgdl"])
                    if info["dense"] is not None:
                        sp_params.append((np.int32(info["dense"]), w, ad))
                        if s == 0:
                            term_keys.append(("term_dense", t.fld))
                    elif inline_lists is not None and inline_lists[ti] is not None:
                        d_, t_, l_ = inline_lists[ti][s]
                        wd = widths[ti]
                        nd = self.sp.shards[s].num_docs
                        pad = wd - d_.shape[0]
                        if pad:
                            d_ = np.concatenate(
                                [d_, np.full((pad, d_.shape[1]), nd, np.int32)])
                            t_ = np.concatenate(
                                [t_, np.zeros((pad, t_.shape[1]), np.float32)])
                            l_ = np.concatenate(
                                [l_, np.ones((pad, l_.shape[1]), np.float32)])
                        sp_params.append((d_, t_, l_, w, ad))
                        if s == 0:
                            term_keys.append(("term_inline", t.fld, wd))
                    else:
                        sp_params.append(
                            (wand.pad_rows_to(row_lists[ti][s], widths[ti]),
                             w, ad))
                        if s == 0:
                            term_keys.append(("term", t.fld, widths[ti]))
                per_shard_params.append(
                    ((), (), tuple(sp_params), ()))
            key = ("bool", ((), (), tuple(term_keys), ()), node._msm())
            params = _stack_shard_params(
                [(p, np.float32(node.boost)) for p in per_shard_params])
            return params, tuple(key for _ in range(S))

        # ---- pass 1: seed θ from each term's best blocks (launch only)
        p1_rows = [
            [i["rows"][s][: min(PASS1_ROWS, len(i["rows"][s]))] for s in range(S)]
            if i["dense"] is None else None
            for i in infos
        ]
        params1, keys1 = synth(p1_rows)
        fn1 = self._compiled(node, ("wand1", keys1), k, None, ())
        return {
            "node": node, "terms": terms, "infos": infos, "win_ub": win_ub,
            "synth": synth, "k": k, "size": size, "from_": from_,
            "outs1": fn1(self.dev, params1, {}),
        }

    def _wand_dispatch2(self, st) -> bool:
        """Host doc-level prune from θ + pass-2 launch; False when pruning
        bought nothing (caller falls back to the exhaustive plan)."""
        from ..query import wand

        node, terms, infos = st["node"], st["terms"], st["infos"]
        win_ub, k = st["win_ub"], st["k"]
        S = self.sp.S
        g_scores1, _gs1, _gd1, _tot1, _ = st["host1"]
        valid1 = np.isfinite(g_scores1)
        theta = float(g_scores1[k - 1]) if valid1.sum() >= k else -np.inf

        # doc-level pruning — drop every posting whose exact self score +
        # other-terms' window bound cannot reach θ, compact survivors into
        # synthetic blocks (query/wand.prune_postings)
        p2_inline = []
        kept = dropped = 0
        boost = float(node.boost)
        has_norms_of = {t.fld: t.fld in self.ctx.has_norms for t in terms}
        for ti, (t, info) in enumerate(zip(terms, infos)):
            if info["dense"] is not None:
                p2_inline.append(None)
                continue
            arrs_s = []
            for s in range(S):
                p = self.sp.shards[s]
                nd = p.num_docs
                nw = wand.windows_for(nd)
                # Σ of the OTHER terms' window bounds at each window
                other = np.sum(
                    [win_ub[s][tj] for tj in range(len(infos)) if tj != ti],
                    axis=0, dtype=np.float32)
                d_, t_, l_, kp, tot = wand.prune_postings(
                    p, nd, info["rows"][s], info["weight"] * boost,
                    info["avgdl"], has_norms_of[t.fld],
                    self.ctx.k1, self.ctx.b,
                    other * boost, theta, nw)
                arrs_s.append((d_, t_, l_))
                kept += kp
                dropped += tot - kp
            p2_inline.append(arrs_s)
        if dropped == 0:
            return False  # pruning bought nothing; use the exhaustive plan
        params2, keys2 = st["synth"](None, p2_inline)
        fn2 = self._compiled(node, ("wand2", keys2), k, None, ())
        st.update(theta=theta, kept=kept, dropped=dropped,
                  outs2=fn2(self.dev, params2, {}))
        return True

    def _wand_finalize(self, st) -> "StackedResult":
        g_scores, g_shard, g_doc, total, _ = st["host2"]
        size, from_ = st["size"], st["from_"]
        valid = np.isfinite(g_scores)
        max_score = float(g_scores[0]) if valid.any() else None
        end = max(size + from_, 0)
        out = StackedResult(
            g_shard[valid][from_:end].astype(np.int32),
            g_doc[valid][from_:end].astype(np.int32),
            g_scores[valid][from_:end].astype(np.float32),
            int(total),
            max_score,
            None,
        )
        out.total_relation = "gte"
        # kept/dropped count POSTINGS since the round-3 doc-level pruning
        # (block-level pruning cannot help mid-frequency disjunctions)
        out.wand_stats = {"rows_kept": st["kept"],
                          "rows_pruned": st["dropped"],
                          "theta": st["theta"]}
        return out

    def search(
        self,
        query: dict | QueryNode | None,
        size: int = 10,
        from_: int = 0,
        aggs: dict | None = None,
        mappings=None,
        prune_floor: int | None = None,
    ) -> StackedResult:
        """prune_floor: None = exact (no block-max pruning); 0 = prune freely
        (track_total_hits=false); N > 0 = prune only when the total provably
        reaches N (the track_total_hits threshold contract).

        Plain-DSL requests are served from the shard request cache when
        warm (whole-searcher scope: the merged result depends on every
        shard, so any shard's epoch bump invalidates it); QueryNode
        requests and per-request mapping overrides bypass the cache."""
        from ..cache import request_cache

        rc = request_cache()
        ck = scope = None
        if rc.enabled and mappings is None and not isinstance(query, QueryNode):
            ck = self._request_cache_key(query, size, from_, aggs, prune_floor)
            scope = self.cache_scope()
            hit = rc.get(scope[0], scope[1], ck)
            if hit is not None:
                from ..telemetry import CACHE_HIT_SPAN, TRACER, profile_event

                profile_event("cache", scope="stacked_search", hits=1,
                              misses=0)
                with TRACER.span(CACHE_HIT_SPAN):
                    return _copy_stacked_result(hit)
            from ..telemetry import profile_event

            profile_event("cache", scope="stacked_search", hits=0, misses=1)
        import time as _time

        from ..telemetry import metrics as _metrics

        _t0 = _time.perf_counter()
        res = self._search_uncached(query, size, from_, aggs, mappings,
                                    prune_floor)
        _elapsed_ms = (_time.perf_counter() - _t0) * 1000
        _metrics.histogram_record("es.shard.search.ms", _elapsed_ms)
        if ck is not None:
            rc.put(scope[0], scope[1], ck, _copy_stacked_result(res),
                   _stacked_result_nbytes(res), recompute_ms=_elapsed_ms)
        return res

    def _request_cache_key(self, query, size, from_, aggs, prune_floor):
        from ..cache import canonical_key

        return canonical_key({
            "op": "stacked_search", "query": query, "aggs": aggs,
            "size": int(size), "from": int(from_),
            "prune_floor": prune_floor,
            # query-time analyzers (synonym-set reloads) change parsed
            # queries without any index write — part of the identity
            "ag": getattr(self.sp.mappings, "analysis_generation", 0),
        })

    def _search_uncached(self, query, size, from_, aggs, mappings,
                         prune_floor) -> StackedResult:
        from ..query.wand import wand_enabled

        m = mappings if mappings is not None else self.sp.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        if prune_floor is not None and not aggs and wand_enabled():
            # experimental (ES_TPU_WAND=1): six measured rounds say the
            # batched exhaustive/impact kernels dominate the two-pass
            # pruned plan on this hardware — see query/wand.py
            res = self.search_wand(node, size, from_, floor=prune_floor)
            if res is not None:
                return res
        return self.search_batch(
            [dict(query=node, size=size, from_=from_, aggs=aggs, mappings=m)]
        )[0]

    # -- serving waves -----------------------------------------------------

    def search_many_begin(self, requests: list[dict]) -> dict:
        """Wave-shaped entry point for the serving front end: plan and
        DISPATCH every request's program without fetching anything, so a
        completer thread can pull the device outputs (`search_many_fetch`,
        engine-state-free) while the engine thread plans the next wave.

        Each request dict: query, size, from_, aggs, mappings,
        prune_floor — the `search()` keyword surface. Per-request results
        are byte-identical to solo `search()` calls: the cache lookup,
        WAND gate and per-request compiled program are the same code, and
        every request's program is independent of its wave-mates (the
        wave only shares the dispatch+fetch round trip, exactly like
        `search_batch`). A request that raises during planning carries
        its exception in the state and re-raises at finish."""
        import time as _time

        from ..cache import request_cache

        rc = request_cache()
        n = len(requests)
        st = {"t0": _time.perf_counter(), "requests": requests,
              "results": [None] * n, "states": [None] * n,
              "errors": [None] * n, "cache_slots": [None] * n}
        from ..telemetry import profile_event

        hits = misses = 0
        for i, r in enumerate(requests):
            query = r.get("query")
            size = r.get("size", 10)
            from_ = r.get("from_", 0)
            aggs = r.get("aggs")
            mappings = r.get("mappings")
            prune_floor = r.get("prune_floor")
            try:
                ck = scope = None
                if (rc.enabled and mappings is None
                        and not isinstance(query, QueryNode)):
                    ck = self._request_cache_key(query, size, from_, aggs,
                                                 prune_floor)
                    scope = self.cache_scope()
                    got = rc.get(scope[0], scope[1], ck)
                    if got is not None:
                        hits += 1
                        st["results"][i] = _copy_stacked_result(got)
                        continue
                    misses += 1
                m = mappings if mappings is not None else self.sp.mappings
                node = (query if isinstance(query, QueryNode)
                        else parse_query(query, m))
                if prune_floor is not None and not aggs:
                    from ..query.wand import wand_enabled

                    # experimental flag (ES_TPU_WAND): the two-pass WAND
                    # plan lost every measured round to the batched
                    # exhaustive/impact kernels (r05 sweep engaged
                    # nowhere; r08 verdict vs the impact tier) — off by
                    # default, the batched wave below is the production
                    # path for prune_floor requests
                    res = (self.search_wand(node, size, from_,
                                            floor=prune_floor)
                           if wand_enabled() else None)
                    if res is not None:
                        st["results"][i] = res
                        st["cache_slots"][i] = (ck, scope)
                        continue
                st["states"][i] = self._agg_dispatch(
                    query=node, size=size, from_=from_, aggs=aggs,
                    mappings=m)
                st["cache_slots"][i] = (ck, scope)
            except Exception as ex:  # noqa: BLE001 - per-request envelope
                st["errors"][i] = ex
        if hits or misses:
            profile_event("cache", scope="stacked_search", hits=hits,
                          misses=misses)
        st["pending"] = [s["outs"] for s in st["states"] if s is not None]
        return st

    def search_many_fetch(self, st: dict) -> None:
        """Pull the wave's device outputs. Touches NO engine/searcher host
        state — safe to run on a completer thread while the engine thread
        plans the next wave (the double-buffer stage of the serving
        pipeline)."""
        if not st["pending"]:
            st["host"] = []
            return
        from ..common import faults
        from ..telemetry import time_kernel

        faults.check("device.fetch", shards=self.sp.S,
                     requests=len(st["pending"]))
        with time_kernel("sharded.spmd_topk", shards=self.sp.S,
                         requests=len(st["pending"]),
                         queries=len(st["pending"]),
                         num_docs=self.sp.S * self.sp.n_max):
            st["host"] = jax.device_get(st["pending"])

    def search_many_finish(self, st: dict,
                           raise_errors: bool = True) -> list:
        """Finalize a fetched wave -> per-request StackedResults in
        request order (or the recorded exception object per slot when
        raise_errors=False). Two-pass terms aggs run their second wave
        here synchronously (rare). Runs on the engine thread: cache
        stores and host merges touch shared state."""
        import time as _time

        host = iter(st.get("host") or [])
        from ..cache import request_cache

        rc = request_cache()
        out = []
        wave2 = []
        for i, s in enumerate(st["states"]):
            if s is not None:
                s["host"] = next(host)
                if self._agg_pass2_dispatch(s):
                    wave2.append(s)
        if wave2:
            # rare two-pass terms aggs: one extra dispatch + fetch round,
            # recorded so the wave's host-transition meta stays honest
            host2 = jax.device_get([s["outs2"] for s in wave2])
            for s, h2 in zip(wave2, host2):
                s["host2"] = h2
            st["extra_dispatches"] = st.get("extra_dispatches", 0) + 1
            st["extra_fetches"] = st.get("extra_fetches", 0) + 1
        from ..telemetry import metrics as _metrics

        wave_ms = (_time.perf_counter() - st["t0"]) * 1000
        for i, s in enumerate(st["states"]):
            if st["errors"][i] is not None:
                if raise_errors:
                    raise st["errors"][i]
                out.append(st["errors"][i])
                continue
            res = st["results"][i] if s is None else self._agg_finalize(s)
            slot = st["cache_slots"][i]
            if s is not None or (slot is not None and st["results"][i]
                                 is not None):
                # computed this wave (dispatched or WAND): store like solo
                _metrics.histogram_record("es.shard.search.ms", wave_ms)
                if slot is not None and slot[0] is not None:
                    ck, scope = slot
                    rc.put(scope[0], scope[1], ck,
                           _copy_stacked_result(res),
                           _stacked_result_nbytes(res))
            out.append(res)
        return out

    def search_many(self, requests: list[dict],
                    raise_errors: bool = True) -> list:
        """Cache-aware batched execution of several `search()`-shaped
        requests: one dispatch wave, one device round trip, per-request
        results byte-identical to solo execution (see search_many_begin)."""
        st = self.search_many_begin(requests)
        self.search_many_fetch(st)
        return self.search_many_finish(st, raise_errors=raise_errors)

    def search_batch(self, requests: list[dict]) -> list:
        """Execute several search/agg requests with batched device
        round-trips: every request's program is dispatched before any
        result is fetched, so the fixed dispatch+fetch latency (the
        dominant cost of a single agg request through a remote runtime —
        BENCH_NOTES.md) is paid once per WAVE, not once per request.
        Two waves maximum: pass-1 for everything, then pass-2 for
        requests whose high-cardinality terms aggs use the two-pass
        candidate scheme. Each request dict: query (dict | QueryNode |
        None), size, from_, aggs, mappings.

        The reference has no agg-batching analog (each search is its own
        scatter/gather); this is the same discipline `ops/batched` applies
        to the query path, extended to aggregations."""
        from ..telemetry import time_kernel

        states = [self._agg_dispatch(**r) for r in requests]
        with time_kernel("sharded.spmd_topk", shards=self.sp.S,
                         requests=len(requests), queries=len(requests),
                         num_docs=self.sp.S * self.sp.n_max):
            host = jax.device_get([s["outs"] for s in states])
        wave2 = []
        for s, ho in zip(states, host):
            s["host"] = ho
            if self._agg_pass2_dispatch(s):
                wave2.append(s)
        if wave2:
            host2 = jax.device_get([s["outs2"] for s in wave2])
            for s, h2 in zip(wave2, host2):
                s["host2"] = h2
        return [self._agg_finalize(s) for s in states]

    def _agg_dispatch(self, query=None, size=10, from_=0, aggs=None,
                      mappings=None):
        """Plan + launch one request's pass-1 program (no device fetch)."""
        m = mappings if mappings is not None else self.sp.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        agg_nodes = None
        if aggs:
            from ..aggs import parse_aggs

            agg_nodes = parse_aggs(aggs, m)
        S = self.sp.S
        views = [self.sp.shard_view(s) for s in range(S)]
        per_shard = []
        keys = []
        for v in views:
            p, k_ = node.prepare(v)
            per_shard.append(p)
            keys.append(k_)
        params = _stack_shard_params(per_shard)
        agg_params, agg_key = {}, ()
        if agg_nodes:
            per_shard_aggs = []
            akeys = []
            for v in views:
                parts = {nme: a.prepare(v, m) for nme, a in agg_nodes.items()}
                per_shard_aggs.append({nme: p for nme, (p, _) in parts.items()})
                akeys.append(tuple((nme, kk) for nme, (_, kk) in sorted(parts.items())))
            agg_params = _stack_shard_params(per_shard_aggs)
            agg_key = tuple(akeys)
        k = min(max(size + from_, 1), max(self.sp.n_max * self.sp.S, 1))
        fn = self._compiled(node, tuple(keys), k, agg_nodes, agg_key)
        from ..monitoring.xla_introspect import check_dispatch

        check_dispatch("sharded.spmd_topk", fn,
                       (self.dev, params, agg_params),
                       fields={"queries": 1, "k": k,
                               "num_docs": self.sp.S * self.sp.n_max})
        return {
            "node": node, "keys": tuple(keys), "k": k, "size": size,
            "from_": from_, "agg_nodes": agg_nodes, "agg_key": agg_key,
            "params": params, "agg_params": agg_params,
            "outs": fn(self.dev, params, agg_params),
        }

    def _agg_pass2_dispatch(self, s) -> bool:
        """Launch pass 2 (two-pass terms candidates) if the request needs
        it; candidate selection uses the GLOBAL merged counts (exact —
        unlike the reference's per-shard shard_size approximation)."""
        agg_nodes = s["agg_nodes"]
        if not agg_nodes:
            return False
        from ..aggs import two_pass_plan

        tp = two_pass_plan(agg_nodes)
        if not tp:
            return False
        _s1, _s2, _s3, _t, agg_out = s["host"]
        merged = {name: anode.merge_partials(agg_out[name])
                  for name, anode in agg_nodes.items()}
        s["merged"] = merged
        s["tp"] = tp
        S = self.sp.S
        agg_params = s["agg_params"]
        for name, a in tp.items():
            cm = a.select_candidates(merged[name])
            agg_params[name] = {
                **agg_params[name],
                "cand": np.broadcast_to(cm, (S, len(cm))).copy(),
            }
        fn2 = self._compiled(
            s["node"], s["keys"], s["k"], agg_nodes,
            (s["agg_key"], "tp2",
             tuple(sorted((n, a._C) for n, a in tp.items()))))
        s["outs2"] = fn2(self.dev, s["params"], agg_params)
        return True

    def _agg_finalize(self, s) -> StackedResult:
        g_scores, g_shard, g_doc, total, agg_out = s["host"]
        agg_nodes = s["agg_nodes"]
        aggregations = None
        if agg_nodes:
            merged = s.get("merged") or {
                name: anode.merge_partials(agg_out[name])
                for name, anode in agg_nodes.items()
            }
            if "host2" in s:
                _s1, _s2, _s3, _t, agg_out2 = s["host2"]
                for name, a in s["tp"].items():
                    merged[name].update(a.merge_partials(agg_out2[name]))
            aggregations = {
                name: anode.finalize(merged[name], 1)[0]
                for name, anode in agg_nodes.items()
            }
        size, from_ = s["size"], s["from_"]
        valid = np.isfinite(g_scores)
        max_score = float(g_scores[0]) if valid.any() else None
        end = max(size + from_, 0)
        return StackedResult(
            g_shard[valid][from_:end].astype(np.int32),
            g_doc[valid][from_:end].astype(np.int32),
            g_scores[valid][from_:end].astype(np.float32),
            int(total),
            max_score,
            aggregations,
        )

    def count(self, query=None) -> int:
        return self.search(query, size=1).total

    # -- field-sorted search ----------------------------------------------

    def _compiled_sorted(self, node, key_t, k, plan, has_after, agg_nodes, agg_key):
        cache_key = ("sorted", key_t, k, plan.struct_key(), has_after, agg_key, self._exec)
        fn = self._cache.get(cache_key)
        if fn is not None:
            return fn
        ctx = self.ctx
        n = self.sp.n_max
        k_local = min(k, max(n, 1))

        def shard_body(dev1, par1, after, agg_par1):
            scores, match = node.device_eval(dev1, par1, ctx)
            ok = match[:n] & dev1["live"]
            total = jnp.sum(ok, dtype=jnp.int32)
            agg_out = {}
            if agg_nodes:
                seg = jnp.where(ok, 0, 1).astype(jnp.int32)
                dev_a = {**dev1, "_query_scores": scores[:n]}
                for name, anode in agg_nodes.items():
                    agg_out[name] = anode.device_eval_segmented(
                        dev_a, agg_par1[name], seg, 1, ok, ctx
                    )
            keys = plan.device_keys(dev1, scores, n)
            sel = ok
            if has_after:
                gt = jnp.zeros(n, bool)
                eq = jnp.ones(n, bool)
                for kk, aa in zip(keys, after):
                    gt = gt | (eq & (kk > aa))
                    eq = eq & (kk == aa)
                sel = sel & gt
            invalid = (~sel).astype(jnp.int32)
            docs = jnp.arange(n, dtype=jnp.int32)
            sorted_ops = jax.lax.sort((invalid, *keys, docs), num_keys=1 + len(keys))
            return (
                sorted_ops[0][:k_local],
                tuple(o[:k_local] for o in sorted_ops[1:-1]),
                sorted_ops[-1][:k_local],
                total,
                agg_out,
            )

        from .spmd import constrain_shards, manual_shard_region

        region = manual_shard_region(
            shard_body, self.mesh,
            in_specs=(P("shards"), P("shards"), P(), P("shards")))

        def run(dev, params, after, agg_params):
            return constrain_shards(region(dev, params, after, agg_params),
                                    self.mesh)

        fn = jax.jit(run)
        self._cache[cache_key] = fn
        return fn

    def search_sorted(
        self,
        query,
        sort_fields,
        size: int = 10,
        from_: int = 0,
        search_after=None,
        aggs: dict | None = None,
        mappings=None,
    ):
        """-> (hits: [(shard, docid, sort_values)], total, aggregations)."""
        from ..query.sort import SortPlan

        m = mappings if mappings is not None else self.sp.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        agg_nodes = None
        if aggs:
            from ..aggs import parse_aggs

            agg_nodes = parse_aggs(aggs, m)
        S = self.sp.S
        views = [self.sp.shard_view(s) for s in range(S)]
        # one plan per shard view (global dv dictionaries -> identical keys)
        plan = SortPlan(sort_fields, views[0], m)
        per_shard, keys_t = [], []
        for v in views:
            p, k_ = node.prepare(v)
            per_shard.append(p)
            keys_t.append(k_)
        params = _stack_shard_params(per_shard)
        agg_params, agg_key = {}, ()
        if agg_nodes:
            from ..aggs import two_pass_plan

            per_shard_aggs, akeys = [], []
            for attempt in (0, 1):
                per_shard_aggs, akeys = [], []
                for v in views:
                    parts = {nm: a.prepare(v, m) for nm, a in agg_nodes.items()}
                    per_shard_aggs.append({nm: p for nm, (p, _) in parts.items()})
                    akeys.append(tuple((nm, kk) for nm, (_, kk) in sorted(parts.items())))
                tp = two_pass_plan(agg_nodes)
                if not tp:
                    break
                # field-sorted execution can't orchestrate two passes: fall
                # back to single-pass (one-pass budgets apply as before)
                for a in tp.values():
                    a.force_single_pass = True
            agg_params = _stack_shard_params(per_shard_aggs)
            agg_key = tuple(akeys)
        k = min(max(size + from_, 1), max(self.sp.n_max, 1))
        after = ()
        if search_after is not None:
            after = plan.after_keys(search_after, self.sp)
        fn = self._compiled_sorted(
            node, tuple(keys_t), k, plan, search_after is not None, agg_nodes, agg_key
        )
        inv, keys_s, docs, totals, agg_out = jax.device_get(
            fn(self.dev, params, after, agg_params)
        )
        aggregations = None
        if agg_nodes:
            aggregations = {
                name: anode.finalize(anode.merge_partials(agg_out[name]), 1)[0]
                for name, anode in agg_nodes.items()
            }
        # host-side coordinator merge: lexsort by (keys..., shard) over the
        # S*k_local candidates, skipping invalid slots
        S_, kl = inv.shape
        flat_inv = inv.reshape(-1)
        shard_of = np.repeat(np.arange(S_, dtype=np.int32), kl)
        flat_docs = docs.reshape(-1)
        flat_keys = [np.asarray(kk).reshape(-1) for kk in keys_s]
        order = np.lexsort(tuple([shard_of] + flat_keys[::-1] + [flat_inv]))
        valid = flat_inv[order] == 0
        order = order[valid]
        take = order[from_ : size + from_]
        # per-position values in original space
        key_cols = [fk[take] for fk in flat_keys]
        values = plan.hit_values(key_cols, list(range(len(take))))
        hits = [
            (int(shard_of[i]), int(flat_docs[i]), v)
            for i, v in zip(take, values)
        ]
        return hits, int(totals.sum()), aggregations


def msearch_sharded(ss: "StackedSearcher", fld: str,
                    queries: list, k: int = 10, _return_program=False):
    """Batched multi-query term-disjunction `_msearch` over the shard mesh.

    The production C5 shape: per-shard batch plans (one BatchPlan per shard,
    stacked to [S, ...]) run the batched disjunction kernel inside shard_map,
    and the coordinator merge applies the reference's
    (score desc, shard asc, doc asc) order (reference behavior:
    action/search/TransportMultiSearchAction.java fan-out +
    SearchPhaseController.java:232 TopDocs.merge). On one chip the same body
    runs under vmap; on a mesh the gather of the [S, Q, k] partials rides
    ICI collectives.

    With the fused kernel eligible (dense tier present, k <= 16,
    ES_TPU_FUSED on TPU or forced), each shard runs the fused tiled
    pipeline (ops/fused._fused_pipeline — in-kernel dense matmul +
    per-tile top-t + canonical f32 rescore) instead of the legacy
    disjunction kernel. Under the pjit execution model (PR 11) the
    pipeline runs inside an embedded shard_map manual region of the ONE
    compiled SPMD program that also merges on-device; the shard_map
    partials + host-merge form survives only as the legacy-model /
    test-oracle route. Queries flagged by any shard re-run on the exact
    arm either way, so results never depend on the fused pass.

    The shard request cache fronts the routes at the storage granularity
    matching each execution model: pjit searchers key at WAVE scope and
    store post-merge per-query rows (so the one-program route stays
    engaged when warm); legacy models keep per-SHARD entries — each
    (query, shard) pair's pre-merge top-k row cached under
    (shard token, shard epoch, canonical query key), so a partially-warm
    msearch only re-scores queries with at least one cold shard, reuses
    warm shards' cached rows at the coordinator merge, and a single
    shard's epoch bump (in-place mutation) leaves the other shards warm.

    -> (scores [Q, k], shard [Q, k], docid [Q, k], totals [Q]) numpy.
    """
    if _return_program or not queries:
        return _msearch_sharded_exact(ss, fld, queries, k, _return_program)
    from ..cache import request_cache

    rc = request_cache()
    if rc.enabled:
        return _msearch_sharded_cached(ss, rc, fld, queries, k)
    # pjit (the resolved default, incl. single-query meshes): ONE
    # compiled SPMD program — fused Pallas arm (embedded shard_map
    # region) > impact > exact, each including the on-device all-gather
    # + top-k merge. Byte-identical rows to the partials + host-merge
    # oracle below (tests/test_spmd.py). No per-tier env fork: the arm
    # is chosen by pack shape alone, the execution model by the
    # searcher's RESOLVED mode (so a later env flip cannot split a
    # searcher across execution models).
    if getattr(ss, "_exec", "vmap") == "pjit":
        return _msearch_merged(ss, fld, queries, k)
    # legacy execution models (shard_map test oracle / off-mesh vmap):
    # per-shard partials + host coordinator merge, fused > impact >
    # exact — the SAME arm priority as the merged route
    return _merge_shard_rows(*_msearch_sharded_partials(ss, fld, queries, k))


def msearch_wave(ss: "StackedSearcher", fld: str, queries: list,
                 k: int = 10):
    """Serving-wave msearch: pad the coalesced term-disjunction batch to
    the compiled power-of-two batch tier (pad queries are empty — they
    plan to zero weights and score nothing) so steady-state traffic
    reuses a small executable family instead of compiling one program per
    wave size, then strip the pad rows off.

    -> ((scores [Q,k], shard [Q,k], doc [Q,k], totals [Q]), tier) — tier
    is the padded batch width, so tier/Q is the wave's device occupancy.
    Each real query's row is byte-identical to a solo 1-query wave: rows
    are computed independently per query and pad lanes contribute exact
    zeros (the serving parity contract, tests/test_serving.py)."""
    st = msearch_wave_begin(ss, fld, queries, k)
    msearch_wave_fetch(st)
    return msearch_wave_finish(st)


def msearch_wave_begin(ss: "StackedSearcher", fld: str, queries: list,
                       k: int = 10) -> dict:
    """Wave-deferred term lane (PR 11): pad to the compiled batch tier,
    consult the request cache, and DISPATCH the cold subset's ONE merged
    SPMD program without fetching anything — the serving wave's single
    fetch stage (`engine.search_wave_fetch`) pulls this lane together
    with every other lane in one host round-trip, so the term lane no
    longer blocks the scheduler thread inside `search_wave_begin`.

    The deferred merged route serves both the pjit mesh AND the off-mesh
    vmap model (a single-device merge is still one program with a k-row
    fetch); only the shard_map oracle resolves synchronously here — it
    is a test fixture, not a serving model."""
    from ..ops.batched import BatchTermSearcher

    Q = len(queries)
    tier = BatchTermSearcher.wave_q_tier(Q)
    padded = list(queries) + [[] for _ in range(tier - Q)]
    st = {"Q": Q, "tier": tier}
    if getattr(ss, "_exec", "vmap") == "shardmap":
        st["result"] = msearch_sharded(ss, fld, padded, k)
        return st
    st.update(_merged_cached_begin(ss, fld, padded, k))
    return st


def msearch_wave_fetch(st: dict) -> None:
    """Pull the wave's pending merged-program outputs (no-op when the
    lane resolved in begin or the engine's combined wave fetch already
    delivered them)."""
    m = st.get("merged")
    if m is not None:
        _msearch_merged_fetch(m)


def msearch_wave_finish(st: dict):
    """-> ((scores [Q,k], shard, doc, totals [Q]), tier); stores cold
    rows into the request cache (engine thread)."""
    if "result" in st:
        v, s, d, t = st["result"]
    else:
        v, s, d, t = _merged_cached_finish(st)
    Q = st["Q"]
    return (v[:Q], s[:Q], d[:Q], t[:Q]), st["tier"]


def _merge_shard_rows(v, i, t):
    """Coordinator merge of per-shard top rows [S, Q, kk]: flat order is
    (score desc, shard asc, doc asc) — the reference's
    SearchPhaseController/TopDocs.merge order. -> (scores [Q, kk],
    shard [Q, kk], docid [Q, kk], totals [Q])."""
    v, i, t = np.asarray(v), np.asarray(i), np.asarray(t)
    S, Q, kk = v.shape
    flat_v = v.transpose(1, 0, 2).reshape(Q, -1)
    flat_i = i.transpose(1, 0, 2).reshape(Q, -1)
    flat_s = np.broadcast_to(
        np.repeat(np.arange(S), kk)[None, :], flat_v.shape
    )
    order = np.lexsort((flat_i, flat_s, -flat_v), axis=1)[:, :kk]
    return (
        np.take_along_axis(flat_v, order, axis=1),
        np.take_along_axis(flat_s, order, axis=1).astype(np.int32),
        np.take_along_axis(flat_i, order, axis=1),
        t.sum(axis=0),
    )


def _impact_sharded_usable(ss: "StackedSearcher") -> bool:
    """The sharded impact arm serves: routing on (ES_TPU_IMPACT), the
    stacked code blocks derived for the CURRENT effective stats, and
    resident on device."""
    from ..ops.scoring import impact_enabled

    return (impact_enabled() and ss.sp.impact_serving()
            and "impact_codes" in ss.dev)


def impact_arm_usable(ss: "StackedSearcher") -> bool:
    """Public arm-routing probe: would msearch route this searcher to the
    impact tier? Superpack eligibility (`tenancy/`) must exclude such
    searchers — members are scored by the exact tenant-gather kernel, and
    parity is against whatever arm per-index dispatch would pick."""
    return _impact_sharded_usable(ss)


def plan_adapter(ss: "StackedSearcher", s: int) -> "_PlanShardAdapter":
    """Public host-planning adapter for one shard of a stacked searcher:
    a BatchTermSearcher over it produces the EXACT per-index plan
    (weights from effective global stats, shard-local block rows) —
    shared by the merged-msearch arm and the superpack tenant-gather
    planner so their plans can never drift apart."""
    return _PlanShardAdapter(ss.sp, s, ss)


def _msearch_sharded_partials(ss: "StackedSearcher", fld: str,
                              queries: list, k: int):
    """Per-shard pre-merge rows (v [S, Q, kk], i [S, Q, kk], t [S, Q])
    from whichever arm serves this searcher: the fused pipeline (with
    per-shard escalation), the impact-tier gather+sum, or the legacy
    exact kernel."""
    from ..planner import execution_planner

    fs = _fused_sharded_for(ss)
    fused_ok = fs is not None and fs.usable(k)
    S, Q, n_max = ss.sp.S, len(queries), ss.sp.n_max
    cands = []
    if fused_ok:
        cands.append(("fused", "sharded.fused_pipeline",
                      {"shards": S, "queries": Q, "k": k,
                       "v": ss.sp.dense_v, "num_docs": S * fs.n_pad}))
    if _impact_sharded_usable(ss):
        cands.append(("impact", "sharded.impact_disjunction",
                      {"shards": S, "queries": Q, "k": k,
                       "num_docs": S * n_max}))
    cands.append(("exact", "sharded.exact_disjunction",
                  {"tier": "exact", "shards": S, "queries": Q, "k": k,
                   "num_docs": S * n_max}))
    arm = execution_planner().choose_arm("sharded.msearch_partials", cands)
    if arm == "fused":
        return fs.msearch_partials(fld, queries, k)
    if arm == "impact":
        out = _msearch_impact_partials(ss, fld, queries, k)
        if out is not None:
            return out
    return _msearch_exact_partials(ss, fld, queries, k)


def _merged_cached_begin(ss: "StackedSearcher", fld: str, queries: list,
                         k: int) -> dict:
    """Wave-scope cache front for the merged pjit route (PR 11
    satellite): post-merge per-query rows are the storage unit, keyed
    under the WHOLE-SEARCHER scope (`cache_scope`: every shard's epoch),
    so a warm cache serves merged rows directly and the cold subset
    rides the ONE-program route — previously an enabled cache silently
    forced every pjit msearch onto the slower partials + host-merge
    path, whose per-shard rows were the only storage unit. Dispatches
    the cold subset WITHOUT fetching; `_merged_cached_finish` assembles
    and stores. With the cache disabled this degrades to cold=everything
    and no stores."""
    from ..cache import canonical_key, request_cache

    rc = request_cache()
    st = {"ss": ss, "fld": fld, "k": k, "queries": queries,
          "rows": {}, "cold": list(range(len(queries))),
          "qkeys": None, "scope": None, "merged": None}
    if rc.enabled:
        qkeys = [
            canonical_key({"op": "msearch_merged", "fld": fld, "k": int(k),
                           "q": [[t, float(b)] for t, b in q]})
            for q in queries
        ]
        tok, ep = ss.cache_scope()
        cold = []
        for qi, ck in enumerate(qkeys):
            got = rc.get(tok, ep, ck)
            if got is None:
                cold.append(qi)
            else:
                st["rows"][qi] = got
        from ..telemetry import profile_event

        profile_event("cache", scope="msearch_merged",
                      hits=len(queries) - len(cold), misses=len(cold))
        st.update(cold=cold, qkeys=qkeys, scope=(tok, ep))
    if st["cold"]:
        st["merged"] = _msearch_merged_begin(
            ss, fld, [queries[qi] for qi in st["cold"]], k)
    return st


def _merged_cached_finish(st: dict):
    """Assemble warm + freshly merged rows -> (v [Q, kk], shard, doc,
    totals [Q]); stores cold rows under the wave-scope keys."""
    from ..cache import request_cache

    rows, cold = st["rows"], st["cold"]
    if st["merged"] is not None:
        cv, csh, ci, ct = _msearch_merged_finish(st["merged"])
        rc = request_cache()
        recompute_ms = None
        if st["qkeys"] is not None and rc.enabled and cold:
            # PR 18: admission hint — the planner's predicted wall for
            # re-running this merged wave, amortized per cold row (None
            # while the kernel EMA is cold: admit, today's behavior)
            from ..planner import execution_planner

            ss = st["ss"]
            total = execution_planner().predict_ms(
                "sharded.allgather_topk",
                {"tier": "exact", "shards": ss.sp.S, "queries": len(cold),
                 "k": st["k"], "num_docs": ss.sp.S * ss.sp.n_max})
            if total is not None:
                recompute_ms = total / len(cold)
        for j, qi in enumerate(cold):
            row = (cv[j].copy(), csh[j].copy(), ci[j].copy(), int(ct[j]))
            rows[qi] = row
            if st["qkeys"] is not None and rc.enabled:
                tok, ep = st["scope"]
                rc.put(tok, ep, st["qkeys"][qi], row,
                       row[0].nbytes + row[1].nbytes + row[2].nbytes + 96,
                       recompute_ms=recompute_ms)
    Q = len(st["queries"])
    width = max((r[0].shape[0] for r in rows.values()), default=st["k"])
    V = np.full((Q, width), -np.inf, np.float32)
    SH = np.zeros((Q, width), np.int32)
    I = np.zeros((Q, width), np.int64)
    T = np.zeros((Q,), np.int64)
    for qi, (rv, rs, ri, rt) in rows.items():
        V[qi, : rv.shape[0]] = rv
        SH[qi, : rs.shape[0]] = rs
        I[qi, : ri.shape[0]] = ri
        T[qi] = rt
    return V, SH, I, T


def _msearch_sharded_cached(ss: "StackedSearcher", rc, fld: str,
                            queries: list, k: int):
    """Cached msearch. pjit searchers key at WAVE scope and store
    post-merge rows so the one-program route stays engaged
    (_merged_cached_begin); legacy execution models keep the per-shard
    storage unit: warm (query, shard) rows come from the cache, queries
    with any cold shard re-score (one batched SPMD dispatch over the
    cold subset — the device program always runs all shards, but warm
    shards' CACHED rows stay authoritative for the merge and warm
    entries are never re-stored), then one coordinator merge."""
    if getattr(ss, "_exec", "vmap") == "pjit":
        st = _merged_cached_begin(ss, fld, queries, k)
        if st["merged"] is not None:
            from ..telemetry import host_transition

            host_transition("dispatch")
            _msearch_merged_fetch(st["merged"])
        return _merged_cached_finish(st)
    from ..cache import canonical_key

    S = ss.sp.S
    qkeys = [
        canonical_key({"op": "msearch_sharded", "fld": fld, "k": int(k),
                       "q": [[t, float(b)] for t, b in q]})
        for q in queries
    ]
    rows: dict[tuple, tuple] = {}
    cold: list[int] = []
    for qi, ck in enumerate(qkeys):
        warm = True
        for s in range(S):
            tok, ep = ss.shard_cache_scope(s)
            got = rc.get(tok, ep, ck)
            if got is None:
                warm = False
            else:
                rows[(qi, s)] = got
        if not warm:
            cold.append(qi)
    from ..telemetry import profile_event

    for s in range(S):
        hits = sum(1 for qi in range(len(queries)) if (qi, s) in rows)
        profile_event("cache", scope="msearch_sharded", shard=s,
                      hits=hits, misses=len(queries) - hits)
    if cold:
        v, i, t = _msearch_sharded_partials(
            ss, fld, [queries[qi] for qi in cold], k)
        v, i, t = np.asarray(v), np.asarray(i), np.asarray(t)
        for j, qi in enumerate(cold):
            for s in range(S):
                if (qi, s) in rows:
                    continue  # warm per-shard entry stays authoritative
                row = (v[s, j].copy(), i[s, j].copy(), int(t[s, j]))
                rows[(qi, s)] = row
                tok, ep = ss.shard_cache_scope(s)
                rc.put(tok, ep, qkeys[qi], row,
                       row[0].nbytes + row[1].nbytes + 96)
    Q = len(queries)
    width = max(r[0].shape[0] for r in rows.values())
    V = np.full((S, Q, width), -np.inf, np.float32)
    I = np.zeros((S, Q, width), np.int64)
    T = np.zeros((S, Q), np.int64)
    for (qi, s), (rv, ri, rt) in rows.items():
        V[s, qi, : rv.shape[0]] = rv
        I[s, qi, : ri.shape[0]] = ri
        T[s, qi] = rt
    return _merge_shard_rows(V, I, T)


def _msearch_stack_plans(ss: "StackedSearcher", fld: str, queries: list,
                         k: int, *, impact: bool = False) -> dict | None:
    """Shared host planning of the stacked msearch arms: one
    BatchTermSearcher plan per shard, padded in place to the common
    (Ts, B) shape (row 0 = padding). -> dict of stacked [S, ...] plan
    arrays + scoring context; None when impact=True and any shard's plan
    cannot ride the impact tier."""
    from ..ops.batched import BatchTermSearcher

    sp = ss.sp
    S = sp.S
    adapters = [_PlanShardAdapter(sp, s, ss) for s in range(S)]
    plans = [BatchTermSearcher(a).plan(fld, queries, k) for a in adapters]
    if impact and any(p.impact_w is None for p in plans):
        return None
    ts_max = max(p.sparse_rows.shape[1] for p in plans)
    b_max = max(p.sparse_rows.shape[2] for p in plans)
    attrs = ("sparse_weights", "impact_w") if impact else ("sparse_weights",)
    for s in range(S):
        sr = plans[s].sparse_rows
        plans[s].sparse_rows = np.pad(
            sr, ((0, 0), (0, ts_max - sr.shape[1]), (0, b_max - sr.shape[2]))
        )
        for attr in attrs:
            a = getattr(plans[s], attr)
            setattr(plans[s], attr,
                    np.pad(a, ((0, 0), (0, ts_max - a.shape[1]))))
    out = {
        "W": np.stack([p.W for p in plans]),  # [S, Q, V]
        "rows": np.stack([p.sparse_rows for p in plans]),
        "ws": np.stack([p.sparse_weights for p in plans]),
        # effective (override-aware) stats with the empty-field 1.0 guard —
        # raw field_stats would diverge from the tier under tiered refresh
        "avgdl": adapters[0].pack.avgdl(fld),
        "has_norms": fld in ss.ctx.has_norms,
        "kk": min(max(k, 1), max(sp.n_max, 1)),
    }
    if impact:
        out["iws"] = np.stack([p.impact_w for p in plans])
    return out


def _msearch_impact_partials(ss: "StackedSearcher", fld: str,
                             queries: list, k: int = 10):
    """The sharded impact arm (BM25S): the same SPMD shard body as the
    exact arm, but the sparse tail is a gather+sum over the stacked
    quantized impact code blocks (batch_term_disjunction's impact_w
    mode) — no tf/dl gathers, no BM25 arithmetic, ~half the postings
    bytes per query. Returns None when any shard's plan cannot ride the
    tier (caller falls back to the exact arm)."""
    from ..ops.batched import batch_term_disjunction

    sp = ss.sp
    S = sp.S
    pl = _msearch_stack_plans(ss, fld, queries, k, impact=True)
    if pl is None:
        return None
    Q = len(queries)
    W, rows, ws, iws = pl["W"], pl["rows"], pl["ws"], pl["iws"]
    avgdl, has_norms, kk = pl["avgdl"], pl["has_norms"], pl["kk"]
    n_max = sp.n_max
    Ts, B = rows.shape[2], rows.shape[3]

    def shard_body(dev1, W1, rows1, ws1, iws1):
        dev = {
            "post_docids": dev1["post_docids"][0],
            "impact_codes": dev1["impact_codes"][0],
            "live": dev1["live"][0],
        }
        if "dense_tfn" in dev1:
            dev["dense_tfn"] = dev1["dense_tfn"][0]
        v, i, t = batch_term_disjunction(
            dev, (Ts, B, kk), W1[0], rows1[0], ws1[0],
            avgdl=avgdl, num_docs=n_max, has_norms=has_norms,
            impact_w=iws1[0],
        )
        return v[None], i[None], t[None]

    sub = {key: ss.dev[key] for key in
           ("post_docids", "impact_codes", "live")}
    if "dense_tfn" in ss.dev:
        sub["dense_tfn"] = ss.dev["dense_tfn"]
    cache_key = ("msearch_impact", fld, Ts, B, kk, Q)
    fn = ss._cache.get(cache_key)
    if fn is None:
        if ss.mesh is not None:
            def run(dev, W_, rows_, ws_, iws_):
                specs = jax.tree_util.tree_map(lambda _: P("shards"), dev)
                return shard_map(
                    shard_body, mesh=ss.mesh,
                    in_specs=(specs,) + (P("shards"),) * 4,
                    out_specs=(P("shards"), P("shards"), P("shards")),
                )(dev, W_, rows_, ws_, iws_)
        else:
            def run(dev, W_, rows_, ws_, iws_):
                def body(d1, w1, r1, s1, i1):
                    return shard_body(
                        jax.tree_util.tree_map(lambda x: x[None], d1),
                        w1[None], r1[None], s1[None], i1[None],
                    )
                v, i, t = jax.vmap(body)(dev, W_, rows_, ws_, iws_)
                return v[:, 0], i[:, 0], t[:, 0]
        fn = ss._cache[cache_key] = jax.jit(run)
    from ..telemetry import profile_event, time_kernel

    code_bytes = int(np.dtype(ss.dev["impact_codes"].dtype).itemsize)
    profile_event("tier", tier="impact", queries=Q)
    fields = dict(tier="impact", shards=S, queries=Q, k=kk,
                  num_docs=S * n_max, rows=int(np.prod(rows.shape)),
                  code_bytes=code_bytes)
    prog_args = (sub, jnp.asarray(W), jnp.asarray(rows), jnp.asarray(ws),
                 jnp.asarray(iws))
    from ..monitoring.xla_introspect import check_dispatch

    check_dispatch("sharded.impact_disjunction", fn, prog_args,
                   fields=fields)
    with time_kernel("sharded.impact_disjunction", **fields):
        v, i, t = jax.device_get(fn(*prog_args))
    return v, i, t


def _msearch_sharded_exact(ss: "StackedSearcher", fld: str,
                           queries: list, k: int = 10,
                           _return_program=False):
    """The legacy exact arm: per-shard partials + coordinator merge."""
    out = _msearch_exact_partials(ss, fld, queries, k, _return_program)
    if _return_program:
        return out
    return _merge_shard_rows(*out)


def _msearch_merged(ss: "StackedSearcher", fld: str, queries: list, k: int,
                    _return_program=False):
    """The one-program msearch route: dispatch + fetch + finish in one
    call (solo callers; the serving wave drives the stages separately
    through `msearch_wave_begin/fetch/finish`)."""
    st = _msearch_merged_begin(ss, fld, queries, k,
                               _return_program=_return_program)
    if _return_program:
        return st
    from ..telemetry import host_transition

    host_transition("dispatch")
    _msearch_merged_fetch(st)
    return _msearch_merged_finish(st)


def _msearch_merged_begin(ss: "StackedSearcher", fld: str, queries: list,
                          k: int, _return_program=False):
    """Plan + DISPATCH the pjit msearch arm (PR 10, reworked PR 11): ONE
    compiled SPMD program per plan shape — per-shard scoring bodies over
    the sharded pack pytree AND the global top-k merge (`lax.top_k` over
    the ICI all-gather of the per-shard (score, shard_doc) rows) in the
    same program. No host round-trip between shard scan and coordinator
    merge; device->host traffic is k rows per query instead of S*k.
    Arm priority matches the partials oracle: fused > impact > exact —
    the fused Pallas pipeline rides an embedded shard_map manual region
    inside the SAME compiled program (PR 11: the `ES_TPU_SPMD` arm
    matrix for the fused tier is gone).

    -> a state dict for `_msearch_merged_fetch` / `_msearch_merged_finish`
    (or the (fn, args, kk) program triple under _return_program)."""
    arm = "exact"
    if not _return_program:
        # PR 18: the one-program route's arms (same eligibility gates)
        # arbitrated by the execution planner; cold = today's static
        # priority, warm = argmin of the predicted walls
        from ..planner import execution_planner

        fs = _fused_sharded_for(ss)
        fused_ok = fs is not None and fs.usable(k)
        impact_ok = _impact_sharded_usable(ss)
        S, Q, n_max = ss.sp.S, len(queries), ss.sp.n_max
        cands = []
        if fused_ok:
            cands.append(("fused", "sharded.fused_allgather_topk",
                          {"shards": S, "queries": Q, "k": k,
                           "v": ss.sp.dense_v,
                           "num_docs": S * fs.n_pad}))
        if impact_ok:
            code_b = (int(np.dtype(ss.dev["impact_codes"].dtype).itemsize)
                      if "impact_codes" in ss.dev else 2)
            cands.append(("impact", "sharded.allgather_topk",
                          {"tier": "impact", "shards": S, "queries": Q,
                           "k": k, "num_docs": S * n_max,
                           "code_bytes": code_b}))
        cands.append(("exact", "sharded.allgather_topk",
                      {"tier": "exact", "shards": S, "queries": Q,
                       "k": k, "num_docs": S * n_max}))
        arm = execution_planner().choose_arm(
            "sharded.msearch_merged", cands)
        if arm == "fused":
            return fs.msearch_merged_begin(fld, queries, k)
    elif _impact_sharded_usable(ss):
        arm = "impact"
    if arm == "impact":
        out = _msearch_merged_arm_begin(ss, fld, queries, k, impact=True,
                                        _return_program=_return_program)
        if out is not None:
            return out
    return _msearch_merged_arm_begin(ss, fld, queries, k, impact=False,
                                     _return_program=_return_program)


def _msearch_merged_fetch(st: dict) -> None:
    """Pull the merged program's outputs — the lane's ONE blocking
    device round-trip. Skips cleanly when the engine's combined wave
    fetch already delivered `st["host"]`."""
    if st.get("host") is not None or st.get("pending") is None:
        return
    from ..telemetry import host_transition, time_kernel

    with time_kernel(st["kernel"], **st["fields"]):
        st["host"] = jax.device_get(st["pending"])
    host_transition("fetch")


def _msearch_merged_finish(st: dict):
    """-> (scores [Q, kk], shard [Q, kk] i32, doc [Q, kk], totals [Q])."""
    _msearch_merged_fetch(st)  # no-op when the wave fetch already ran
    return st["finish"](st)


def _merged_rows_finish(st: dict):
    mv, msh, mi, mt = st["host"]
    return (np.asarray(mv), np.asarray(msh).astype(np.int32),
            np.asarray(mi), np.asarray(mt))


def _msearch_merged_arm_begin(ss: "StackedSearcher", fld: str,
                              queries: list, k: int, *, impact: bool,
                              _return_program=False):
    from ..ops.batched import batch_term_disjunction

    sp = ss.sp
    S = sp.S
    pl = _msearch_stack_plans(ss, fld, queries, k, impact=impact)
    if pl is None:
        return None
    Q = len(queries)
    avgdl, has_norms, kk = pl["avgdl"], pl["has_norms"], pl["kk"]
    n_max = sp.n_max
    Ts, B = pl["rows"].shape[2], pl["rows"].shape[3]
    dev_keys = (("post_docids", "impact_codes", "live") if impact
                else ("post_docids", "post_tfs", "post_dls", "live"))
    sub = {key: ss.dev[key] for key in dev_keys}
    if "dense_tfn" in ss.dev:
        sub["dense_tfn"] = ss.dev["dense_tfn"]
    cache_key = ("msearch_merged", impact, fld, Ts, B, kk, Q)
    fn = ss._cache.get(cache_key)
    if fn is None:
        from .spmd import (
            constrain, constrain_shards, merge_topk_rows, replica_axis,
        )

        mesh = ss.mesh
        ra = replica_axis(mesh)

        def shard_one(dev1, W1, rows1, ws1, iws1):
            return batch_term_disjunction(
                dev1, (Ts, B, kk), W1, rows1, ws1,
                avgdl=avgdl, num_docs=n_max, has_norms=has_norms,
                impact_w=(iws1 if impact else None),
            )

        def run(dev, W_, rows_, ws_, iws_):
            if ra is not None:
                # replica groups: the query axis splits over the mesh's
                # second axis, so each replica group scans the (shard-
                # local, replicated) pack for its own slice of the wave
                W_, rows_, ws_, iws_ = (
                    constrain(x, mesh, P("shards", ra))
                    for x in (W_, rows_, ws_, iws_))
            outs = jax.vmap(shard_one)(dev, W_, rows_, ws_, iws_)
            v, i, t = constrain_shards(outs, mesh)
            return merge_topk_rows(v, i, t, mesh=mesh)

        fn = ss._cache[cache_key] = jax.jit(run)
    iws = pl.get("iws")
    if iws is None:
        iws = np.zeros_like(pl["ws"])
    if _return_program:
        # measurement hook (scripts/c5_mesh_probe.py): the ONE compiled
        # program + its device inputs, so the in-program merge cost can
        # be timed against the shard-local partials program
        return fn, (sub, jnp.asarray(pl["W"]), jnp.asarray(pl["rows"]),
                    jnp.asarray(pl["ws"]), jnp.asarray(iws)), kk
    from ..telemetry import profile_event

    tier = "impact" if impact else "exact"
    profile_event("tier", tier=tier, queries=Q)
    fields = dict(tier=tier, shards=S, queries=Q, k=kk,
                  num_docs=S * n_max, rows=int(np.prod(pl["rows"].shape)))
    if impact:
        fields["code_bytes"] = int(
            np.dtype(ss.dev["impact_codes"].dtype).itemsize)
    prog_args = (sub, jnp.asarray(pl["W"]), jnp.asarray(pl["rows"]),
                 jnp.asarray(pl["ws"]), jnp.asarray(iws))
    from ..monitoring.xla_introspect import check_dispatch

    # PR 12: the one-program scan+merge vs its own compiled cost analysis
    check_dispatch("sharded.allgather_topk", fn, prog_args, fields=fields)
    outs = fn(*prog_args)
    return {"pending": outs, "host": None,
            "kernel": "sharded.allgather_topk", "fields": fields,
            "finish": _merged_rows_finish}


def global_merge_rows(ss: "StackedSearcher", v, i, t):
    """Standalone on-device coordinator merge of per-shard top rows —
    the `sharded.global_merge` program. Production arms fold the merge
    into their own compiled program (`_msearch_merged`); this entry
    point serves rows produced OUTSIDE one mergeable program (the mesh
    probe's merge-fraction measurement, tests) and returns the merged
    (scores [Q, kk], shard, doc, totals [Q]) as numpy."""
    from ..telemetry import time_kernel

    v = jnp.asarray(v)
    i = jnp.asarray(i)
    t = jnp.asarray(t)
    S, Q, kk = v.shape
    cache_key = ("global_merge", S, Q, kk)
    fn = ss._cache.get(cache_key)
    if fn is None:
        from .spmd import merge_topk_rows

        fn = ss._cache[cache_key] = jax.jit(
            lambda v_, i_, t_: merge_topk_rows(v_, i_, t_, mesh=ss.mesh))
    from ..monitoring.xla_introspect import check_dispatch

    check_dispatch("sharded.global_merge", fn, (v, i, t),
                   fields={"shards": S, "queries": Q, "k": kk})
    with time_kernel("sharded.global_merge", shards=S, queries=Q, k=kk):
        mv, msh, mi, mt = jax.device_get(fn(v, i, t))
    return (np.asarray(mv), np.asarray(msh).astype(np.int32),
            np.asarray(mi), np.asarray(mt))


def _msearch_exact_partials(ss: "StackedSearcher", fld: str,
                            queries: list, k: int = 10,
                            _return_program=False):
    """Batched disjunction kernel per shard (also the escalation target of
    the fused arm's flagged queries) -> pre-merge per-shard rows
    (v [S, Q, kk], i [S, Q, kk], t [S, Q]) numpy."""
    from ..ops.batched import batch_term_disjunction

    sp = ss.sp
    S = sp.S
    pl = _msearch_stack_plans(ss, fld, queries, k)
    Q = len(queries)
    W, rows, ws = pl["W"], pl["rows"], pl["ws"]
    avgdl, has_norms, kk = pl["avgdl"], pl["has_norms"], pl["kk"]
    n_max = sp.n_max
    Ts, B = rows.shape[2], rows.shape[3]

    def shard_body(dev1, W1, rows1, ws1):
        dev = {
            "post_docids": dev1["post_docids"][0],
            "post_tfs": dev1["post_tfs"][0],
            "post_dls": dev1["post_dls"][0],
            "live": dev1["live"][0],
        }
        if "dense_tfn" in dev1:
            dev["dense_tfn"] = dev1["dense_tfn"][0]
        v, i, t = batch_term_disjunction(
            dev, (Ts, B, kk), W1[0], rows1[0], ws1[0],
            avgdl=avgdl, num_docs=n_max, has_norms=has_norms,
        )
        return v[None], i[None], t[None]

    sub = {key: ss.dev[key] for key in
           ("post_docids", "post_tfs", "post_dls", "live")}
    if "dense_tfn" in ss.dev:
        sub["dense_tfn"] = ss.dev["dense_tfn"]
    cache_key = ("msearch_sharded", fld, Ts, B, kk, Q)
    fn = ss._cache.get(cache_key)
    if fn is None:
        if ss.mesh is not None:
            def run(dev, W_, rows_, ws_):
                specs = jax.tree_util.tree_map(lambda _: P("shards"), dev)
                return shard_map(
                    shard_body, mesh=ss.mesh,
                    in_specs=(specs, P("shards"), P("shards"), P("shards")),
                    out_specs=(P("shards"), P("shards"), P("shards")),
                )(dev, W_, rows_, ws_)
        else:
            def run(dev, W_, rows_, ws_):
                def body(d1, w1, r1, s1):
                    return shard_body(
                        jax.tree_util.tree_map(lambda x: x[None], d1),
                        w1[None], r1[None], s1[None],
                    )
                v, i, t = jax.vmap(body)(dev, W_, rows_, ws_)
                return v[:, 0], i[:, 0], t[:, 0]
        fn = ss._cache[cache_key] = jax.jit(run)
    if _return_program:
        # measurement hook (scripts/c5_mesh_probe.py): the compiled
        # program + its device inputs, so collective-merge overhead can be
        # timed against the shard-local portion on a virtual mesh
        return fn, (sub, jnp.asarray(W), jnp.asarray(rows),
                    jnp.asarray(ws)), kk
    from ..telemetry import time_kernel

    fields = dict(tier="exact", shards=S, queries=Q, k=kk,
                  num_docs=S * n_max, rows=int(np.prod(rows.shape)))
    prog_args = (sub, jnp.asarray(W), jnp.asarray(rows), jnp.asarray(ws))
    from ..monitoring.xla_introspect import check_dispatch

    check_dispatch("sharded.exact_disjunction", fn, prog_args,
                   fields=fields)
    with time_kernel("sharded.exact_disjunction", **fields):
        v, i, t = jax.device_get(fn(*prog_args))
    return v, i, t


class _PlanShardAdapter:
    """Minimal BatchTermSearcher host adapter for one shard of a stacked
    pack (planning only — execution happens in msearch_sharded's SPMD
    body, not through this object)."""

    def __init__(self, sp: StackedPack, s: int, ss: "StackedSearcher"):
        self.pack = sp.shard_view(s)
        self.ctx = ss.ctx
        self.dev = {}


def _fused_sharded_for(ss: "StackedSearcher"):
    """Cached fused-msearch arm for a StackedSearcher, or None when the
    pack shape can never qualify (no dense tier / no pallas)."""
    from ..ops import fused as F

    if F.pltpu is None or F.fused_enabled() == "0":
        return None
    if getattr(ss.sp, "dense_tf", None) is None or "dense_tfn" not in ss.dev:
        return None
    fs = getattr(ss, "_fused_msearch", None)
    if fs is None:
        fs = ss._fused_msearch = _FusedShardedMsearch(ss)
    return fs


class _FusedShardedMsearch:
    """C5 `_msearch` through the fused kernel, one pipeline per shard.

    The same `ops/fused._fused_pipeline` program that serves single-shard
    C1 runs as the per-shard body here: the in-kernel dense matmul +
    per-tile top-t + one-hot sparse scatter + canonical f32 rescore
    (lax.scan over QC-query chunks). Two routes share that body:

      * `msearch_merged_begin` (PR 11, the production pjit route) — the
        body runs inside an embedded shard_map manual region of ONE
        compiled SPMD program that also performs the on-device
        all-gather top-k merge; the host fetches k merged rows + one
        escalation bool per query.
      * `msearch` / `msearch_partials` (the shard_map oracle) — [S, Q, k]
        partials fetched and merged by the host coordinator in
        (score desc, shard asc, doc asc) order; kept as the parity
        fixture and the per-shard-cache execution arm of the legacy
        execution models.

    Queries flagged by ANY shard (window overflow, tile saturation,
    margin test) re-run on the exact arm, so results never depend on
    the fused pass — the same escalation contract as FusedTermSearcher."""

    def __init__(self, ss: "StackedSearcher"):
        from ..ops import fused as F

        self.ss = ss
        sp = ss.sp
        self.S = sp.S
        V = sp.dense_v
        # geometry snapshot (one per searcher — see FusedTermSearcher)
        self._qsub = F._cfg_qsub()
        self._tile_n = F._cfg_tile()
        self._t_env = int(os.environ.get("ES_TPU_FUSED_T", 0))
        self._vp2 = -(-2 * V // 128) * 128
        if (F.fused_topk_enabled() and V
                and os.environ.get("ES_TPU_FUSED_TILE") is None):
            self._tile_n = min(
                self._tile_n, F.auto_tile_matmul(self._vp2, self._qsub))
        self.n_max = sp.n_max
        self.n_pad = -(-max(sp.n_max, 1) // self._tile_n) * self._tile_n
        # the sharded arm runs stacked-tier-only (one resident layout per
        # chip); a stack too large for its chip disqualifies the arm
        self._use_stack = (
            os.environ.get("ES_TPU_FUSED_STACK", "1") != "0"
            and self._vp2 * self.n_pad * 2 <= 6 * 1024**3
        )
        self._inkernel = F.fused_topk_enabled() and self._use_stack
        self._fa = None
        self._fa_live_of = None
        self._fa_tier_of = None
        self._cache: dict = {}

    def usable(self, k: int) -> bool:
        from ..ops import fused as F

        mode = F.fused_enabled()
        if not (0 < k <= 16) or not self._use_stack:
            return False
        if self.n_max > F.MAX_DOCS_FUSED or self.n_max < 1:
            return False
        if mode == "force":
            return True
        return (jax.default_backend() == "tpu"
                and self.n_max >= 4 * F.FINE_N)

    def _arrays(self):
        from ..ops import fused as F

        dev = self.ss.dev
        if self._fa is None or self._fa_tier_of is not dev["dense_tfn"]:
            padw = self.n_pad - self.n_max
            rpad = self._vp2 - 2 * self.ss.sp.dense_v

            @jax.jit
            def split(t):  # [S, V, n_max] scored tfn -> [S, vp2, n_pad]
                tp = jnp.pad(t, ((0, 0), (0, 0), (0, padw)))
                hif = F._mask_hi(tp)
                hi = hif.astype(jnp.bfloat16)
                lo = (tp - hif).astype(jnp.bfloat16)
                st = jnp.concatenate([hi, lo], axis=1)
                return jnp.pad(st, ((0, 0), (0, rpad), (0, 0)))

            self._fa = {
                "tier32": dev["dense_tfn"],
                "post_docids": dev["post_docids"],
                "post_tfs": dev["post_tfs"],
                "post_dls": dev["post_dls"],
                "tier16_stack": split(dev["dense_tfn"]),
            }
            self._fa_tier_of = dev["dense_tfn"]
            self._fa_live_of = None  # force the live rebuild below
        if self._fa_live_of is not dev["live"]:
            padw = self.n_pad - self.n_max
            self._fa["live"] = jnp.pad(
                dev["live"].astype(jnp.float32), ((0, 0), (0, padw))
            )[:, None, :]
            self._fa_live_of = dev["live"]
        return self._fa

    def _geom(self, nreal):
        """Shared kernel geometry of one fused batch: (bud, tile_n,
        qsub, t) — window budget from the REAL posting count, pow2-
        quantized (see FusedTermSearcher._compiled_scan)."""
        from ..index.pack import BLOCK
        from ..ops import fused as F

        tile_n, qsub = self._tile_n, self._qsub
        njc = self.n_pad // tile_n
        t = self._t_env if self._t_env > 0 else F.tile_t_for(njc)
        nreal_q = 1 << max(nreal - 1, 1).bit_length()
        mean_win = max(1, nreal_q * BLOCK // ((F.QC // qsub) * njc))
        bude = min(
            64 * 1024, max(2048, 1 << (2 * mean_win - 1).bit_length())
        )
        return bude // 128, tile_n, qsub, t

    def _compiled(self, fld, C, R, Td, k, nreal, interpret):
        from ..ops import fused as F

        bud, tile_n, qsub, t = self._geom(nreal)
        key = (fld, C, R, Td, k, interpret, bud, tile_n, qsub, t,
               self._inkernel, self.ss.mesh is None)
        fn = self._cache.get(key)
        from ..monitoring.device import note_executable_cache

        note_executable_cache("sharded_fused", fn is not None)
        if fn is not None:
            return fn
        kw = dict(
            k=k, n=self.n_max, n_pad=self.n_pad,
            has_norms=fld in self.ss.ctx.has_norms,
            k1=1.2, b=0.75,
            bud=bud, t=t, tile_n=tile_n, qsub=qsub,
            interpret=interpret, inkernel=self._inkernel,
        )

        def shard_scan(fa1, avgdl, rows, row_q, row_w, dr, dw):
            def body(carry, xs):
                return carry, F._fused_pipeline(fa1, avgdl, *xs, **kw)

            _, outs = jax.lax.scan(body, 0, (rows, row_q, row_w, dr, dw))
            return outs

        from .spmd import manual_shard_region

        run = manual_shard_region(
            shard_scan, self.ss.mesh,
            in_specs=(P("shards"), P()) + (P("shards"),) * 5)
        fn = self._cache[key] = jax.jit(run)
        return fn

    def _compiled_merged(self, fld, C, R, Td, k, nreal, interpret):
        """ONE compiled SPMD program (PR 11, ROADMAP item 1): the
        per-shard fused Pallas pipeline runs inside an embedded
        shard_map manual region — custom calls cannot be GSPMD-
        partitioned, but a manual region never asks the partitioner —
        and its sharded [S, C·qc, k] rows feed the on-device all-gather
        top-k merge in the SAME program. The per-query escalation flag
        is OR'd across shards in-program too, so the host fetches
        merged k-rows + one bool per query: no more fused-tier fork off
        the one-program route, no S·k-row fetch, no host merge."""
        from ..ops import fused as F

        bud, tile_n, qsub, t = self._geom(nreal)
        key = ("merged", fld, C, R, Td, k, interpret, bud, tile_n, qsub,
               t, self._inkernel, self.ss.mesh is None)
        fn = self._cache.get(key)
        from ..monitoring.device import note_executable_cache

        note_executable_cache("sharded_fused", fn is not None)
        if fn is not None:
            return fn
        kw = dict(
            k=k, n=self.n_max, n_pad=self.n_pad,
            has_norms=fld in self.ss.ctx.has_norms,
            k1=1.2, b=0.75,
            bud=bud, t=t, tile_n=tile_n, qsub=qsub,
            interpret=interpret, inkernel=self._inkernel,
        )

        def shard_scan(fa1, avgdl, rows, row_q, row_w, dr, dw):
            def body(carry, xs):
                return carry, F._fused_pipeline(fa1, avgdl, *xs, **kw)

            _, outs = jax.lax.scan(body, 0, (rows, row_q, row_w, dr, dw))
            return outs

        from .spmd import constrain_shards, manual_shard_region, \
            merge_topk_rows

        mesh = self.ss.mesh
        region = manual_shard_region(
            shard_scan, mesh,
            in_specs=(P("shards"), P()) + (P("shards"),) * 5)

        def run(fa, avgdl, rows, row_q, row_w, dr, dw):
            v, i, tot, fl = region(fa, avgdl, rows, row_q, row_w, dr, dw)
            S_, C_, qc, kk = v.shape
            v2, i2, t2 = constrain_shards(
                (v.reshape(S_, C_ * qc, kk), i.reshape(S_, C_ * qc, kk),
                 tot.reshape(S_, C_ * qc)), mesh)
            mv, msh, mi, mt = merge_topk_rows(v2, i2, t2, mesh=mesh)
            flags = jnp.any(fl.reshape(S_, C_ * qc), axis=0)
            return mv, msh, mi, mt, flags

        fn = self._cache[key] = jax.jit(run)
        return fn

    def msearch(self, fld, queries, k):
        """Shard_map oracle route: per-shard partials + host merge —
        kept for the legacy execution model and parity fixtures; the
        production pjit route is `msearch_merged_begin`."""
        return _merge_shard_rows(*self.msearch_partials(fld, queries, k))

    def msearch_merged(self, fld, queries, k):
        """The one-program fused msearch, begin+fetch+finish in one call
        (tests/probes; the serving wave drives the stages separately)."""
        st = self.msearch_merged_begin(fld, queries, k)
        _msearch_merged_fetch(st)
        return st["finish"](st)

    def msearch_merged_begin(self, fld, queries, k) -> dict:
        """Plan + DISPATCH the fused one-program route (no fetch)."""
        from ..telemetry import profile_event

        idxs, pb = self._plan_batch(fld, queries, k)
        interpret = jax.default_backend() != "tpu"
        fn = self._compiled_merged(fld, pb["C"], pb["R"], pb["Td"], k,
                                   pb["nreal"], interpret)
        outs = fn(self._arrays(), pb["avgdl"], pb["rows"], pb["row_q"],
                  pb["row_w"], pb["dr"], pb["dw"])
        Q = len(queries)
        profile_event("tier", tier="fused", queries=Q)
        fields = dict(tier="fused", shards=self.S, queries=Q, k=k,
                      v=self.ss.sp.dense_v, num_docs=self.S * self.n_pad)
        return {"pending": outs, "host": None,
                "kernel": "sharded.fused_allgather_topk", "fields": fields,
                "finish": self._merged_finish,
                "idxs": idxs, "queries": queries, "fld": fld, "k": k}

    def _merged_finish(self, st: dict):
        """Fetched merged outputs -> (scores [Q, k], shard, doc, totals);
        flagged queries re-run on the exact merged arm (the escalation
        contract of the oracle route, at merged-row granularity)."""
        from ..ops import fused as F

        mv, msh, mi, mt, fl = [np.asarray(x) for x in st["host"]]
        queries, k, fld = st["queries"], st["k"], st["fld"]
        idxs = st["idxs"]
        Q = len(queries)
        kk = mv.shape[-1]
        qc = F.QC
        scores = np.full((Q, kk), -np.inf, np.float32)
        shards = np.zeros((Q, kk), np.int32)
        ids = np.zeros((Q, kk), np.int64)
        totals = np.zeros((Q,), np.int64)
        flagged = np.zeros((Q,), bool)
        for ci, qidx in enumerate(idxs):
            nq = len(qidx)
            base = ci * qc
            scores[qidx] = mv[base:base + nq]
            shards[qidx] = msh[base:base + nq]
            ids[qidx] = mi[base:base + nq]
            totals[qidx] = mt[base:base + nq]
            flagged[qidx] = fl[base:base + nq]
        if flagged.any():
            from ..telemetry import host_transition, profile_event

            still = np.nonzero(flagged)[0]
            profile_event("tier", tier="exact_escalation",
                          queries=int(still.shape[0]))
            st_ex = _msearch_merged_arm_begin(
                self.ss, fld, [queries[i_] for i_ in still], k,
                impact=False)
            host_transition("dispatch")
            _msearch_merged_fetch(st_ex)
            ev, esh, ei, et = _merged_rows_finish(st_ex)
            ke = min(ev.shape[1], kk)
            scores[still, :] = -np.inf
            scores[still, :ke] = ev[:, :ke]
            shards[still, :] = 0
            shards[still, :ke] = esh[:, :ke]
            ids[still, :] = 0
            ids[still, :ke] = ei[:, :ke]
            totals[still] = et
            st["extra_dispatches"] = st.get("extra_dispatches", 0) + 1
            st["extra_fetches"] = st.get("extra_fetches", 0) + 1
        return scores, shards, ids, totals

    def _plan_batch(self, fld, queries, k):
        """Host planning shared by the oracle and merged routes: per-
        shard per-chunk fused plans padded to one (R, Td) envelope.
        -> (chunk idxs, dict of stacked [S, C, ...] arrays + shapes)."""
        from ..ops import fused as F

        sp = self.ss.sp
        S = self.S
        Q = len(queries)
        qc = F.QC
        idxs = [np.arange(s0, min(s0 + qc, Q)) for s0 in range(0, Q, qc)]
        views = [sp.shard_view(s) for s in range(S)]
        plans = [
            [F.plan_fused(v, fld, [queries[i] for i in qidx], k, qc=qc)
             for qidx in idxs]
            for v in views
        ]  # [S][C]
        C = len(idxs)
        R = max(p.rows.shape[0] for ps in plans for p in ps)
        Td = max(p.dense_rows.shape[1] for ps in plans for p in ps)
        nreal = max(p.nreal for ps in plans for p in ps)

        def _padr(a, width):
            return np.pad(
                a, [(0, width - a.shape[0])] + [(0, 0)] * (a.ndim - 1))

        return idxs, {
            "rows": np.stack([[_padr(p.rows, R) for p in ps]
                              for ps in plans]),
            "row_q": np.stack([[_padr(p.row_q, R) for p in ps]
                               for ps in plans]),
            "row_w": np.stack([[_padr(p.row_w, R) for p in ps]
                               for ps in plans]),
            "dr": np.stack([
                [np.pad(p.dense_rows,
                        ((0, 0), (0, Td - p.dense_rows.shape[1])))
                 for p in ps] for ps in plans]),
            "dw": np.stack([
                [np.pad(p.dense_w, ((0, 0), (0, Td - p.dense_w.shape[1])))
                 for p in ps] for ps in plans]),
            "avgdl": np.float32(views[0].avgdl(fld)),
            "C": C, "R": R, "Td": Td, "nreal": nreal,
        }

    def msearch_partials(self, fld, queries, k):
        """Pre-merge per-shard rows (scores [S, Q, kk], ids, totals
        [S, Q]); queries flagged by ANY shard have their per-shard rows
        replaced by the exact arm's partials, so the merge (and any cached
        per-shard entry) never depends on the fused pass."""
        ss = self.ss
        sp = ss.sp
        S = self.S
        Q = len(queries)
        idxs, pb = self._plan_batch(fld, queries, k)
        interpret = jax.default_backend() != "tpu"
        fn = self._compiled(fld, pb["C"], pb["R"], pb["Td"], k,
                            pb["nreal"], interpret)
        from ..telemetry import profile_event, time_kernel

        profile_event("tier", tier="fused", queries=Q)
        with time_kernel("sharded.fused_pipeline", tier="fused", shards=S,
                         queries=Q, k=k, v=sp.dense_v,
                         num_docs=S * self.n_pad):
            v, i, t, fl = jax.device_get(
                fn(self._arrays(), pb["avgdl"], pb["rows"], pb["row_q"],
                   pb["row_w"], pb["dr"], pb["dw"]))
        # [S, C, qc, ...] -> per-shard [S, Q, ...]
        kk = v.shape[-1]
        scores = np.full((S, Q, kk), -np.inf, np.float32)
        ids = np.zeros((S, Q, kk), np.int64)
        totals = np.zeros((S, Q), np.int64)
        flagged = np.zeros((Q,), bool)
        for ci, qidx in enumerate(idxs):
            nq = len(qidx)
            scores[:, qidx] = v[:, ci, :nq]
            ids[:, qidx] = i[:, ci, :nq]
            totals[:, qidx] = t[:, ci, :nq]
            flagged[qidx] |= fl[:, ci, :nq].any(axis=0)
        if flagged.any():
            # escalation at per-shard granularity: the exact arm's
            # pre-merge rows REPLACE the fused rows for flagged queries,
            # so downstream consumers (merge, per-shard cache entries)
            # see only exact data for them
            still = np.nonzero(flagged)[0]
            profile_event("tier", tier="exact_escalation",
                          queries=int(still.shape[0]))
            ev, ei, et = _msearch_exact_partials(
                self.ss, fld, [queries[i_] for i_ in still], k)
            ke = ev.shape[2]
            scores[:, still, :] = -np.inf
            scores[:, still, :ke] = ev
            ids[:, still, :] = 0
            ids[:, still, :ke] = ei
            totals[:, still] = et
        return scores, ids, totals
