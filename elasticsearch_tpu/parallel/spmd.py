"""GSPMD sharding of the stacked pack: one compiled program per slice.

PR 10 replaces the explicit-stacking + per-shard `shard_map` execution
model with the sharding discipline every GSPMD training/inference stack
applies to its weights (SNIPPETS.md [1][2] — GDA/pjit sharded
compilation, regex partition rules over a params pytree): the device
pack IS a pytree, a `match_partition_rules`-style table maps every leaf
name to a `PartitionSpec`, arrays go up via `jax.device_put` with a
`NamedSharding`, and the search programs become ordinary `jit`-compiled
SPMD functions — `jax.vmap` over the shard axis of the sharded inputs,
`with_sharding_constraint` on the hot intermediates, and the global
top-k merge as `lax.top_k` over an ICI all-gather of the per-shard
(score, shard_doc) rows. XLA's SPMD partitioner lowers the gather to
ICI collectives; per-query device->host traffic drops from S*k rows to
k because only the merged (replicated) result is fetched.

Execution-mode contract (`ES_TPU_SPMD`):

  * ``pjit`` / ``auto`` (default) — GSPMD: sharded pack pytree, shard
    bodies embedded as `manual_shard_region` (shard_map-in-jit) regions
    of the ONE compiled program, on-device all-gather merge. PR 11:
    the manual region is how the fused Pallas arm rides this program —
    XLA's SPMD partitioner cannot split a custom call, but a manual
    region needs no partitioning decisions at all, so the Pallas
    kernels run per mesh device INSIDE the same compiled SPMD program
    that merges on-device. No separate code shape, no `force_xla` pin.
  * ``shardmap`` — the legacy PR-1..9 model: per-shard `shard_map`
    bodies + HOST coordinator merge. Demoted to a test oracle (parity
    fixtures, the C5 probe's shard-local timing arm); production
    routing never selects it unless the env forces it.

Replica groups: when `ES_TPU_REPLICAS=R` (R > 1) and the host exposes
S*R devices, the mesh gains a second ``replicas`` axis. Pack leaves are
sharded over ``shards`` only — i.e. replicated across ``replicas`` —
and the merged query axis is constrained over ``replicas``, so R
replica groups serve concurrent reads of the same resident pack.

Multi-process stretch (`ES_TPU_DIST_COORD`): `maybe_init_distributed`
wires `jax.distributed.initialize` behind env flags so the same mesh
code can span TCP cluster nodes; experimental, off by default.
"""

from __future__ import annotations

import os
import re

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# ---------------------------------------------------------------------------
# execution mode
# ---------------------------------------------------------------------------

def spmd_mode() -> str:
    """Resolved SPMD execution mode: "pjit" | "shardmap".

    ES_TPU_SPMD=auto|pjit|shardmap; auto (the default) resolves to pjit
    — the GSPMD path is the production model, shard_map the fallback."""
    v = os.environ.get("ES_TPU_SPMD", "auto").strip().lower()
    if v == "shardmap":
        return "shardmap"
    return "pjit"


# ---------------------------------------------------------------------------
# partition rules over the pack pytree
# ---------------------------------------------------------------------------

# leaf-path regex -> PartitionSpec. Paths are '/'-joined pytree key paths
# of the device pack dict built by `parallel/sharded.stacked_to_device`
# (e.g. "post_docids", "norms/body", "dv_int/bytes/0",
# "vec_ann/vec/codes"). Every stacked leaf carries the shard axis
# leading, so its spec shards dim 0 over "shards" and (implicitly)
# replicates the rest — including across a "replicas" mesh axis when one
# exists. The table is deliberately EXHAUSTIVE and non-overlapping: a
# leaf matching zero rules or more than one rule is a hard error
# (tests/test_spmd.py), so a new pack component cannot silently ship
# replicated (HBM x S) or mis-sharded.
PACK_PARTITION_RULES: list[tuple[str, P]] = [
    (r"^(post_docids|post_tfs|post_dls)$", P("shards")),
    (r"^impact_codes$", P("shards")),
    (r"^pos_keys$", P("shards")),
    (r"^live$", P("shards")),
    (r"^dense_tf$", P("shards")),
    (r"^dense_tfn$", P("shards")),
    (r"^norms/", P("shards")),
    (r"^text_has/", P("shards")),
    (r"^dv_int/", P("shards")),
    (r"^dv_float/", P("shards")),
    (r"^dv_ord/", P("shards")),
    (r"^dv_mv/", P("shards")),
    (r"^dv_int_ord/", P("shards")),
    (r"^vec/", P("shards")),
    (r"^vec_has/", P("shards")),
    (r"^vec_sq/", P("shards")),
    (r"^vec_ann/", P("shards")),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jtu.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jtu.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jtu.GetAttrKey):
            parts.append(str(p.name))
        else:  # pragma: no cover - future key kinds degrade to repr
            parts.append(str(p))
    return "/".join(parts)


def leaf_paths(tree) -> list[tuple[str, object]]:
    """-> [(path_str, leaf)] for every leaf of the pack pytree."""
    flat, _ = jtu.tree_flatten_with_path(tree)
    return [(_path_str(path), leaf) for path, leaf in flat]


def match_partition_rules(tree, rules=None):
    """-> pytree of PartitionSpec, one per leaf of `tree`.

    The fmengine/GSPMD `match_partition_rules` discipline applied to the
    pack: scalars (and 1-element arrays) replicate as PS(); every other
    leaf must match EXACTLY ONE rule — zero matches means an unsharded
    new component (it would replicate S-fold in HBM), two means an
    ambiguous table; both are hard errors, never silent fallbacks."""
    rules = PACK_PARTITION_RULES if rules is None else rules
    flat, treedef = jtu.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in flat:
        name = _path_str(path)
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        hits = [spec for rx, spec in rules if re.search(rx, name)]
        if len(hits) == 0:
            raise ValueError(
                f"no partition rule matches pack leaf [{name}] "
                f"(shape {shape}) — add it to PACK_PARTITION_RULES")
        if len(hits) > 1:
            raise ValueError(
                f"pack leaf [{name}] matched {len(hits)} partition rules "
                "— the table must be non-overlapping")
        specs.append(hits[0])
    return jtu.tree_unflatten(treedef, specs)


def shard_put(tree, mesh: Mesh):
    """Ship a host pack pytree to the mesh: `jax.device_put` with the
    rule-matched NamedSharding per leaf. This is the GSPMD replacement
    for the positional `P("shards", None, ...)` construction — the
    sharding of every leaf is decided by its NAME, the same way a
    training stack shards its params pytree."""
    specs = match_partition_rules(tree)
    return jtu.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), NamedSharding(mesh, s)),
        tree, specs)


# ---------------------------------------------------------------------------
# sharding constraints (the hot-intermediate annotations)
# ---------------------------------------------------------------------------

def constrain(x, mesh: Mesh | None, spec: P):
    """with_sharding_constraint, a no-op off-mesh (so traced bodies are
    shared between the single-device and pjit paths)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain_shards(tree, mesh: Mesh | None):
    """Constrain every leaf of a per-shard output pytree to stay sharded
    over the mesh's shard axis (dim 0) — the annotation that keeps the
    vmapped shard bodies partitioned instead of gathered."""
    if mesh is None:
        return tree
    s = NamedSharding(mesh, P("shards"))
    return jtu.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, s), tree)


def replica_axis(mesh: Mesh | None) -> str | None:
    """The mesh's replica axis name when replica groups are configured."""
    if mesh is not None and "replicas" in mesh.axis_names:
        return "replicas"
    return None


def manual_shard_region(shard_body, mesh: Mesh | None, *, in_specs):
    """Run a per-shard body as ONE region of the caller's jit program.

    On a mesh the body executes inside an embedded `shard_map` — manual
    partitioning, the only execution form in which Pallas custom calls
    run per mesh device inside a single compiled SPMD program (GSPMD
    cannot partition a custom call; a manual region never asks it to).
    The surrounding program stays GSPMD, so the on-device all-gather
    top-k merge composes directly with the region's sharded outputs —
    this is the PR-11 closure of the fused-arm fork (ROADMAP item 1).

    Off-mesh the same body runs under `vmap` over the stacked axis.
    `in_specs` entries are `P("shards")` for [S, ...]-stacked pytree
    args (squeezed to the shard-local slice for the body) or `P()` for
    replicated args passed through whole. Outputs keep the leading
    shard axis (out_specs P("shards"))."""
    import jax.tree_util as jtu

    shards_spec = P("shards")
    if mesh is None:
        axes = tuple(0 if s == shards_spec else None for s in in_specs)

        def region(*args):
            return jax.vmap(shard_body, in_axes=axes)(*args)

        return region
    from ..utils.jax_env import shard_map

    def body(*args_s):
        def one(spec, t):
            if spec == shards_spec:
                return jtu.tree_map(lambda x: x[0], t)
            return t

        outs = shard_body(*(one(s, a) for s, a in zip(in_specs, args_s)))
        return jtu.tree_map(lambda x: jnp.asarray(x)[None], outs)

    def region(*args):
        return shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=shards_spec)(*args)

    return region


# ---------------------------------------------------------------------------
# the on-device global top-k merge
# ---------------------------------------------------------------------------

def merge_topk_rows(v, i, t, *, mesh: Mesh | None = None):
    """Global coordinator merge, traced: per-shard top rows
    (v [S, Q, kk] f32, i [S, Q, kk] ids, t [S, Q] totals) ->
    (scores [Q, kk], shard [Q, kk] i32, doc [Q, kk], totals [Q]).

    Order is (score desc, shard asc, doc asc) — the reference's
    SearchPhaseController / Lucene TopDocs.merge order, byte-identical
    to the host `_merge_shard_rows` lexsort: `lax.top_k` breaks score
    ties by lowest flat index, the shard-major flat layout makes flat
    index order = (shard asc, rank asc), and each shard's row is already
    (score desc, doc asc) internally, so rank asc == doc asc on ties.

    Under a mesh the input rows are constrained to replicated before the
    top-k — THIS is the ICI all-gather (S*Q*kk (score, id) rows cross
    the interconnect once; the merged k rows are replicated, so the host
    fetch pulls k rows per query instead of S*k). With replica groups
    the query axis stays split over "replicas" so each group merges only
    its own slice of the wave."""
    S, Q, kk = v.shape
    flat_v = jnp.swapaxes(v, 0, 1).reshape(Q, S * kk)
    flat_i = jnp.swapaxes(i, 0, 1).reshape(Q, S * kk)
    ra = replica_axis(mesh)
    flat_v = constrain(flat_v, mesh, P(ra, None))
    flat_i = constrain(flat_i, mesh, P(ra, None))
    mv, sel = jax.lax.top_k(flat_v, kk)
    shard = (sel // kk).astype(jnp.int32)
    mi = jnp.take_along_axis(flat_i, sel, axis=1)
    return mv, shard, mi, t.sum(axis=0)


def allgather_rows_bytes(s: int, q: int, kk: int,
                         id_bytes: int = 8) -> float:
    """The collective-traffic model of the merge: every shard's [Q, kk]
    (score f32, id i64) rows are all-gathered across the S mesh devices
    — per-device ICI traffic is (S-1)/S of the total row bytes out and
    the same in; the model reports the TOTAL gathered row volume
    S*Q*kk*(4+id_bytes), the quantity the all-gather moves across the
    interconnect once (BENCH_NOTES round 14)."""
    return float(s * q * kk * (4 + id_bytes))


# ---------------------------------------------------------------------------
# mesh construction + the multi-process stretch
# ---------------------------------------------------------------------------

_dist_initialized = False


def maybe_init_distributed() -> bool:
    """Experimental multi-process mesh across TCP cluster nodes: when
    ES_TPU_DIST_COORD is set, `jax.distributed.initialize` joins this
    process to the slice-wide device mesh (coordinator address +
    ES_TPU_DIST_NPROCS / ES_TPU_DIST_RANK) so `jax.devices()` spans
    every node and the same pjit programs compile slice-wide. Off by
    default; failures log and degrade to the single-process mesh."""
    global _dist_initialized
    coord = os.environ.get("ES_TPU_DIST_COORD")
    if not coord or _dist_initialized:
        return _dist_initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("ES_TPU_DIST_NPROCS", "1")),
            process_id=int(os.environ.get("ES_TPU_DIST_RANK", "0")),
        )
        _dist_initialized = True
    except Exception:  # noqa: BLE001 - degrade to single-process
        _dist_initialized = False
    return _dist_initialized


def make_mesh(num_shards: int) -> Mesh | None:
    """Mesh over the first num_shards devices; None -> single-device vmap.

    In pjit mode, ES_TPU_REPLICAS=R (with S*R devices available) builds
    a 2-D (S, R) mesh with axes ("shards", "replicas"): the pack shards
    over the first axis and replicates over the second, so R replica
    groups serve concurrent reads. The shard_map fallback always gets
    the 1-D mesh (its in/out specs name only "shards")."""
    maybe_init_distributed()
    devices = jax.devices()
    if num_shards <= 1 or len(devices) < num_shards:
        return None
    if spmd_mode() == "pjit":
        want = int(os.environ.get("ES_TPU_REPLICAS", "1") or 1)
        r = max(1, min(want, len(devices) // num_shards))
        if r > 1:
            arr = np.array(devices[: num_shards * r]).reshape(num_shards, r)
            return Mesh(arr, ("shards", "replicas"))
    return Mesh(np.array(devices[:num_shards]), ("shards",))
