"""StackedPack: S shard packs fused into [S, ...] arrays for a device mesh.

This is where the framework diverges hardest from the reference. The
reference's shards are independent Lucene indexes on separate nodes with
shard-local term dictionaries and ordinals, merged by string key at the
coordinator (reference behavior: SearchPhaseController.java:232 top-docs
merge; GlobalOrdinalsStringTermsAggregator + coordinator reduce for terms
aggs). On a TPU slice all shards pack in one process, so we can afford
**global dictionaries**: keyword ordinals, numeric uniq-ordinals, histogram
bucket plans, and avgdl/docCount stats are shared across shards. Shard merge
then degenerates to array reductions (sum/min/max/OR) instead of key-space
remapping — the agg reduce rides ICI/host memcpy, not string hashing.

Per-shard state that stays local: postings + term dictionary (each shard
scores its own term blocks; per-shard df supports the reference's default
query_then_fetch idf, global df supports dfs_query_then_fetch).

PR 10: the [S, ...] family built here is consumed as a GSPMD-sharded
PYTREE — `parallel/sharded._stacked_host_tree` names every leaf and
`parallel/spmd.PACK_PARTITION_RULES` maps leaf names to PartitionSpecs
(exactly-one-rule enforced), so adding an array to this class means
adding its rule, or the upload fails loudly instead of replicating the
array S-fold in HBM.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from ..cluster.routing import shard_for_id
from ..index.mappings import Mappings
from ..index.pack import BLOCK, DocValuesColumn, PackBuilder, ShardPack, VectorColumn


@dataclass
class _ShardView:
    """ShardPack facade handing global stats to query planning.

    `term_blocks` resolves against the shard's own postings but reports the
    global df; `field_stats` and `docvalues` come from the global (stacked)
    dictionaries so every shard plans identical shapes and scores with
    identical statistics. This is the reference's dfs_query_then_fetch
    semantics (search/dfs/DfsPhase.java aggregates term/collection stats
    before scoring) — the only sharded scoring mode here, chosen because
    cross-shard-consistent scores are strictly more useful and global stats
    are free when all shards pack in one process."""

    pack: ShardPack
    stacked: "StackedPack"
    shard_index: int = 0

    @property
    def num_docs(self):
        # padded width: dense accumulators must be the same size on every
        # device of the mesh
        return self.stacked.n_max

    @property
    def field_stats(self):
        return self.stacked.eff_field_stats

    @property
    def docvalues(self):
        return self.stacked.global_docvalues

    @property
    def vectors(self):
        # the stacked union, NOT the per-shard dict: planning state derived
        # here (similarity, dims, field presence) must be identical on every
        # shard because device_eval is traced once for the whole mesh
        return self.stacked.vectors

    @property
    def norms(self):
        return self.pack.norms

    @property
    def text_present(self):
        return self.pack.text_present

    def avgdl(self, fld):
        st = self.stacked.eff_field_stats.get(fld)
        if not st or st["doc_count"] == 0:
            return 1.0
        return st["sum_dl"] / st["doc_count"]

    def term_blocks(self, fld, term):
        s, n, df = self.pack.term_blocks(fld, term)
        return s, n, self.stacked.eff_global_df.get((fld, term), df)

    def dense_row_of(self, fld, term):
        # global tier decision: identical on every shard (see StackedPack)
        return self.stacked.dense_dict.get((fld, term))

    @property
    def dense_tfn(self):
        # batched planning reads only the row-count shape; expose this
        # shard's raw stacked tier rows (tf, not tfn — never scored here)
        dt = getattr(self.stacked, "dense_tf", None)
        return None if dt is None else dt[self.shard_index]

    def impact_wscale(self, fld, term):
        """Impact-tier dequant scale (see ShardPack.impact_wscale), gated
        on the STACKED serving state: the searcher must have derived code
        blocks for the current effective stats (refresh_impacts). Returns
        0.0 — not None — for a term this shard simply lacks, so every
        shard prepares the same param shape (the rows are all-padding and
        contribute nothing)."""
        st = self.stacked
        if not st.impact_serving():
            return None
        tid = self.pack.term_dict.get((fld, term))
        if tid is None or self.pack.impact_ubf is None:
            return 0.0
        return float(self.pack.impact_ubf[tid]) / st.impact_meta["qmax"]

    def terms_for_field(self, fld):
        # expansion is per-shard (each shard enumerates its own dictionary),
        # matching the reference's per-shard MultiTermQuery rewrite
        return self.pack.terms_for_field(fld)

    def term_pos_blocks(self, fld, term):
        return self.pack.term_pos_blocks(fld, term)


# sentinel: "no searcher has derived impact codes for this pack yet" —
# distinct from stats_override's None so a fresh pack never claims to serve
_IMPACT_UNSET = object()


class StackedPack:
    def __init__(
        self,
        shards: list[ShardPack],
        mappings: Mappings,
        dense_min_df: int | None = None,
    ):
        self.shards = shards
        self.mappings = mappings
        self.S = len(shards)
        self._nbytes_cache: int | None = None
        # tiered refresh: when this pack is one tier of a (base, tail) pair,
        # the engine overrides the scoring statistics with the COMBINED
        # stats so both tiers score identically (the reference's analog:
        # Lucene collection statistics span all segments at reader open)
        self.stats_override: dict | None = None
        self.n_max = max((p.num_docs for p in shards), default=0)
        self.nb_max = max((p.num_blocks for p in shards), default=1)

        # ---- global stats ------------------------------------------------
        self.field_stats: dict[str, dict] = {}
        for p in shards:
            for fld, st in p.field_stats.items():
                g = self.field_stats.setdefault(fld, {"sum_dl": 0.0, "doc_count": 0})
                g["sum_dl"] += st["sum_dl"]
                g["doc_count"] += st["doc_count"]
        self.global_df: dict[tuple[str, str], int] = {}
        for p in shards:
            for key, tid in p.term_dict.items():
                self.global_df[key] = self.global_df.get(key, 0) + int(p.term_df[tid])

        # ---- global docvalue dictionaries + remapped columns -------------
        # built as columns padded to n_max and stacked [S, n_max]
        self.global_docvalues: dict[str, DocValuesColumn] = {}
        self.stacked_docvalues: dict[str, DocValuesColumn] = {}
        fields = sorted({f for p in shards for f in p.docvalues})
        for fld in fields:
            cols = [p.docvalues.get(fld) for p in shards]
            kind = next(c.kind for c in cols if c is not None)
            vals = []
            has = []
            if kind == "ord":
                terms = sorted({t for c in cols if c and c.ord_terms for t in c.ord_terms})
                ord_of = {t: i for i, t in enumerate(terms)}
                mv_any = any(c is not None and c.mv_pair_docs is not None
                             for c in cols)
                mv_docs_list, mv_ords_list = [], []
                for p, c in zip(shards, cols):
                    v = np.full(self.n_max, -1, np.int32)
                    h = np.zeros(self.n_max, bool)
                    if c is not None:
                        remap = np.array(
                            [ord_of[t] for t in (c.ord_terms or [])] + [-1], np.int32
                        )
                        v[: p.num_docs] = remap[c.values]
                        h[: p.num_docs] = c.has_value
                        if mv_any:
                            if c.mv_pair_docs is not None:
                                mv_docs_list.append(c.mv_pair_docs)
                                mv_ords_list.append(remap[c.mv_pair_ords])
                            else:
                                # single-valued shard: its pairs are the
                                # (doc, value) entries of the dense column
                                sel = np.flatnonzero(c.has_value)
                                mv_docs_list.append(sel.astype(np.int32))
                                mv_ords_list.append(remap[c.values[sel]])
                    elif mv_any:
                        mv_docs_list.append(np.array([], np.int32))
                        mv_ords_list.append(np.array([], np.int32))
                    vals.append(v)
                    has.append(h)
                g = DocValuesColumn(kind, np.stack(vals), np.stack(has), terms)
                if mv_any:
                    pmax = max((len(d) for d in mv_docs_list), default=1) or 1
                    sd = np.full((self.S, pmax), -1, np.int32)
                    so = np.zeros((self.S, pmax), np.int32)
                    for i, (d, o) in enumerate(zip(mv_docs_list, mv_ords_list)):
                        sd[i, : len(d)] = d
                        so[i, : len(o)] = o
                    g.mv_pair_docs = sd
                    g.mv_pair_ords = so
            else:
                dtype = np.int64 if kind == "int" else np.float32
                present_vals = [
                    c.values[c.has_value] for c in cols if c is not None and c.has_value.any()
                ]
                allv = np.concatenate(present_vals) if present_vals else np.array([], dtype)
                uniq = np.unique(allv) if kind == "int" else None
                for p, c in zip(shards, cols):
                    v = np.zeros(self.n_max, dtype)
                    h = np.zeros(self.n_max, bool)
                    if c is not None:
                        v[: p.num_docs] = c.values
                        h[: p.num_docs] = c.has_value
                    vals.append(v)
                    has.append(h)
                g = DocValuesColumn(kind, np.stack(vals), np.stack(has))
                if len(allv):
                    g.vmin = allv.min().item()
                    g.vmax = allv.max().item()
                if kind == "int" and uniq is not None and len(uniq):
                    g.uniq_values = uniq
                    ords = []
                    for p, c in zip(shards, cols):
                        o = np.full(self.n_max, -1, np.int32)
                        if c is not None and c.has_value.any():
                            o[: p.num_docs][c.has_value] = np.searchsorted(
                                uniq, c.values[c.has_value]
                            ).astype(np.int32)
                        ords.append(o)
                    g.uniq_ords = np.stack(ords)
            self.stacked_docvalues[fld] = g
            # planning view: same dict/stats, values not used by prepare
            self.global_docvalues[fld] = g

        # ---- stacked postings & norms ------------------------------------
        self.post_docids = np.full((self.S, self.nb_max, BLOCK), self.n_max, np.int32)
        self.post_tfs = np.zeros((self.S, self.nb_max, BLOCK), np.float32)
        self.post_dls = np.ones((self.S, self.nb_max, BLOCK), np.float32)
        self.live = np.zeros((self.S, self.n_max), bool)
        for i, p in enumerate(shards):
            d = p.post_docids.copy()
            d[d == p.num_docs] = self.n_max  # re-sentinel padding to n_max
            self.post_docids[i, : p.num_blocks] = d
            self.post_tfs[i, : p.num_blocks] = p.post_tfs
            self.post_dls[i, : p.num_blocks] = p.post_dls
            self.live[i, : p.num_docs] = p.live
        # ---- impact tier planning state (BM25S) --------------------------
        # Per-shard row->term/field maps + the static per-row code scale
        # (avgdl-INDEPENDENT: ubf bounds tfn over any doc length, see
        # index/pack.py). The code BLOCKS themselves are derived on device
        # by StackedSearcher.refresh_impacts from the EFFECTIVE field
        # stats — global at build, combined under stats_override — so the
        # tier re-norms with one elementwise pass per refresh, never a
        # host rebuild. `_impact_basis` records which stats the resident
        # codes were derived from; serving is gated on it matching.
        from ..index.pack import (
            IMPACT_QMAX, impact_dtype_default, impact_row_terms,
            impact_term_ubf,
        )

        self.impact_meta = None
        self._impact_basis = _IMPACT_UNSET
        if any(len(p.term_df) for p in shards):
            dtype = impact_dtype_default()
            qmax = IMPACT_QMAX[dtype]
            self.impact_fields = sorted(
                {f for p in shards for (f, _t) in p.term_dict})
            fcode = {f: i for i, f in enumerate(self.impact_fields)}
            self.impact_row_scale_inv = np.zeros(
                (self.S, self.nb_max), np.float32)
            self.impact_row_field = np.full(
                (self.S, self.nb_max), -1, np.int32)
            for i, p in enumerate(shards):
                T = len(p.term_df)
                if T == 0:
                    continue
                ubf = p.impact_ubf
                if ubf is None:
                    ubf = impact_term_ubf(p.term_block_start, p.block_max_tf)
                    p.impact_ubf = ubf
                rt = impact_row_terms(p.term_block_start, p.num_blocks)
                fields_by_tid = np.array(
                    [fcode[f] for (f, _t), _tid in sorted(
                        p.term_dict.items(), key=lambda kv: kv[1])],
                    np.int32)
                sel = rt >= 0
                rows = np.flatnonzero(sel)
                self.impact_row_scale_inv[i, rows] = (
                    qmax / np.maximum(ubf[rt[sel]], 1e-9))
                self.impact_row_field[i, rows] = fields_by_tid[rt[sel]]
            from ..index.pack import BM25_B, BM25_K1

            self.impact_meta = {"dtype": dtype, "qmax": qmax,
                                "k1": BM25_K1, "b": BM25_B}

        # ---- stacked position blocks -------------------------------------
        self.pos_keys = None
        if any(p.pos_keys is not None for p in shards):
            from ..index.pack import POS_INF

            nbp_max = max(
                (p.pos_keys.shape[0] for p in shards if p.pos_keys is not None),
                default=1,
            )
            self.pos_keys = np.full((self.S, nbp_max, BLOCK), POS_INF, np.int64)
            for i, p in enumerate(shards):
                if p.pos_keys is not None:
                    self.pos_keys[i, : p.pos_keys.shape[0]] = p.pos_keys
        norm_fields = sorted({f for p in shards for f in p.norms})
        self.norms = {}
        self.text_present = {}
        for fld in norm_fields:
            arr = np.ones((self.S, self.n_max), np.float32)
            pres = np.zeros((self.S, self.n_max), bool)
            for i, p in enumerate(shards):
                if fld in p.norms:
                    arr[i, : p.num_docs] = p.norms[fld]
                    pres[i, : p.num_docs] = p.text_present[fld]
            self.norms[fld] = arr
            self.text_present[fld] = pres
        # completion inputs: host-side union with shard tags, input-sorted
        self.completion: dict[str, list] = {}
        for i, p in enumerate(shards):
            for fld, entries in p.completion.items():
                self.completion.setdefault(fld, []).extend(
                    (inp, w, i, d) for (inp, w, d) in entries
                )
        for fld in self.completion:
            self.completion[fld].sort()
        # ---- stacked vectors ---------------------------------------------
        self.vectors: dict[str, VectorColumn] = {}
        vec_fields = sorted({f for p in shards for f in p.vectors})
        for fld in vec_fields:
            vc0 = next(p.vectors[fld] for p in shards if fld in p.vectors)
            vals = np.zeros((self.S, self.n_max, vc0.dims), np.float32)
            has = np.zeros((self.S, self.n_max), bool)
            for i, p in enumerate(shards):
                if fld in p.vectors:
                    vals[i, : p.num_docs] = p.vectors[fld].values
                    has[i, : p.num_docs] = p.vectors[fld].has_value
            svc = VectorColumn(vals, has, vc0.similarity, vc0.dims,
                               ann_quant=vc0.ann_quant)
            # stacked ANN: present only when EVERY populated shard built
            # one (uniform nlist ensured by shared mappings). Shards pad
            # to the widest (C, L); pad centroids get a huge norm so
            # their probe logit (c.q - ||c||^2/2) can never win, pad
            # slots stay -1 (dead lanes in the gather-scan).
            anns = [p.vectors[fld].ann for p in shards if fld in p.vectors]
            if anns and all(v is not None for v in anns):
                C = max(v["centroids"].shape[0] for v in anns)
                L = max(v["tile"] for v in anns)
                D = vc0.dims
                cents = np.full((self.S, C, D), 1e6, np.float32)
                order = np.full((self.S, C, L), -1, np.int32)
                codes = np.zeros((self.S, C, L, D), np.int8)
                scale = np.zeros((self.S, C, L), np.float32)
                offset = np.zeros((self.S, C, L), np.float32)
                for i, p in enumerate(shards):
                    v = p.vectors[fld].ann if fld in p.vectors else None
                    if v is None:
                        continue
                    c_i, l_i = v["order"].shape
                    cents[i, :c_i] = v["centroids"]
                    order[i, :c_i, :l_i] = v["order"]
                    codes[i, :c_i, :l_i] = v["codes"]
                    scale[i, :c_i, :l_i] = v["scale"]
                    offset[i, :c_i, :l_i] = v["offset"]
                svc.ann = {
                    "centroids": cents, "order": order, "codes": codes,
                    "scale": scale, "offset": offset,
                    "nlist": C, "tile": L,
                    "built_n": max(v["built_n"] for v in anns),
                }
            self.vectors[fld] = svc

        # ---- global dense tier -------------------------------------------
        # tier membership must be a GLOBAL decision (global df) so every
        # shard's query plan routes each term identically — the per-shard
        # program is traced once for the whole mesh. RAW tf rows are stored
        # (dense_tf); the scored tfn rows are computed ON DEVICE from
        # (tf, norms, avgdl) by the searcher — avgdl is a runtime input, so
        # stat drift from tiered refreshes re-norms the tier with one
        # elementwise device pass instead of a host rebuild + transfer.
        from ..index.pack import default_dense_min_df

        n_total = sum(p.num_docs for p in shards)
        thresh = dense_min_df if dense_min_df is not None else default_dense_min_df(n_total)
        dense_keys = sorted(k for k, df in self.global_df.items() if df >= thresh)
        self.dense_dict: dict[tuple[str, str], int] = {
            k: i for i, k in enumerate(dense_keys)
        }
        self.dense_fields: list[str] = [k[0] for k in dense_keys]
        self.dense_tf = None
        if dense_keys:
            self.dense_tf = np.zeros((self.S, len(dense_keys), self.n_max), np.float32)
            for i, k in enumerate(dense_keys):
                fld = k[0]
                for s, p in enumerate(shards):
                    s0, nb, _df = p.term_blocks(fld, k[1])
                    if nb == 0:
                        continue
                    docs = p.post_docids[s0 : s0 + nb].ravel()
                    valid = docs < p.num_docs
                    docs = docs[valid]
                    tfs = p.post_tfs[s0 : s0 + nb].ravel()[valid]
                    self.dense_tf[s, i, docs] = tfs

    def dense_tfn_host(self, row: int, shard: int, avgdl: float,
                       k1: float | None = None, b: float | None = None) -> np.ndarray:
        """One dense row's tfn computed host-side with the CURRENT avgdl
        (WAND planning bounds; the bulk tfn tier lives on device)."""
        from ..index.pack import BM25_K1, BM25_B

        k1 = BM25_K1 if k1 is None else k1
        b = BM25_B if b is None else b
        tf = self.dense_tf[shard, row]
        fld = self.dense_fields[row]
        if fld in self.norms:
            K = k1 * (1.0 - b + b * self.norms[fld][shard] / max(avgdl, 1e-9))
        else:
            K = k1
        return (tf / np.maximum(tf + K, 1e-9)).astype(np.float32)

    def impact_serving(self) -> bool:
        """True when the resident impact code blocks were derived from the
        CURRENT effective stats (StackedSearcher.refresh_impacts ran after
        the last stats_override change) — the planning gate for the
        gather+sum scoring path. A stale basis degrades to the exact
        raw-postings path, never to wrong scores."""
        return (self.impact_meta is not None
                and self._impact_basis is self.stats_override)

    @property
    def eff_field_stats(self) -> dict:
        if self.stats_override is not None:
            return self.stats_override["field_stats"]
        return self.field_stats

    @property
    def eff_global_df(self) -> dict:
        if self.stats_override is not None:
            return self.stats_override["global_df"]
        return self.global_df

    @property
    def num_docs(self) -> int:
        return sum(p.num_docs for p in self.shards)

    @property
    def dense_v(self) -> int:
        """Dense-tier row count (0 = no tier) — the fused-kernel geometry
        input shared by the single-shard and sharded fused searchers."""
        return 0 if self.dense_tf is None else self.dense_tf.shape[1]

    def shard_view(self, s: int) -> _ShardView:
        return _ShardView(self.shards[s], self, s)

    def nbytes(self) -> int:
        """Total array bytes of the stacked device-bound structures (the
        memory the circuit breaker must admit before the pack ships to HBM)."""
        if self._nbytes_cache is not None:
            return self._nbytes_cache

        seen: set[int] = set()
        total = 0

        def walk(obj):
            nonlocal total
            if isinstance(obj, (str, int, float, bool, type(None))):
                return
            if id(obj) in seen:
                return
            seen.add(id(obj))
            if isinstance(obj, np.ndarray):
                total += obj.nbytes
            elif isinstance(obj, dict):
                for v in obj.values():
                    walk(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    walk(v)
            elif hasattr(obj, "__dict__"):
                for v in vars(obj).values():
                    walk(v)

        walk({k: v for k, v in vars(self).items() if k != "mappings"})
        if self.impact_meta is not None:
            # the searcher derives the stacked impact-code blocks on
            # device (refresh_impacts): [S, nb_max, BLOCK] at the code
            # dtype, on top of the host planning arrays walked above
            code_bytes = 2 if self.impact_meta["dtype"] == "uint16" else 1
            total += self.S * self.nb_max * BLOCK * code_bytes
        if self.dense_tf is not None:
            # the searcher materializes the derived dense_tfn alongside the
            # raw tf rows on device — admit both copies
            total += self.dense_tf.nbytes
            from ..ops.fused import fused_enabled

            if fused_enabled() != "0":
                # the fused msearch arm holds the split-bf16 [2V, n_pad]
                # stack per shard too (~the f32 tier's bytes again)
                total += self.dense_tf.nbytes
        self._nbytes_cache = total
        return total


def route_docs(
    docs: list[tuple[str, dict]], num_shards: int
) -> list[list[tuple[str, dict]]]:
    """Murmur3-route (id, source) docs to per-shard lists — the single
    source of truth for doc->shard placement; pack building and hit-id
    resolution both consume this."""
    routed: list[list[tuple[str, dict]]] = [[] for _ in range(num_shards)]
    for doc_id, source in docs:
        routed[shard_for_id(doc_id, num_shards)].append((doc_id, source))
    return routed


def _ingest_shard(builder: PackBuilder,
                  shard_docs: list[tuple[str, dict]],
                  mappings: Mappings) -> None:
    """Parse + batch-analyze one shard's docs into its builder (the
    vectorized dispatch inside tags itself `build.analyze`; the host
    oracle lane tags the legacy `analyze` stage)."""
    parsed = [mappings.parse_document(source) for _, source in shard_docs]
    builder.add_documents_batch(
        parsed, doc_ids=[doc_id for doc_id, _ in shard_docs])


def build_stacked_pack_routed(
    routed: list[list[tuple[str, dict]]], mappings: Mappings,
    dense_min_df: int | None = None,
) -> StackedPack:
    from ..analysis.batched import analyze_mode, analyze_overlap_enabled
    from ..monitoring.refresh_profile import active_collector, refresh_stage

    builders = [PackBuilder(mappings) for _ in range(len(routed))]
    # analyze stays a named collector stage (the batch dispatch nested
    # inside charges build.analyze; parse + residual stay in `analyze`)
    overlap = (len(builders) > 1 and analyze_overlap_enabled()
               and analyze_mode() != "host")
    packs: list = []
    if not overlap:
        with refresh_stage("analyze"):
            for b, shard_docs in zip(builders, routed):
                _ingest_shard(b, shard_docs, mappings)
        # per-shard dense tiers disabled: StackedPack builds its own
        # global one (global df decisions + global avgdl), so a local
        # tier would only burn build time and host RAM
        packs = [b.build(dense_min_df=1 << 62) for b in builders]
    else:
        # depth-1 double buffer (the C3/serving pattern applied to
        # ingest): a worker thread analyzes shard k+1 while the main
        # thread builds shard k — the builds release the GIL in the
        # native accumulator / XLA, so analyze(k+1) ∥ build(k) is real
        # wall-clock overlap. Worker time can't charge the flat-sum
        # collector (sum(stages) == wall is per-thread by construction);
        # it lands as an async span (note_span) so the RefreshProfile
        # timestamps show the overlap and the cumulative stage
        # accounting still sees every analyze millisecond.
        coll = active_collector()

        def _spawn(s: int):
            box: list[BaseException] = []

            def _run():
                t0 = time.perf_counter()
                try:
                    _ingest_shard(builders[s], routed[s], mappings)
                except BaseException as ex:  # noqa: BLE001 - rethrown on join
                    box.append(ex)
                finally:
                    if coll is not None:
                        coll.note_span("build.analyze", t0,
                                       time.perf_counter())

            th = threading.Thread(target=_run, daemon=True,
                                  name=f"analyze-shard-{s}")
            th.start()
            return th, box

        with refresh_stage("analyze"):
            _ingest_shard(builders[0], routed[0], mappings)
        pending = None
        try:
            for s in range(len(builders)):
                pending = _spawn(s + 1) if s + 1 < len(builders) else None
                packs.append(builders[s].build(dense_min_df=1 << 62))
                if pending is not None:
                    th, box = pending
                    th.join()
                    pending = None
                    if box:
                        raise box[0]
        finally:
            if pending is not None:
                pending[0].join()
    for p, shard_docs in zip(packs, routed):
        # source references (shared with EsIndex.shard_docs) for host-side
        # per-object matching (nested queries, query/nested.py)
        p.doc_sources = [src for _, src in shard_docs]
    with refresh_stage("stack"):
        return StackedPack(packs, mappings, dense_min_df=dense_min_df)


def build_stacked_pack(
    docs: list[tuple[str, dict]], mappings: Mappings, num_shards: int,
    dense_min_df: int | None = None,
) -> StackedPack:
    """Route (id, source) docs to shards (Murmur3 like the reference) and
    pack each shard."""
    return build_stacked_pack_routed(
        route_docs(docs, num_shards), mappings, dense_min_df=dense_min_df)
