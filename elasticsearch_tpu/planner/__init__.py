"""Adaptive execution planner (PR 18): cost-model-driven arm selection.

The closed loop over everything the runtime already measures: predicted
wall time per eligible arm = analytic cost (monitoring/costmodel) ÷ that
kernel's MEASURED achieved-roofline EMA (fed by every `time_kernel`
observation), argmin wins, and the predicted-vs-actual residual comes
back as a drift gauge — mispredictions are observable, the PR-12
discipline. See planner/core.py for the subsystem.
"""

from .core import (  # noqa: F401
    ARM_SITES,
    ExecutionPlanner,
    execution_planner,
    reset_for_tests,
)
