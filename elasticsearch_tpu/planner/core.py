"""The adaptive execution planner (PR 18, ROADMAP item 4).

One process-wide ``ExecutionPlanner`` closes the loop between the
analytic cost model and the measured runtime:

- **Predict**: an arm's wall time is its kernel's ideal roofline time
  (max of flops/peak_flops, bytes/peak_bw, ici_bytes/peak_ici from the
  PR-5 cost model) divided by that kernel's *measured* achieved-roofline
  EMA. The EMA is fed by every `telemetry.time_kernel` exit (the same
  utilization record that drives the MFU/bw histograms), so the
  predictor prices each arm at the efficiency this host actually
  achieves — not the datasheet peak.

- **Choose**: every arm dispatch site routes through
  ``choose_arm(site, candidates)`` with its eligible arms in today's
  static priority order (fused > impact > exact). Cold state (any
  candidate unpredictable) falls back to the FIRST candidate — byte-
  identical to the pre-planner routing; warm state picks the argmin of
  the predictions. The registry of sites/arms/kernels (``ARM_SITES``)
  is lint-enforced (tests/test_planner.py): no orphan env-gate routing.

- **Feed back**: at observe time the planner recomputes the prediction
  it would have made for the dispatch (pre-update state) and exports
  the relative residual (actual − predicted) / predicted as the
  ``es.planner.residual`` histogram + per-kernel gauge, the PR-12 drift
  discipline; `slo.planner.residual` turns the worst kernel's |residual|
  EMA into a standing SLO floor.

- **Reprice**: the PR-14 degradation pins are subsumed — a device OOM
  reprices the fused (and, for the retry, impact) arm to ∞ (filtered
  from the candidate list) instead of pinning `ES_TPU_FUSED=0` env
  vars; the repricing lifts when the recovery ramp finishes.

- **Knobs**: the same predictor advises `knn.nprobe` from a latency
  target (`planner.knn.target_ms`), the serving wave close (effective
  max_wave / coalesce window from queue depth vs the measured drain and
  arrival EMAs), and request-cache admission by predicted recompute
  cost (`planner.cache.min_recompute_us`). Every knob is clamped to its
  static bounds and passes through untouched when cold or disabled.

State is deliberately tiny (dicts of floats under one lock): a decision
is pure dict/float arithmetic and stays well under the 100 µs budget.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

# site -> arm -> the kernel whose cost model prices that arm. Keys are
# the literal choose_arm(...) site names at the dispatch call sites —
# the tier-1 lint (tests/test_planner.py) enforces the bijection, the
# same discipline KERNEL_COSTS gets from tests/test_monitoring.py.
# `sharded.msearch_merged` prices impact and exact through the same
# one-program kernel (sharded.allgather_topk) with different tier
# fields; their efficiency EMA is shared — documented, not hidden.
ARM_SITES: dict[str, dict[str, str]] = {
    "batched.msearch": {
        "fused": "fused.pallas_scan",
        "impact": "sparse.impact_sum",
        "exact": "batched.disjunction",
    },
    "sharded.msearch_merged": {
        "fused": "sharded.fused_allgather_topk",
        "impact": "sharded.allgather_topk",
        "exact": "sharded.allgather_topk",
    },
    "sharded.msearch_partials": {
        "fused": "sharded.fused_pipeline",
        "impact": "sharded.impact_disjunction",
        "exact": "sharded.exact_disjunction",
    },
}

_DEFAULTS = {
    "enabled": True,
    "alpha": 0.2,            # planner.ema.alpha
    "knn_target_ms": 0.0,    # planner.knn.target_ms (0 = advisory off)
    "cache_min_recompute_us": 0.0,  # planner.cache.min_recompute_us
}


class ExecutionPlanner:
    """Per-process planner state: kernel efficiency EMAs, residual
    tracking, arm repricing, decision accounting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cfg = dict(_DEFAULTS)
        # kernel -> EMA of achieved roofline fraction (max of mfu /
        # bw_util / ici_util). Seeded lazily from the FIRST time_kernel
        # observation (the normalization basis is the KERNEL_COSTS
        # device peaks); an empty entry means COLD -> static fallback.
        self._eff: dict[str, float] = {}
        self._obs: dict[str, int] = {}
        # kernel -> EMA of posting rows per query, harvested from
        # observed dispatch fields: lets rows-dependent cost fns
        # (impact gather) price future dispatches before planning.
        self._rows_per_q: dict[str, float] = {}
        # kernel -> residual state (last, EMA of |residual|, count)
        self._residual: dict[str, dict] = {}
        # arm -> active repricing count (scoped ∞-cost contexts) and
        # arm -> {key: predicate} standing repricers (degradation state)
        self._repriced_scoped: dict[str, int] = {}
        self._repricers: dict[str, dict] = {}
        self._decisions: dict[str, int] = {}
        self._modes = {"model": 0, "static": 0, "repriced": 0}
        self._knobs = {"nprobe_adjustments": 0, "wave_adjustments": 0,
                       "cache_rejections": 0, "cache_admissions": 0}

    # -- configuration ------------------------------------------------------

    def configure(self, **kw) -> None:
        with self._lock:
            for key, val in kw.items():
                if key in self._cfg and val is not None:
                    self._cfg[key] = val

    @property
    def enabled(self) -> bool:
        if os.environ.get("ES_TPU_PLANNER", "1") == "0":
            return False
        return bool(self._cfg["enabled"])

    # -- the measurement feed (telemetry.time_kernel exit hook) -------------

    def observe(self, kernel: str, fields: dict, seconds: float,
                util: dict) -> None:
        """Fold one timed dispatch into the kernel's efficiency EMA and
        export the predicted-vs-actual residual. Never raises — the
        planner is routing advice, not the serving path."""
        achieved = max(util.get("mfu", 0.0), util.get("bw_util", 0.0),
                       util.get("ici_util", 0.0))
        if achieved <= 0 or seconds <= 0:
            return
        from ..telemetry import metrics

        with self._lock:
            # the prediction this dispatch WOULD have gotten (pre-update
            # EMA state) — the residual convention of BENCH_NOTES r22
            predicted_s = self._predict_seconds_locked(kernel, fields)
            alpha = float(self._cfg["alpha"])
            prev = self._eff.get(kernel)
            self._eff[kernel] = (achieved if prev is None
                                 else (1 - alpha) * prev + alpha * achieved)
            self._obs[kernel] = self._obs.get(kernel, 0) + 1
            rows, q = fields.get("rows"), fields.get("queries")
            if rows and q:
                rq = float(rows) / max(int(q), 1)
                prev_rq = self._rows_per_q.get(kernel)
                self._rows_per_q[kernel] = (
                    rq if prev_rq is None
                    else (1 - alpha) * prev_rq + alpha * rq)
            residual = None
            if predicted_s is not None and predicted_s > 0:
                residual = (seconds - predicted_s) / predicted_s
                st = self._residual.setdefault(
                    kernel, {"last": 0.0, "abs_ema": None, "count": 0})
                st["last"] = residual
                st["abs_ema"] = (
                    abs(residual) if st["abs_ema"] is None
                    else (1 - alpha) * st["abs_ema"] + alpha * abs(residual))
                st["count"] += 1
        if residual is not None:
            metrics.histogram_record("es.planner.residual", residual)
            metrics.gauge_set(f"es.planner.residual.{kernel}",
                              round(residual, 6))

    def observe_wall(self, kernel: str, fields: dict,
                     seconds: float) -> None:
        """Serving-path feed: on the wave route the arm kernels' own
        `time_kernel` exits fold into the ONE combined fetch
        (`serving.wave_program`), so no utilization record exists for
        the routed arm itself. Per-wave decision attribution
        (serving/service._record_flight) reports the arm's apportioned
        wall here and the achieved-roofline fraction is recovered from
        the analytic ideal — closing the same loop the solo paths close
        directly in `time_kernel`."""
        if seconds <= 0:
            return
        with self._lock:
            ideal = self._ideal_seconds(kernel, fields)
        if ideal is None or ideal <= 0:
            return
        self.observe(kernel, fields, seconds,
                     {"mfu": min(ideal / seconds, 1.0)})

    # -- prediction ---------------------------------------------------------

    def _ideal_seconds(self, kernel: str, fields: dict) -> float | None:
        """Roofline-ideal wall of one dispatch from the analytic cost
        model: max over the compute / HBM / ICI terms."""
        from ..monitoring.costmodel import device_peaks, ici_peak, kernel_cost

        cost = kernel_cost(kernel, fields)
        if cost is None and "rows" not in fields:
            # rows-dependent cost fn before planning: price with the
            # measured rows-per-query EMA when one exists
            rq = self._rows_per_q.get(kernel)
            q = fields.get("queries")
            if rq is not None and q:
                cost = kernel_cost(
                    kernel, {**fields, "rows": int(rq * int(q))})
        if cost is None:
            return None
        peak_f, peak_b, _kind = device_peaks()
        t = max(cost["flops"] / peak_f, cost["bytes"] / peak_b)
        if cost.get("ici_bytes"):
            t = max(t, cost["ici_bytes"] / ici_peak())
        return t

    def _predict_seconds_locked(self, kernel: str,
                                fields: dict) -> float | None:
        eff = self._eff.get(kernel)
        if eff is None or eff <= 0:
            return None
        t = self._ideal_seconds(kernel, fields)
        if t is None:
            return None
        return t / eff

    def predict_ms(self, kernel: str, fields: dict) -> float | None:
        """Predicted wall ms of one dispatch, or None while cold."""
        with self._lock:
            sec = self._predict_seconds_locked(kernel, fields)
        return None if sec is None else sec * 1000.0

    # -- repricing (subsumes the PR-14 degradation pins) --------------------

    def repriced(self, arm: str) -> bool:
        """An arm priced at ∞: filtered from every candidate list."""
        with self._lock:
            if self._repriced_scoped.get(arm, 0) > 0:
                return True
            preds = list(self._repricers.get(arm, {}).values())
        for fn in preds:
            try:
                if fn():
                    return True
            except Exception:  # noqa: BLE001 - a dead predicate never pins
                continue
        return False

    def repriced_arms(self) -> list[str]:
        arms = set(self._repriced_scoped) | set(self._repricers)
        return sorted(a for a in arms if self.repriced(a))

    @contextmanager
    def reprice(self, arms, reason: str = ""):
        """Scope in which `arms` cost ∞ (the device-OOM retry runs the
        exact arm through ordinary candidate filtering, not env pins)."""
        from ..telemetry import metrics

        arms = tuple(arms)
        with self._lock:
            for a in arms:
                self._repriced_scoped[a] = \
                    self._repriced_scoped.get(a, 0) + 1
        for a in arms:
            metrics.counter_inc(f"es.planner.repriced.{a}")
        try:
            yield
        finally:
            with self._lock:
                for a in arms:
                    n = self._repriced_scoped.get(a, 1) - 1
                    if n <= 0:
                        self._repriced_scoped.pop(a, None)
                    else:
                        self._repriced_scoped[a] = n

    def add_repricer(self, arm: str, key, predicate) -> None:
        """Standing repricer (e.g. DeviceDegradation.degraded): the arm
        stays at ∞ for as long as the predicate holds."""
        with self._lock:
            self._repricers.setdefault(arm, {})[key] = predicate

    def remove_repricer(self, arm: str, key) -> None:
        with self._lock:
            self._repricers.get(arm, {}).pop(key, None)

    # -- arm choice ---------------------------------------------------------

    def choose_arm(self, site: str, candidates) -> str:
        """Pick one arm for a dispatch. `candidates` is a list of
        (arm, kernel, fields) in TODAY'S static priority order; the
        last entry must be the always-correct exact arm. Returns the
        arm name. Cold (any surviving candidate unpredictable) ->
        static fallback = first survivor, so an empty-EMA planner is
        byte-identical to the pre-planner routing."""
        t0 = time.perf_counter()
        alive = [c for c in candidates if not self.repriced(c[0])]
        mode = "static"
        if not alive:
            # everything repriced: the last candidate is the smallest-
            # footprint correct arm (the PR-14 stage-3 contract)
            alive = [candidates[-1]]
            mode = "repriced"
        chosen = alive[0]
        predicted: dict[str, float] = {}
        if self.enabled and len(alive) > 1:
            preds = []
            with self._lock:
                for arm, kernel, fields in alive:
                    preds.append(
                        self._predict_seconds_locked(kernel, fields))
            if all(p is not None for p in preds):
                mode = "model"
                best = min(range(len(preds)), key=lambda j: preds[j])
                chosen = alive[best]
            predicted = {alive[j][0]: round(preds[j] * 1000.0, 4)
                         for j in range(len(alive))
                         if preds[j] is not None}
        if len(alive) < len(candidates) and mode == "static":
            mode = "repriced"  # the filtering, not the model, routed this
        decision_us = (time.perf_counter() - t0) * 1e6
        arm = chosen[0]
        with self._lock:
            self._decisions[arm] = self._decisions.get(arm, 0) + 1
            self._modes[mode] = self._modes.get(mode, 0) + 1
        from ..telemetry import metrics, profile_event

        metrics.counter_inc(f"es.planner.decisions.{arm}")
        metrics.histogram_record("es.planner.decision_us", decision_us)
        # `priced_kernel`, not `kernel`: profile-event consumers treat a
        # `kernel` key as a utilization record (kind == "kernel")
        profile_event("planner", site=site, arm=arm, mode=mode,
                      priced_kernel=chosen[1], fields=dict(chosen[2]),
                      predicted_ms=predicted,
                      decision_us=round(decision_us, 2))
        return arm

    # -- knobs --------------------------------------------------------------

    def advise_nprobe(self, default_nprobe: int, nlist: int,
                      fields: dict) -> int:
        """Largest nprobe in [1, nlist] whose predicted ann.gather_scan
        wall stays under planner.knn.target_ms (binary search over the
        monotone cost). Cold / disabled / no target -> the default
        (coverage-heuristic) value, untouched."""
        target_ms = float(self._cfg["knn_target_ms"])
        if not self.enabled or target_ms <= 0:
            return default_nprobe
        kernel = "ann.gather_scan"
        with self._lock:
            if self._eff.get(kernel) is None:
                return default_nprobe
            lo, hi = 1, max(int(nlist), 1)
            best = 1
            while lo <= hi:
                mid = (lo + hi) // 2
                sec = self._predict_seconds_locked(
                    kernel, {**fields, "nprobe": mid})
                if sec is None:
                    return default_nprobe
                if sec * 1000.0 <= target_ms:
                    best = mid
                    lo = mid + 1
                else:
                    hi = mid - 1
            advised = max(1, min(best, int(nlist)))
            if advised != default_nprobe:
                self._knobs["nprobe_adjustments"] += 1
        return advised

    def advise_wave_close(self, max_wave: int, max_wait_s: float,
                          depth: int, drain_ms_ema: float | None,
                          arrivals_per_s_ema: float | None):
        """Effective (wave size, coalesce window) for one wave close.
        Warm: holding the wave open is only worth the arrivals one
        drain period is expected to deliver — the wave target becomes
        depth + E[arrivals during drain] (clamped to [1, max_wave]) and
        the window becomes the time to accumulate that target (clamped
        to [0, max_wait_s]). Cold or disabled: the configured values,
        untouched (byte parity with the static scheduler)."""
        if (not self.enabled or not drain_ms_ema or drain_ms_ema <= 0
                or not arrivals_per_s_ema or arrivals_per_s_ema <= 0):
            return max_wave, max_wait_s
        expect = arrivals_per_s_ema * (drain_ms_ema / 1000.0)
        eff_wave = int(min(max_wave, max(1, depth + expect)))
        need = max(eff_wave - depth, 0)
        eff_wait = min(max_wait_s,
                       max(0.0, need / arrivals_per_s_ema))
        if eff_wave != max_wave or eff_wait != max_wait_s:
            with self._lock:
                self._knobs["wave_adjustments"] += 1
        return eff_wave, eff_wait

    def admit_cache(self, recompute_ms: float | None) -> bool:
        """Request-cache admission by predicted recompute cost: entries
        cheaper to recompute than planner.cache.min_recompute_us are
        not worth their residency. Floor 0 (default) admits everything
        — parity with the pre-planner cache."""
        floor_us = float(self._cfg["cache_min_recompute_us"])
        if not self.enabled or floor_us <= 0 or recompute_ms is None:
            return True
        ok = recompute_ms * 1000.0 >= floor_us
        with self._lock:
            self._knobs["cache_admissions" if ok else
                        "cache_rejections"] += 1
        return ok

    # -- introspection ------------------------------------------------------

    def worst_kernel(self) -> tuple[str | None, float | None]:
        """(kernel, |residual| EMA) of the worst-predicted kernel."""
        with self._lock:
            worst, worst_val = None, None
            for k, st in self._residual.items():
                v = st.get("abs_ema")
                if v is not None and (worst_val is None or v > worst_val):
                    worst, worst_val = k, v
        return worst, worst_val

    def stats(self) -> dict:
        worst, worst_val = self.worst_kernel()
        with self._lock:
            kernels = {
                k: {
                    "efficiency_ema": round(self._eff[k], 6),
                    "observations": self._obs.get(k, 0),
                    **({"residual_last":
                        round(self._residual[k]["last"], 6),
                        "residual_abs_ema":
                        round(self._residual[k]["abs_ema"], 6),
                        "predictions": self._residual[k]["count"]}
                       if k in self._residual
                       and self._residual[k]["abs_ema"] is not None
                       else {}),
                }
                for k in sorted(self._eff)
            }
            out = {
                "enabled": self.enabled,
                "config": {
                    "ema_alpha": self._cfg["alpha"],
                    "knn_target_ms": self._cfg["knn_target_ms"],
                    "cache_min_recompute_us":
                        self._cfg["cache_min_recompute_us"],
                },
                "decisions": dict(sorted(self._decisions.items())),
                "decision_modes": dict(self._modes),
                "knobs": dict(self._knobs),
                "kernels": kernels,
                "sites": sorted(ARM_SITES),
            }
        out["repriced"] = self.repriced_arms()
        out["worst_kernel"] = worst
        out["worst_abs_residual_ema"] = (
            round(worst_val, 6) if worst_val is not None else None)
        return out

    def reset(self) -> None:
        with self._lock:
            self._cfg = dict(_DEFAULTS)
            self._eff.clear()
            self._obs.clear()
            self._rows_per_q.clear()
            self._residual.clear()
            self._repriced_scoped.clear()
            self._repricers.clear()
            self._decisions.clear()
            self._modes = {"model": 0, "static": 0, "repriced": 0}
            for k in self._knobs:
                self._knobs[k] = 0


_singleton: ExecutionPlanner | None = None
_singleton_lock = threading.Lock()


def execution_planner() -> ExecutionPlanner:
    """The process-wide planner every dispatch site consults. An Engine
    binds its planner.* settings consumers onto it at construction."""
    global _singleton
    if _singleton is None:
        with _singleton_lock:
            if _singleton is None:
                _singleton = ExecutionPlanner()
    return _singleton


def reset_for_tests() -> None:
    execution_planner().reset()
