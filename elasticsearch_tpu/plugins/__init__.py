"""Plugin SPI: extension points for queries, aggregations, ingest
processors, analyzers, and REST handlers.

The reference loads plugins from class-path services and asks each for its
extensions (reference behavior: plugins/PluginsService.java:69 loading;
plugins/SearchPlugin.java:64 — getQueries :126, getAggregations :133;
IngestPlugin#getProcessors; AnalysisPlugin; ActionPlugin#getRestHandlers).
Here a plugin is a Python class implementing the same getter surface;
plugins are registered programmatically or loaded from a
"module.path:ClassName" spec (the entry-point analog of
META-INF/services).

Extension lookups are consulted by the query DSL, the aggregation parser,
the ingest pipeline builder, the analysis registry, and the REST app at
the same points the reference consults its plugin-built registries
(SearchModule, IngestService.processorFactories, RestController).
"""

from __future__ import annotations

import importlib

from ..utils.errors import IllegalArgumentError


class Plugin:
    """Base class. Override any subset of the extension getters.

    name/description surface in GET _cat/plugins and _nodes/plugins."""

    name = "unnamed"
    description = ""

    def get_queries(self) -> dict:
        """{query_name: parser(body, mappings) -> QueryNode}"""
        return {}

    def get_aggregations(self) -> dict:
        """{agg_name: parser(name, body, sub_nodes, mappings) -> AggNode}"""
        return {}

    def get_processors(self) -> dict:
        """{processor_type: ProcessorClass}"""
        return {}

    def get_analyzers(self) -> dict:
        """{analyzer_name: Analyzer instance}"""
        return {}

    def get_rest_handlers(self) -> list:
        """[(method, path, async handler(request) -> aiohttp response)]"""
        return []


class PluginRegistry:
    def __init__(self):
        self.plugins: list[Plugin] = []
        self.queries: dict[str, object] = {}
        self.aggregations: dict[str, object] = {}
        self.processors: dict[str, type] = {}
        self.analyzers: dict[str, object] = {}
        self.rest_handlers: list = []

    def register(self, plugin: Plugin) -> None:
        for reg, got in (
            (self.queries, plugin.get_queries()),
            (self.aggregations, plugin.get_aggregations()),
            (self.processors, plugin.get_processors()),
            (self.analyzers, plugin.get_analyzers()),
        ):
            for key, val in got.items():
                if key in reg:
                    raise IllegalArgumentError(
                        f"extension [{key}] already registered "
                        f"(plugin [{plugin.name}])"
                    )
                reg[key] = val
        self.rest_handlers.extend(plugin.get_rest_handlers())
        self.plugins.append(plugin)

    def load_spec(self, spec: str) -> Plugin:
        """Load "module.path:ClassName", instantiate, register."""
        mod_name, _, cls_name = spec.partition(":")
        if not cls_name:
            raise IllegalArgumentError(
                f"plugin spec [{spec}] must be module:ClassName")
        try:
            cls = getattr(importlib.import_module(mod_name), cls_name)
        except (ImportError, AttributeError) as e:
            raise IllegalArgumentError(f"cannot load plugin [{spec}]: {e}")
        plugin = cls()
        self.register(plugin)
        return plugin

    def info(self) -> list[dict]:
        return [
            {"name": p.name, "description": p.description,
             "classname": type(p).__qualname__}
            for p in self.plugins
        ]


# node-level registry (the PluginsService singleton analog); tests and
# embedders may also build private registries and swap them in
registry = PluginRegistry()
