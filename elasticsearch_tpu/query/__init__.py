from .dsl import parse_query
from .executor import ShardSearcher

__all__ = ["parse_query", "ShardSearcher"]
