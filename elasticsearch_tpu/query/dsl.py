"""JSON Query DSL -> plan nodes.

Parity target: the reference's query parsers (reference behavior:
index/query/*QueryBuilder.java `fromXContent`, registered in
search/SearchModule.java). Field-type-aware desugaring happens here:

- `match` on text  -> bool-should (or must for operator=and) of TermNodes,
  terms produced by the field's search analyzer — exactly how
  MatchQueryBuilder builds a BooleanQuery of TermQuerys.
- `term`/`terms` on numeric/date/bool fields -> docvalue equality (the
  reference uses point queries; same result set, constant score).
- `multi_match` (best_fields) -> DisMax over per-field match queries.
"""

from __future__ import annotations

from ..index.mappings import (
    Mappings,
    TEXT_TYPES,
    KEYWORD_TYPES,
    INT_TYPES,
    FLOAT_TYPES,
    DATE_TYPES,
    BOOL_TYPES,
    parse_date_to_millis,
)
from ..utils.errors import QueryParsingError
from .nodes import (
    QueryNode,
    TermNode,
    MatchAllNode,
    MatchNoneNode,
    RangeNode,
    TermsNode,
    ExistsNode,
    ConstantScoreNode,
    DisMaxNode,
    BoolNode,
    ExpandedTermsNode,
    PhraseNode,
    KnnNode,
)


def parse_query(q: dict | None, mappings: Mappings) -> QueryNode:
    if q is None:
        return MatchAllNode()
    if not isinstance(q, dict) or len(q) != 1:
        raise QueryParsingError(f"query must be an object with exactly one key, got {q!r}")
    (kind, body), = q.items()
    parser = _PARSERS.get(kind)
    if parser is None:
        from ..plugins import registry

        parser = registry.queries.get(kind)
    if parser is None:
        raise QueryParsingError(f"unknown query [{kind}]")
    return parser(body, mappings)


def _field_type(mappings: Mappings, fld: str) -> str | None:
    if fld == "_tsid":
        # the reference's TimeSeriesIdFieldMapper refuses queries: _tsid
        # exists for aggregations/fetch, not search (tsdb/40_search.yml)
        from ..utils.errors import IllegalArgumentError

        raise IllegalArgumentError("[_tsid] is not searchable")
    ft = mappings.fields.get(fld)
    return ft.type if ft else None


def _coerce_for_field(mappings: Mappings, fld: str, value):
    """-> (kind, coerced_value) where kind selects the docvalue column type."""
    from ..index.mappings import DATE_NANOS_TYPES, IP_TYPES, parse_date_to_nanos

    t = _field_type(mappings, fld)
    if t in DATE_TYPES:
        ft = mappings.fields.get(fld)
        if ft is not None and ft.format:
            from ..index.mappings import parse_date_with_formats

            return "int", parse_date_with_formats(value, ft.format)
        return "int", parse_date_to_millis(value)
    if t in DATE_NANOS_TYPES:
        return "int", parse_date_to_nanos(value)
    if t in IP_TYPES:
        return "ip", str(value)
    if t in BOOL_TYPES:
        if isinstance(value, str):
            value = value == "true"
        return "int", int(bool(value))
    if t in INT_TYPES:
        return "int", int(value)
    if t in FLOAT_TYPES:
        return "float", float(value)
    return "ord", str(value)


def _ip_value_node(fld: str, value, boost: float):
    """An ip term: exact address -> postings term on the normalized form;
    CIDR block -> ordinal range over the address-sorted dictionary
    (reference: IpFieldMapper termQuery -> InetAddressPoint exact/prefix)."""
    import ipaddress

    from ..utils.errors import QueryParsingError

    s = str(value)
    try:
        if "/" in s:
            net = ipaddress.ip_network(s, strict=False)
            return _IpRangeNode(
                fld, str(net.network_address), str(net.broadcast_address),
                True, True, boost,
            )
        return TermNode(fld, str(ipaddress.ip_address(s)), boost=boost)
    except ValueError as e:
        raise QueryParsingError(f"'{s}' is not an IP string literal: {e}")


def _parse_match(body, mappings):
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[match] query expects {field: ...}")
    (fld, spec), = body.items()
    if isinstance(spec, dict):
        text = spec.get("query")
        operator = spec.get("operator", "or")
        boost = float(spec.get("boost", 1.0))
        msm = spec.get("minimum_should_match")
    else:
        text, operator, boost, msm = spec, "or", 1.0, None
    if text is None:
        raise QueryParsingError("[match] requires [query]")
    t = _field_type(mappings, fld)
    if t is not None and t not in TEXT_TYPES and t not in KEYWORD_TYPES:
        # match on numeric/date/bool degrades to equality, like ES
        kind, v = _coerce_for_field(mappings, fld, text)
        if kind == "ip":
            return _ip_value_node(fld, v, boost)
        return RangeNode(fld, v, v, kind=kind, boost=boost)
    ft = mappings.fields.get(fld)
    if ft is not None and ft.type in KEYWORD_TYPES:
        terms = [str(text)]
    else:
        analyzer = ft.get_search_analyzer() if ft else None
        if analyzer is None:
            from ..analysis import get_analyzer

            analyzer = get_analyzer("standard")
        terms = analyzer.terms(str(text))
    if not terms:
        return MatchNoneNode()
    leaves = [TermNode(fld, term) for term in terms]
    if len(leaves) == 1:
        leaves[0].boost = boost
        return leaves[0]
    if operator == "and":
        return BoolNode(must=leaves, boost=boost)
    return BoolNode(should=leaves, boost=boost, minimum_should_match=int(msm) if msm else None)


def _parse_multi_match(body, mappings):
    if not isinstance(body, dict):
        raise QueryParsingError("[multi_match] expects an object")
    text = body.get("query")
    fields = body.get("fields") or []
    mm_type = body.get("type", "best_fields")
    tie = float(body.get("tie_breaker", 0.0))
    boost = float(body.get("boost", 1.0))
    if text is None or not fields:
        raise QueryParsingError("[multi_match] requires [query] and [fields]")
    if mm_type not in ("best_fields", "most_fields", "phrase", "bool_prefix"):
        raise QueryParsingError(f"[multi_match] type [{mm_type}] is not supported")
    children = []
    for f in fields:
        fboost = 1.0
        if "^" in f:
            f, fb = f.split("^", 1)
            fboost = float(fb)
        if mm_type == "bool_prefix":
            child = _parse_match_bool_prefix(
                {f: {"query": text, "boost": fboost}}, mappings
            )
            children.append(child)
            continue
        if mm_type == "phrase":
            child = _parse_match_phrase(
                {f: {"query": text, "boost": fboost}}, mappings
            )
        else:
            child = _parse_match({f: {"query": text, "boost": fboost}}, mappings)
        children.append(child)
    if mm_type == "most_fields":
        return BoolNode(should=children, boost=boost)
    return DisMaxNode(children=children, tie_breaker=tie, boost=boost)


def _parse_match_phrase(body, mappings):
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[match_phrase] query expects {field: ...}")
    (fld, spec), = body.items()
    if not isinstance(spec, dict):
        spec = {"query": spec}
    if "query" not in spec:
        raise QueryParsingError("[match_phrase] requires [query]")
    text = str(spec["query"])
    boost = float(spec.get("boost", 1.0))
    slop = int(spec.get("slop", 0))
    ft = mappings.fields.get(fld)
    if ft is None or ft.type in KEYWORD_TYPES:
        return TermNode(fld, text, boost=boost)
    if ft.type not in TEXT_TYPES:
        kind, v = _coerce_for_field(mappings, fld, text)
        return RangeNode(fld, v, v, kind=kind, boost=boost)
    analyzer = ft.get_search_analyzer()
    if analyzer is None:
        from ..analysis import get_analyzer

        analyzer = get_analyzer("standard")
    toks = analyzer.analyze(text)
    if not toks:
        return MatchNoneNode()
    if len(toks) == 1:
        return TermNode(fld, toks[0].term, boost=boost)
    return PhraseNode(
        fld, [(t.term, t.position) for t in toks], boost=boost, slop=slop
    )


def _parse_match_phrase_prefix(body, mappings):
    """match_phrase_prefix: phrase whose last term is a prefix (reference
    behavior: MatchPhrasePrefixQueryBuilder — last position expands to up to
    max_expansions terms; here the expansion happens against the field's
    term dictionary at prepare time via a dis_max of full phrases)."""
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[match_phrase_prefix] query expects {field: ...}")
    (fld, spec), = body.items()
    if not isinstance(spec, dict):
        spec = {"query": spec}
    text = str(spec.get("query", ""))
    boost = float(spec.get("boost", 1.0))
    max_exp = int(spec.get("max_expansions", 50))
    ft = mappings.fields.get(fld)
    if ft is None or ft.type not in TEXT_TYPES:
        return _parse_prefix({fld: {"value": text.lower()}}, mappings)
    analyzer = ft.get_search_analyzer()
    toks = analyzer.analyze(text)
    if not toks:
        return MatchNoneNode()
    if len(toks) == 1:
        return _parse_prefix({fld: {"value": toks[0].term, "boost": boost}}, mappings)
    from .prefix_phrase import PhrasePrefixNode

    return PhrasePrefixNode(
        fld=fld,
        terms=[(t.term, t.position) for t in toks[:-1]],
        prefix=toks[-1].term,
        prefix_position=toks[-1].position,
        max_expansions=max_exp,
        boost=boost,
    )


def _parse_match_bool_prefix(body, mappings):
    """match_bool_prefix: bool-should of terms + a prefix on the last
    (reference behavior: MatchBoolPrefixQueryBuilder)."""
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[match_bool_prefix] query expects {field: ...}")
    (fld, spec), = body.items()
    if not isinstance(spec, dict):
        spec = {"query": spec}
    text = str(spec.get("query", ""))
    boost = float(spec.get("boost", 1.0))
    ft = mappings.fields.get(fld)
    analyzer = ft.get_search_analyzer() if ft else None
    if analyzer is None:
        from ..analysis import get_analyzer as _ga

        analyzer = _ga("standard")
    terms = [t.term for t in analyzer.analyze(text)]
    if not terms:
        return MatchNoneNode()
    clauses = [TermNode(fld, t) for t in terms[:-1]]
    clauses.append(_parse_prefix({fld: {"value": terms[-1]}}, mappings))
    return BoolNode(should=clauses, minimum_should_match=1, boost=boost)


def _parse_term(body, mappings):
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[term] query expects {field: value}")
    (fld, spec), = body.items()
    if isinstance(spec, dict):
        value = spec.get("value")
        boost = float(spec.get("boost", 1.0))
    else:
        value, boost = spec, 1.0
    t = _field_type(mappings, fld)
    if fld == "_id":
        # _id lives in the reserved ordinal column, not the inverted index
        # (reference: IdFieldMapper termQuery over the _id metadata field)
        return TermsNode("_id", [str(value)], kind="ord", boost=boost)
    if t in TEXT_TYPES or t in KEYWORD_TYPES or t is None:
        return TermNode(fld, str(value), boost=boost)
    kind, v = _coerce_for_field(mappings, fld, value)
    if kind == "ip":
        return _ip_value_node(fld, v, boost)
    return RangeNode(fld, v, v, kind=kind, boost=boost)


def _parse_terms(body, mappings):
    if not isinstance(body, dict):
        raise QueryParsingError("[terms] expects an object")
    boost = float(body.get("boost", 1.0))
    items = [(f, v) for f, v in body.items() if f != "boost"]
    if len(items) != 1:
        raise QueryParsingError("[terms] query expects a single field")
    fld, values = items[0]
    if not isinstance(values, list):
        raise QueryParsingError("[terms] values must be an array")
    t = _field_type(mappings, fld)
    from ..index.mappings import DATE_NANOS_TYPES, IP_TYPES

    if fld == "_id":
        return TermsNode("_id", [str(v) for v in values], kind="ord", boost=boost)
    if t in INT_TYPES or t in DATE_TYPES or t in DATE_NANOS_TYPES or t in BOOL_TYPES:
        coerced = [_coerce_for_field(mappings, fld, v)[1] for v in values]
        return TermsNode(fld, coerced, kind="int", boost=boost)
    if t in FLOAT_TYPES:
        return TermsNode(fld, [float(v) for v in values], kind="float", boost=boost)
    if t in IP_TYPES:
        return ConstantScoreNode(
            BoolNode(should=[_ip_value_node(fld, v, 1.0) for v in values]),
            boost=boost,
        )
    if t in KEYWORD_TYPES or (t is None):
        return TermsNode(fld, [str(v) for v in values], kind="ord", boost=boost)
    # text field: OR of term queries, constant score
    return ConstantScoreNode(
        BoolNode(should=[TermNode(fld, str(v)) for v in values]), boost=boost
    )


def _parse_range(body, mappings):
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[range] query expects {field: bounds}")
    (fld, spec), = body.items()
    if not isinstance(spec, dict):
        raise QueryParsingError("[range] bounds must be an object")
    boost = float(spec.get("boost", 1.0))
    lo = hi = None
    inc_lo = inc_hi = True
    kind = None
    for op in ("gte", "gt", "lte", "lt"):
        if op in spec:
            k, v = _coerce_for_field(mappings, fld, spec[op])
            kind = kind or k
            if op == "gte":
                lo = v
            elif op == "gt":
                lo, inc_lo = v, False
            elif op == "lte":
                hi = v
            else:
                hi, inc_hi = v, False
    if kind == "ord":
        # keyword ranges resolve against the sorted ordinal dictionary at
        # prepare() time; represented as string bounds here
        return _KeywordRangeNode(fld, spec.get("gte", spec.get("gt")), spec.get("lte", spec.get("lt")), inc_lo, inc_hi, boost)
    if kind == "ip":
        return _IpRangeNode(
            fld, spec.get("gte", spec.get("gt")), spec.get("lte", spec.get("lt")),
            inc_lo, inc_hi, boost,
        )
    return RangeNode(fld, lo, hi, inc_lo, inc_hi, boost=boost, kind=kind or "int")


def _parse_bool(body, mappings):
    if not isinstance(body, dict):
        raise QueryParsingError("[bool] expects an object")

    def clause(name):
        c = body.get(name, [])
        if isinstance(c, dict):
            c = [c]
        return [parse_query(q, mappings) for q in c]

    msm = body.get("minimum_should_match")
    return BoolNode(
        must=clause("must"),
        filter=clause("filter"),
        should=clause("should"),
        must_not=clause("must_not"),
        minimum_should_match=int(msm) if msm is not None else None,
        boost=float(body.get("boost", 1.0)),
    )


def _parse_constant_score(body, mappings):
    if not isinstance(body, dict) or "filter" not in body:
        raise QueryParsingError("[constant_score] requires [filter]")
    return ConstantScoreNode(
        parse_query(body["filter"], mappings), boost=float(body.get("boost", 1.0))
    )


def _parse_dis_max(body, mappings):
    if not isinstance(body, dict) or "queries" not in body:
        raise QueryParsingError("[dis_max] requires [queries]")
    return DisMaxNode(
        children=[parse_query(q, mappings) for q in body["queries"]],
        tie_breaker=float(body.get("tie_breaker", 0.0)),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_exists(body, mappings):
    if not isinstance(body, dict) or "field" not in body:
        raise QueryParsingError("[exists] requires [field]")
    return ExistsNode(body["field"], boost=float(body.get("boost", 1.0)))


def _parse_match_all(body, mappings):
    body = body or {}
    return MatchAllNode(boost=float(body.get("boost", 1.0)))


def _parse_match_none(body, mappings):
    return MatchNoneNode()


def parse_knn(body, mappings) -> KnnNode:
    """knn section/query: {"field", "query_vector", "k", "num_candidates",
    "filter", "boost", "similarity"}."""
    if not isinstance(body, dict) or "field" not in body or "query_vector" not in body:
        raise QueryParsingError("[knn] requires [field] and [query_vector]")
    k = int(body.get("k", 10))
    nc = int(body["num_candidates"]) if body.get("num_candidates") is not None else None
    if k < 1 or (nc is not None and nc < k):
        raise QueryParsingError("[knn] k must be >= 1 and num_candidates >= k")
    filt = body.get("filter")
    fnode = None
    if filt is not None:
        if isinstance(filt, list):
            fnode = BoolNode(filter=[parse_query(q, mappings) for q in filt])
        else:
            fnode = parse_query(filt, mappings)
    nprobe = body.get("nprobe")
    if nprobe is not None and int(nprobe) < 1:
        raise QueryParsingError("[knn] nprobe must be >= 1")
    return KnnNode(
        fld=body["field"],
        qvec=[float(x) for x in body["query_vector"]],
        k=k,
        num_candidates=nc,
        filter_node=fnode,
        boost=float(body.get("boost", 1.0)),
        similarity_threshold=float(body["similarity"]) if body.get("similarity") is not None else None,
        nprobe=int(nprobe) if nprobe is not None else None,
    )


def _single_field_body(kind, body, value_key="value"):
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError(f"[{kind}] query expects {{field: ...}}")
    (fld, spec), = body.items()
    if isinstance(spec, dict):
        if value_key not in spec:
            raise QueryParsingError(f"[{kind}] requires [{value_key}]")
        return fld, spec
    return fld, {value_key: spec}


def _parse_prefix(body, mappings):
    fld, spec = _single_field_body("prefix", body)
    value = str(spec["value"])
    ci = bool(spec.get("case_insensitive", False))
    pre = value.lower() if ci else value
    matcher = (lambda t: t.lower().startswith(pre)) if ci else (lambda t: t.startswith(pre))
    return ExpandedTermsNode(
        kind="prefix", fld=fld, matcher=matcher, boost=float(spec.get("boost", 1.0))
    )


def _wildcard_regex(pattern: str) -> str:
    import re as _re

    out = []
    for ch in pattern:
        if ch == "*":
            out.append(".*")
        elif ch == "?":
            out.append(".")
        else:
            out.append(_re.escape(ch))
    return "".join(out)


def _parse_wildcard(body, mappings):
    import re

    if isinstance(body, dict) and len(body) == 1:
        # legacy body form {field: {"wildcard": "pat*"}} (still accepted by ES)
        (fld0, spec0), = body.items()
        if isinstance(spec0, dict) and "value" not in spec0 and "wildcard" in spec0:
            body = {fld0: {**spec0, "value": spec0["wildcard"]}}
    fld, spec = _single_field_body("wildcard", body)
    pattern = str(spec["value"])
    flags = re.IGNORECASE if spec.get("case_insensitive", False) else 0
    rx = re.compile(_wildcard_regex(pattern), flags)
    return ExpandedTermsNode(
        kind="wildcard",
        fld=fld,
        matcher=lambda t: rx.fullmatch(t) is not None,
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_regexp(body, mappings):
    """Lucene RegExp core operators map onto Python re for the common cases;
    exotic Lucene operators (&, ~ intersection/complement) are unsupported."""
    import re

    fld, spec = _single_field_body("regexp", body)
    pattern = str(spec["value"])
    flags = re.IGNORECASE if spec.get("case_insensitive", False) else 0
    try:
        rx = re.compile(pattern, flags)
    except re.error as e:
        raise QueryParsingError(f"[regexp] invalid pattern [{pattern}]: {e}")
    return ExpandedTermsNode(
        kind="regexp",
        fld=fld,
        matcher=lambda t: rx.fullmatch(t) is not None,
        boost=float(spec.get("boost", 1.0)),
    )


def _edit_distance_within(a: str, b: str, maxd: int, transpositions: bool = True) -> bool:
    """Banded (Damerau-)Levenshtein with early exit at maxd."""
    if abs(len(a) - len(b)) > maxd:
        return False
    if maxd == 0:
        return a == b
    prev2 = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j, cb in enumerate(b, 1):
            cost = 0 if ca == cb else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (
                transpositions
                and prev2 is not None
                and i > 1
                and j > 1
                and ca == b[j - 2]
                and a[i - 2] == cb
            ):
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            row_min = min(row_min, cur[j])
        if row_min > maxd:
            return False
        prev2, prev = prev, cur
    return prev[len(b)] <= maxd


def _fuzzy_max_dist(fuzziness, term: str) -> int:
    s = "AUTO" if fuzziness is None else str(fuzziness).upper()
    if s.startswith("AUTO"):
        low, high = 3, 6
        if s.startswith("AUTO:"):  # AUTO:low,high custom thresholds
            try:
                low, high = (int(x) for x in s[5:].split(","))
            except ValueError:
                raise QueryParsingError(f"failed to parse fuzziness [{fuzziness}]")
        n = len(term)
        return 0 if n < low else (1 if n < high else 2)
    try:
        return int(float(s))
    except ValueError:
        raise QueryParsingError(f"failed to parse fuzziness [{fuzziness}]")


def _parse_fuzzy(body, mappings):
    fld, spec = _single_field_body("fuzzy", body)
    value = str(spec["value"])
    maxd = _fuzzy_max_dist(spec.get("fuzziness"), value)
    prefix_length = int(spec.get("prefix_length", 0))
    transpositions = bool(spec.get("transpositions", True))
    max_expansions = int(spec.get("max_expansions", 50))
    pre = value[:prefix_length]

    def matcher(t):
        if prefix_length and not t.startswith(pre):
            return False
        return _edit_distance_within(t, value, maxd, transpositions)

    return ExpandedTermsNode(
        kind="fuzzy",
        fld=fld,
        matcher=matcher,
        boost=float(spec.get("boost", 1.0)),
        scored=True,
        max_expansions=max_expansions,
    )


def _parse_ids(body, mappings):
    # resolved by the engine layer (docid lookup is host-side state); the
    # parser represents it as a terms query on the reserved _id keyword column
    if not isinstance(body, dict) or "values" not in body:
        raise QueryParsingError("[ids] requires [values]")
    return TermsNode("_id", [str(v) for v in body["values"]], kind="ord")


class _KeywordRangeNode(RangeNode):
    """Range on a keyword-family field: string bounds -> ordinal bounds at
    prepare. Subclasses override _sort_key for dictionaries whose ordinal
    order is not lexicographic (ip)."""

    _sort_key = staticmethod(lambda s: s)
    _key_cache_attr: str | None = None

    def __init__(self, fld, lo_s, hi_s, inc_lo, inc_hi, boost):
        super().__init__(fld, None, None, inc_lo, inc_hi, boost=boost, kind="ord")
        self.lo_s = lo_s
        self.hi_s = hi_s

    def prepare(self, pack):
        import bisect
        import numpy as np

        col = pack.docvalues.get(self.fld)
        terms = col.ord_terms if col is not None and col.ord_terms else []
        keys = terms
        if self._key_cache_attr is not None and col is not None:
            keys = getattr(col, self._key_cache_attr, None)
            if keys is None:
                keys = [self._sort_key(t) for t in terms]
                setattr(col, self._key_cache_attr, keys)
        # map bounds to ordinal space: find tightest ordinal range
        lo_ord, hi_ord = 0, len(terms) - 1
        if self.lo_s is not None:
            k = self._sort_key(str(self.lo_s))
            lo_ord = (
                bisect.bisect_left(keys, k)
                if self.include_lo
                else bisect.bisect_right(keys, k)
            )
        if self.hi_s is not None:
            k = self._sort_key(str(self.hi_s))
            hi_ord = (
                bisect.bisect_right(keys, k) - 1
                if self.include_hi
                else bisect.bisect_left(keys, k) - 1
            )
        params = (
            np.asarray(lo_ord, np.int64),
            np.asarray(hi_ord, np.int64),
            np.asarray(True),
            np.asarray(True),
            np.float32(self.boost),
        )
        return params, ("range", self.fld, "ord", col is None)


class _IpRangeNode(_KeywordRangeNode):
    """Range/CIDR on an ip field: the pack sorts ip ord_terms by address
    value (ip_sort_key), so a CIDR block is a contiguous ordinal interval."""

    from ..index.mappings import ip_sort_key as _ip_key

    _sort_key = staticmethod(_ip_key)
    _key_cache_attr = "_ip_keys"


def _parse_function_score(body, mappings):
    from .script_nodes import parse_function_score

    return parse_function_score(body, mappings, parse_query)


def _parse_script_score(body, mappings):
    from .script_nodes import parse_script_score

    return parse_script_score(body, mappings, parse_query)


def _parse_script_filter(body, mappings):
    from .script_nodes import parse_script_filter

    return parse_script_filter(body, mappings, parse_query)


_PARSERS = {
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "match_bool_prefix": _parse_match_bool_prefix,
    "multi_match": _parse_multi_match,
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "bool": _parse_bool,
    "constant_score": _parse_constant_score,
    "dis_max": _parse_dis_max,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "knn": parse_knn,
    "prefix": _parse_prefix,
    "wildcard": _parse_wildcard,
    "regexp": _parse_regexp,
    "fuzzy": _parse_fuzzy,
    "function_score": _parse_function_score,
    "script_score": _parse_script_score,
    "script": _parse_script_filter,
    "percolate": lambda body, m: _parse_percolate(body, m),
    "more_like_this": lambda body, m: _x("parse_more_like_this", body, m),
    "terms_set": lambda body, m: _x("parse_terms_set", body, m),
    "combined_fields": lambda body, m: _x("parse_combined_fields", body, m),
    "rank_feature": lambda body, m: _x("parse_rank_feature", body, m),
    "distance_feature": lambda body, m: _x("parse_distance_feature", body, m),
    "pinned": lambda body, m: _x("parse_pinned", body, m),
    "wrapper": lambda body, m: _x("parse_wrapper", body, m),
    "intervals": lambda body, m: _parse_intervals_q(body, m),
    "nested": lambda body, m: _parse_nested_q(body, m),
    "geo_bounding_box": lambda body, m: _parse_geo_bbox(body, m),
    "geo_distance": lambda body, m: _parse_geo_dist(body, m),
    "query_string": lambda body, m: _parse_query_string(body, m),
    "simple_query_string": lambda body, m: _parse_simple_query_string(body, m),
}


def _x(fn_name, body, mappings):
    from . import extra

    return getattr(extra, fn_name)(body, mappings)


def _parse_percolate(body, mappings):
    from .percolate import parse_percolate

    return parse_percolate(body, mappings)


def _parse_intervals_q(body, mappings):
    from .intervals import parse_intervals

    return parse_intervals(body, mappings)


def _parse_nested_q(body, mappings):
    from .nested import parse_nested

    return parse_nested(body, mappings)


def _parse_geo_bbox(body, mappings):
    from .geo import parse_geo_bounding_box

    return parse_geo_bounding_box(body, mappings)


def _parse_geo_dist(body, mappings):
    from .geo import parse_geo_distance

    return parse_geo_distance(body, mappings)


def _parse_query_string(body, mappings):
    from .querystring import parse_query_string

    return parse_query(parse_query_string(body, mappings), mappings)


def _parse_simple_query_string(body, mappings):
    from .querystring import parse_simple_query_string

    return parse_query(parse_simple_query_string(body, mappings), mappings)
