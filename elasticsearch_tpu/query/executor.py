"""Shard-level query execution: pack on device + compiled plan cache.

This is the TPU analog of the reference's per-shard query phase (reference
behavior: search/query/QueryPhase.java:61-149 — build collectors, run the
searcher, emit QuerySearchResult of top-k docids/scores + total). One
`ShardSearcher` owns the device-resident pack; each distinct query *shape*
(plan structure + block-bucket sizes + k) compiles once and is cached, so
steady-state queries are a single XLA executable launch with small host->
device parameter transfers (block row lists, idf weights).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..index.pack import ShardPack
from ..ops.scoring import top_k_with_total
from ..utils.errors import IllegalArgumentError
from .dsl import parse_query
from .nodes import ExecContext, QueryNode


def pack_to_device(pack: ShardPack, device=None) -> dict:
    """Ship a host ShardPack to HBM as a flat dict-of-arrays pytree.

    The single-shard twin of `parallel/sharded.stacked_to_device`: the
    host tree is built first, then placed in one tree_map pass — leaf
    PATHS here are the same vocabulary the stacked path's partition-rule
    table (parallel/spmd.PACK_PARTITION_RULES) matches against, so a new
    component added here without a rule fails the stacked upload (and
    tests/test_spmd.py's table lint) instead of silently replicating."""
    from ..utils.jax_env import ensure_x64

    ensure_x64()
    host = _pack_host_tree(pack)
    import jax.tree_util as jtu

    put = (lambda x: jax.device_put(x, device)) if device else jnp.asarray
    return jtu.tree_map(put, host)


def _pack_host_tree(pack: ShardPack) -> dict:
    put = np.asarray
    dev = {
        "post_docids": put(pack.post_docids),
        "post_tfs": put(pack.post_tfs),
        "post_dls": put(pack.post_dls),
        # [N]-aligned doc lengths: phrase scoring normalizes its per-doc
        # phrase frequency elementwise against these
        "norms": {f: put(a) for f, a in pack.norms.items()},
        "text_has": {f: put(a) for f, a in pack.text_present.items()},
        "dv_int": {},
        "dv_float": {},
        "dv_ord": {},
        "dv_mv": {},
        "live": put(pack.live),
        "vec": {},
        "vec_has": {},
    }
    dev["dv_int_ord"] = {}
    for f, col in pack.docvalues.items():
        key = {"int": "dv_int", "float": "dv_float", "ord": "dv_ord"}[col.kind]
        vals = col.values if col.kind != "ord" else col.values.astype(np.int64)
        dev[key][f] = (put(vals), put(col.has_value))
        if col.uniq_ords is not None:
            dev["dv_int_ord"][f] = put(col.uniq_ords)
        if col.mv_pair_docs is not None:
            dev["dv_mv"][f] = (put(col.mv_pair_docs), put(col.mv_pair_ords))
    dev["vec_sq"] = {}
    dev["vec_ann"] = {}
    for f, vc in pack.vectors.items():
        dev["vec"][f] = put(vc.values)
        dev["vec_has"][f] = put(vc.has_value)
        dev["vec_sq"][f] = put((vc.values * vc.values).sum(axis=-1).astype(np.float32))
        if vc.ann is not None:
            from ..ann import ann_to_device

            dev["vec_ann"][f] = ann_to_device(vc.ann, vc.values, put)
    if pack.dense_tfn is not None:
        dev["dense_tfn"] = put(pack.dense_tfn)
    if pack.pos_keys is not None:
        dev["pos_keys"] = put(pack.pos_keys)
    if pack.impact_codes is not None:
        # impact-scored sparse tier (BM25S): quantized per-posting BM25
        # contributions — the gather+sum scoring path's only operand
        # besides post_docids
        dev["impact_codes"] = put(pack.impact_codes)
    return dev


@dataclass
class ShardResult:
    doc_ids: np.ndarray  # [<=k] int32 local docids
    scores: np.ndarray  # [<=k] float32
    total: int
    max_score: float | None
    aggregations: dict | None = None


def _copy_shard_result(res: "ShardResult") -> "ShardResult":
    """Defensive copy for cache store/serve: callers may mutate hit arrays
    or aggregation dicts, and the cached original must stay pristine."""
    import copy as _copy

    return ShardResult(
        res.doc_ids.copy(), res.scores.copy(), res.total, res.max_score,
        _copy.deepcopy(res.aggregations),
    )


def _shard_result_nbytes(res: "ShardResult") -> int:
    import json as _json

    n = int(res.doc_ids.nbytes + res.scores.nbytes) + 256
    if res.aggregations:
        try:
            n += len(_json.dumps(res.aggregations, default=str))
        except Exception:
            n += 4096
    return n


class ShardSearcher:
    def __init__(self, pack: ShardPack, device=None, mappings=None):
        self.pack = pack
        self.mappings = mappings
        self.dev = pack_to_device(pack, device)
        self.ctx = ExecContext(
            num_docs=pack.num_docs,
            avgdl={f: pack.avgdl(f) for f in pack.norms},
            has_norms=frozenset(pack.norms),
        )
        from ..index.pack import BM25_K1, BM25_B

        assert not pack.dense_dict or (self.ctx.k1, self.ctx.b) == (BM25_K1, BM25_B), (
            "dense-tier packs bake default k1/b; rebuild with dense disabled"
        )
        self._cache: dict = {}
        # shard request cache identity: a process-unique token (never
        # reused, unlike id()) + epochs that bump on any in-place mutation
        # of the device-visible pack / scoring stats (cache/request_cache)
        from ..cache import next_searcher_token

        self.cache_token = next_searcher_token()
        self._pack_epoch = 0
        self._stats_epoch = 0

    def cache_scope(self, shard: int = 0):
        """-> (token, epoch) pair keying this searcher's cache entries."""
        return ((self.cache_token, shard),
                (self._pack_epoch, self._stats_epoch))

    def bump_epoch(self, stats: bool = False):
        """Invalidate every cached result of this searcher (call after any
        in-place mutation of the pack or its scoring statistics)."""
        self._pack_epoch += 1
        if stats:
            self._stats_epoch += 1
        from ..cache import request_cache

        request_cache().invalidate_searcher(self.cache_token)

    # -- compilation -------------------------------------------------------

    def _compiled(self, node: QueryNode, struct_key: tuple, k: int, agg_nodes=None, agg_key=()):
        key = (struct_key, k, agg_key)
        fn = self._cache.get(key)
        from ..monitoring.device import note_executable_cache

        note_executable_cache("compiled_plan", fn is not None)
        if fn is None:
            ctx = self.ctx
            n = self.pack.num_docs

            def run(dev, params, agg_params):
                scores, match = node.device_eval(dev, params, ctx)
                ok = match[:n] & dev["live"]
                agg_out = {}
                if agg_nodes:
                    seg = jnp.where(ok, 0, 1).astype(jnp.int32)
                    dev_a = {**dev, "_query_scores": scores[:n]}
                    for name, anode in agg_nodes.items():
                        agg_out[name] = anode.device_eval_segmented(
                            dev_a, agg_params[name], seg, 1, ok, ctx
                        )
                return (*top_k_with_total(scores, match, dev["live"], k), agg_out)

            fn = jax.jit(run)
            self._cache[key] = fn
        return fn

    # -- entry points ------------------------------------------------------

    def batched(self):
        """Cached BatchTermSearcher over this shard's device pack — the
        `_msearch` fast path. Its dense tier rides the fused Pallas
        kernel (in-kernel split-bf16 matmul + per-tile top-t + canonical
        f32 rescore) whenever ES_TPU_FUSED / ES_TPU_FUSED_TOPK and the
        pack shape allow; per-query `search` keeps the compiled-plan
        path, whose final selection also streams through the fused
        scan (ops/scoring.top_k_with_total)."""
        bs = getattr(self, "_batched", None)
        if bs is None:
            from ..ops.batched import BatchTermSearcher

            bs = self._batched = BatchTermSearcher(self)
        return bs

    def msearch(self, fld: str, queries, k: int = 10, **kw):
        """Batched term-disjunction `_msearch` -> (scores, docids, totals,
        first_pass_exact) numpy (see BatchTermSearcher.msearch).

        Consults the shard request cache per QUERY before dispatching the
        fused pipeline: warm queries are assembled host-side, only the
        cold subset is planned and dispatched, and every cold query's
        result row is stored under (searcher token, epoch, canonical
        query key) — a repeated query stream never re-enters the device.
        """
        from ..cache import canonical_key, request_cache

        rc = request_cache()
        if not rc.enabled or not queries:
            return self.batched().msearch(fld, queries, k, **kw)
        tok, epoch = self.cache_scope()
        opts = sorted((str(a), v) for a, v in kw.items())
        qkeys = [
            canonical_key({"op": "msearch", "fld": fld, "k": int(k),
                           "opts": opts,
                           "q": [[t, float(b)] for t, b in q]})
            for q in queries
        ]
        rows: dict[int, tuple] = {}
        cold: list[int] = []
        for qi, ck in enumerate(qkeys):
            got = rc.get(tok, epoch, ck)
            if got is None:
                cold.append(qi)
            else:
                rows[qi] = got
        from ..telemetry import profile_event

        profile_event("cache", scope="msearch", shard=0,
                      hits=len(queries) - len(cold), misses=len(cold))
        if cold:
            _t0 = time.perf_counter()
            cv, ci, ct, cex = self.batched().msearch(
                fld, [queries[qi] for qi in cold], k, **kw)
            # amortize the measured wave wall over the cold rows — the
            # per-entry recompute cost the planner's admission floor sees
            _row_ms = (time.perf_counter() - _t0) * 1000 / len(cold)
            for j, qi in enumerate(cold):
                row = (cv[j].copy(), ci[j].copy(), int(ct[j]), bool(cex[j]))
                rows[qi] = row
                rc.put(tok, epoch, qkeys[qi], row,
                       row[0].nbytes + row[1].nbytes + 96,
                       recompute_ms=_row_ms)
        Q = len(queries)
        width = max(r[0].shape[0] for r in rows.values())
        scores = np.full((Q, width), -np.inf, np.float32)
        ids = np.zeros((Q, width), np.int64)
        totals = np.zeros((Q,), np.int64)
        exact = np.ones((Q,), bool)
        for qi, (rv, ri, rt, re_) in rows.items():
            scores[qi, : rv.shape[0]] = rv
            ids[qi, : ri.shape[0]] = ri
            totals[qi] = rt
            exact[qi] = re_
        return scores, ids, totals, exact

    def search(
        self,
        query: dict | QueryNode | None,
        size: int = 10,
        from_: int = 0,
        mappings=None,
        aggs: dict | None = None,
    ) -> ShardResult:
        """Compiled-plan per-query search, served from the shard request
        cache when the request is a plain DSL tree against the searcher's
        own mappings (cached results are byte-identical: execution is
        deterministic per (searcher, epoch, canonical request))."""
        from ..cache import canonical_key, request_cache

        rc = request_cache()
        ck = scope = None
        if rc.enabled and mappings is None and not isinstance(query, QueryNode):
            # analysis generation: query-time analyzers (synonym-set
            # reloads) change parsed queries without any index write
            ck = canonical_key({"op": "search", "query": query, "aggs": aggs,
                                "size": int(size), "from": int(from_),
                                "ag": getattr(self.mappings,
                                              "analysis_generation", 0)})
            scope = self.cache_scope()
            hit = rc.get(scope[0], scope[1], ck)
            if hit is not None:
                from ..telemetry import CACHE_HIT_SPAN, TRACER, profile_event

                profile_event("cache", scope="search", shard=0, hits=1,
                              misses=0)
                with TRACER.span(CACHE_HIT_SPAN):
                    return _copy_shard_result(hit)
            from ..telemetry import profile_event

            profile_event("cache", scope="search", shard=0, hits=0, misses=1)
        from ..telemetry import metrics as _metrics

        _t0 = time.perf_counter()
        res = self._search_uncached(query, size, from_, mappings, aggs)
        _elapsed_ms = (time.perf_counter() - _t0) * 1000
        _metrics.histogram_record("es.shard.search.ms", _elapsed_ms)
        if ck is not None:
            rc.put(scope[0], scope[1], ck, _copy_shard_result(res),
                   _shard_result_nbytes(res), recompute_ms=_elapsed_ms)
        return res

    def _plan_request(self, query, size, from_, mappings, aggs):
        """Parse/prepare/compile one request and DISPATCH its program (no
        fetch). -> ("result", ShardResult) for degenerate requests or
        ("dispatch", state); `_finalize_request` turns the fetched outputs
        into a ShardResult. Shared by the solo path and `search_many`, so
        coalesced waves execute byte-identical per-request programs."""
        m = mappings if mappings is not None else self.mappings
        if m is None and (aggs or not isinstance(query, QueryNode)):
            from ..utils.errors import QueryParsingError

            raise QueryParsingError("no mappings available to parse the request")
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        agg_nodes = None
        if aggs:
            from ..aggs import parse_aggs

            agg_nodes = parse_aggs(aggs, m)
        if self.pack.num_docs == 0:
            return ("result", ShardResult(
                np.array([], np.int32), np.array([], np.float32), 0, None,
                {} if aggs else None,
            ))
        params, struct_key = node.prepare(self.pack)
        agg_params, agg_key = {}, ()
        if agg_nodes:
            parts = {n: a.prepare(self.pack, m) for n, a in agg_nodes.items()}
            agg_params = {n: p for n, (p, _) in parts.items()}
            agg_key = tuple((n, k) for n, (_, k) in sorted(parts.items()))
        k = min(max(size + from_, 1), self.pack.num_docs)
        fn = self._compiled(node, struct_key, k, agg_nodes, agg_key)
        # PR 12: cross-check the analytic cost model against the lowered
        # program's own cost analysis (bounded: once per plan shape)
        from ..monitoring.xla_introspect import check_dispatch

        check_dispatch("compiled_plan", fn,
                       (self.dev, params, agg_params),
                       fields={"queries": 1, "k": k,
                               "num_docs": self.pack.num_docs})
        return ("dispatch", {
            "node": node, "struct_key": struct_key, "k": k,
            "agg_nodes": agg_nodes, "agg_key": agg_key, "params": params,
            "agg_params": agg_params, "size": size, "from_": from_,
            "outs": fn(self.dev, params, agg_params),
        })

    def _finalize_request(self, state, host) -> ShardResult:
        """host = the fetched (top_scores, top_ids, total, agg_out) of a
        dispatched request; runs the (rare) two-pass agg second program
        synchronously and builds the ShardResult."""
        top_scores, top_ids, total, agg_out = host
        node, struct_key, k = state["node"], state["struct_key"], state["k"]
        agg_nodes, agg_key = state["agg_nodes"], state["agg_key"]
        agg_params = state["agg_params"]
        params = state["params"]
        size, from_ = state["size"], state["from_"]
        aggregations = None
        if agg_nodes:
            from ..aggs import two_pass_plan

            tp = two_pass_plan(agg_nodes)
            if tp:
                # pass 2: exact sub-aggs over the candidate slots only
                for name, a in tp.items():
                    agg_params[name] = {
                        **agg_params[name],
                        "cand": a.select_candidates(agg_out[name]),
                    }
                fn2 = self._compiled(
                    node, struct_key, k, agg_nodes,
                    (agg_key, "tp2",
                     tuple(sorted((n, a._C) for n, a in tp.items()))))
                _s, _i, _t, agg_out2 = jax.device_get(
                    fn2(self.dev, params, agg_params))
                for name in tp:
                    agg_out[name] = {**agg_out[name], **agg_out2[name]}
            aggregations = {
                name: anode.finalize(agg_out[name], 1)[0]
                for name, anode in agg_nodes.items()
            }
        valid = np.isfinite(top_scores)
        max_score = float(top_scores[0]) if valid.any() else None
        end = max(size + from_, 0)
        ids = top_ids[valid][from_:end]
        scs = top_scores[valid][from_:end]
        return ShardResult(
            ids.astype(np.int32), scs.astype(np.float32), int(total), max_score, aggregations
        )

    def _search_uncached(
        self,
        query: dict | QueryNode | None,
        size: int = 10,
        from_: int = 0,
        mappings=None,
        aggs: dict | None = None,
    ) -> ShardResult:
        kind, state = self._plan_request(query, size, from_, mappings, aggs)
        if kind == "result":
            return state
        from ..ops.scoring import topk_mode
        from ..telemetry import time_kernel

        k = state["k"]
        with time_kernel("compiled_plan", shard=0, queries=1,
                         tier=topk_mode(self.pack.num_docs, k),
                         num_docs=self.pack.num_docs, k=k):
            host = jax.device_get(state["outs"])
        return self._finalize_request(state, host)

    def search_many(self, requests: list[dict]) -> list[ShardResult]:
        """Wave-shaped entry point: execute several `search()`-shaped
        request dicts (query, size, from_, mappings, aggs) with every
        compiled program dispatched before ANY result is fetched — one
        device round trip per wave instead of one per request. Cache
        lookups/stores, planning, and per-request programs are the same
        code as solo `search()`, so wave results are byte-identical to
        solo execution."""
        from ..cache import canonical_key, request_cache

        rc = request_cache()
        n = len(requests)
        results: list = [None] * n
        states: list = [None] * n
        slots: list = [None] * n
        for i, r in enumerate(requests):
            query = r.get("query")
            size = r.get("size", 10)
            from_ = r.get("from_", 0)
            mappings = r.get("mappings")
            aggs = r.get("aggs")
            ck = scope = None
            if (rc.enabled and mappings is None
                    and not isinstance(query, QueryNode)):
                ck = canonical_key(
                    {"op": "search", "query": query, "aggs": aggs,
                     "size": int(size), "from": int(from_),
                     "ag": getattr(self.mappings, "analysis_generation", 0)})
                scope = self.cache_scope()
                hit = rc.get(scope[0], scope[1], ck)
                if hit is not None:
                    results[i] = _copy_shard_result(hit)
                    continue
            kind, st = self._plan_request(query, size, from_, mappings, aggs)
            if kind == "result":
                results[i] = st
            else:
                states[i] = st
                slots[i] = (ck, scope)
        live = [s for s in states if s is not None]
        if live:
            from ..ops.scoring import topk_mode
            from ..telemetry import host_transition, time_kernel

            # the wave contract (PR 11): every program dispatched above,
            # ONE blocking fetch here — counted like the sharded wave
            host_transition("dispatch")
            k0 = max(s["k"] for s in live)
            with time_kernel("compiled_plan", shard=0, queries=len(live),
                             tier=topk_mode(self.pack.num_docs, k0),
                             num_docs=self.pack.num_docs, k=k0):
                host = jax.device_get([s["outs"] for s in live])
            host_transition("fetch")
            host = iter(host)
            for i, s in enumerate(states):
                if s is None:
                    continue
                res = self._finalize_request(s, next(host))
                results[i] = res
                if slots[i] is not None and slots[i][0] is not None:
                    ck, scope = slots[i]
                    rc.put(scope[0], scope[1], ck, _copy_shard_result(res),
                           _shard_result_nbytes(res))
        return results

    def count(self, query: dict | QueryNode | None, mappings=None) -> int:
        return self.search(query, size=1, mappings=mappings).total

    # -- field-sorted search ----------------------------------------------

    def _compiled_sorted(self, node, struct_key, k, plan, has_after, agg_nodes, agg_key):
        key = ("sorted", struct_key, k, plan.struct_key(), has_after, agg_key)
        fn = self._cache.get(key)
        if fn is None:
            ctx = self.ctx
            n = self.pack.num_docs

            def run(dev, params, after, agg_params):
                scores, match = node.device_eval(dev, params, ctx)
                ok = match[:n] & dev["live"]
                total = jnp.sum(ok, dtype=jnp.int32)
                agg_out = {}
                if agg_nodes:
                    seg = jnp.where(ok, 0, 1).astype(jnp.int32)
                    dev_a = {**dev, "_query_scores": scores[:n]}
                    for name, anode in agg_nodes.items():
                        agg_out[name] = anode.device_eval_segmented(
                            dev_a, agg_params[name], seg, 1, ok, ctx
                        )
                keys = plan.device_keys(dev, scores, n)
                sel = ok
                if has_after:
                    # lexicographic "strictly after the cursor"
                    gt = jnp.zeros(n, bool)
                    eq = jnp.ones(n, bool)
                    for kk, aa in zip(keys, after):
                        gt = gt | (eq & (kk > aa))
                        eq = eq & (kk == aa)
                    sel = sel & gt
                invalid = (~sel).astype(jnp.int32)
                docs = jnp.arange(n, dtype=jnp.int32)
                sorted_ops = jax.lax.sort(
                    (invalid, *keys, docs), num_keys=1 + len(keys)
                )
                inv_s = sorted_ops[0][:k]
                keys_s = tuple(o[:k] for o in sorted_ops[1:-1])
                docs_s = sorted_ops[-1][:k]
                return inv_s, keys_s, docs_s, total, agg_out

            fn = jax.jit(run)
            self._cache[key] = fn
        return fn

    def search_sorted(
        self,
        query,
        sort_fields,
        size: int = 10,
        from_: int = 0,
        search_after=None,
        mappings=None,
        aggs: dict | None = None,
    ):
        """-> (hits: [(docid, sort_values)], total, aggregations)."""
        from .sort import SortPlan

        m = mappings if mappings is not None else self.mappings
        node = query if isinstance(query, QueryNode) else parse_query(query, m)
        agg_nodes = None
        if aggs:
            from ..aggs import parse_aggs

            agg_nodes = parse_aggs(aggs, m)
        if self.pack.num_docs == 0:
            return [], 0, ({} if aggs else None)
        plan = SortPlan(sort_fields, self.pack, m)
        params, struct_key = node.prepare(self.pack)
        agg_params, agg_key = {}, ()
        if agg_nodes:
            parts = {nm: a.prepare(self.pack, m) for nm, a in agg_nodes.items()}
            from ..aggs import two_pass_plan

            tp = two_pass_plan(agg_nodes)
            if tp:
                # field-sorted execution can't orchestrate two passes: fall
                # back to single-pass (the one-pass budgets apply as before)
                for a in tp.values():
                    a.force_single_pass = True
                parts = {nm: a.prepare(self.pack, m)
                         for nm, a in agg_nodes.items()}
            agg_params = {nm: p for nm, (p, _) in parts.items()}
            agg_key = tuple((nm, kk) for nm, (_, kk) in sorted(parts.items()))
        k = min(max(size + from_, 1), self.pack.num_docs)
        after = ()
        if search_after is not None:
            after = plan.after_keys(search_after, self.pack)
        fn = self._compiled_sorted(
            node, struct_key, k, plan, search_after is not None, agg_nodes, agg_key
        )
        inv, keys_s, docs, total, agg_out = jax.device_get(
            fn(self.dev, params, after, agg_params)
        )
        aggregations = None
        if agg_nodes:
            aggregations = {
                name: anode.finalize(agg_out[name], 1)[0]
                for name, anode in agg_nodes.items()
            }
        nvalid = int((inv == 0).sum())
        take = list(range(min(nvalid, k)))[from_ : size + from_]
        values = plan.hit_values(keys_s, take)
        hits = [(int(docs[i]), v) for i, v in zip(take, values)]
        return hits, int(total), aggregations
