"""Long-tail query types.

Parity targets (reference): index/query/MoreLikeThisQueryBuilder.java
(TF-IDF term selection from like texts/docs), TermsSetQueryBuilder.java
(per-doc minimum_should_match from a field), CombinedFieldsQueryBuilder.java
(cross-field term matching — approximated as a per-field should-bool, a
documented divergence from true BM25F), RankFeatureQueryBuilder.java
(saturation/log/sigmoid/linear over a positive feature column),
DistanceFeatureQueryBuilder.java (decay by distance from an origin in
date/geo space), PinnedQueryBuilder.java (promoted ids above organic
results), WrapperQueryBuilder.java (base64-embedded query)."""

from __future__ import annotations

import base64
import json
import math
from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp
import numpy as np

from ..index.mappings import parse_date_to_millis
from ..utils.errors import IllegalArgumentError, QueryParsingError
from .nodes import BoolNode, QueryNode, TermNode


# ---- more_like_this -------------------------------------------------------

@dataclass
class MoreLikeThisNode(QueryNode):
    fields: list = dc_field(default_factory=list)
    like_texts: list = dc_field(default_factory=list)
    like_ids: list = dc_field(default_factory=list)
    unlike_texts: list = dc_field(default_factory=list)
    mappings: object = None
    max_query_terms: int = 25
    min_term_freq: int = 2
    min_doc_freq: int = 5
    minimum_should_match: str = "30%"
    boost: float = 1.0
    _inner: QueryNode | None = None

    def _select_terms(self, stacked) -> list[tuple[str, str]]:
        """TF-IDF-ranked (field, term) candidates from the like sources."""
        from collections import Counter

        tf: Counter = Counter()
        for fld in self.fields:
            ft = self.mappings.fields.get(fld)
            if ft is None or ft.type not in ("text", "keyword"):
                continue
            analyzer = ft.get_analyzer() if ft.type == "text" else None
            texts = list(self.like_texts)
            for like_id in self.like_ids:
                for pack in stacked.shards:
                    sources = getattr(pack, "doc_sources", None)
                    col = pack.docvalues.get("_id")
                    if sources is None or col is None:
                        continue
                    terms_list = col.ord_terms or []
                    for docid, src in enumerate(sources):
                        if (docid < len(col.values) and col.values[docid] >= 0
                                and terms_list[col.values[docid]] == like_id):
                            v = src.get(fld)
                            if isinstance(v, str):
                                texts.append(v)
            unlike_terms = set()
            for u in self.unlike_texts:
                if analyzer:
                    unlike_terms |= {t.term for t in analyzer.analyze(u)}
                else:
                    unlike_terms.add(u)
            for text in texts:
                toks = ([t.term for t in analyzer.analyze(text)]
                        if analyzer else [text])
                for t in toks:
                    if t not in unlike_terms:
                        tf[(fld, t)] += 1
        n_docs = max(stacked.n_max * stacked.S, 1)
        scored = []
        for (fld, term), f in tf.items():
            if f < self.min_term_freq:
                continue
            df = stacked.global_df.get((fld, term), 0)
            if df < self.min_doc_freq:
                continue
            from ..ops.scoring import bm25_idf  # THE idf implementation

            idf = bm25_idf(n_docs, df)
            scored.append((f * idf, fld, term))
        scored.sort(key=lambda x: (-x[0], x[1], x[2]))
        return [(fld, term) for _, fld, term in scored[: self.max_query_terms]]

    def prepare(self, pack):
        stacked = getattr(pack, "stacked", None)
        if stacked is None:
            # bare ShardPack (e.g. percolate matcher): single-shard view
            class _One:
                shards = [pack]
                global_df = {k: int(pack.term_df[v])
                             for k, v in pack.term_dict.items()}
                n_max = pack.num_docs
                S = 1

            stacked = _One()
        if self._inner is None:
            selected = self._select_terms(stacked)
            if not selected:
                from .nodes import MatchNoneNode

                self._inner = MatchNoneNode()
            else:
                msm = self.minimum_should_match
                if isinstance(msm, str) and msm.endswith("%"):
                    msm_n = max(1, int(len(selected) * int(msm[:-1]) / 100))
                else:
                    msm_n = int(msm)
                self._inner = BoolNode(
                    should=[TermNode(f, t) for f, t in selected],
                    minimum_should_match=msm_n, boost=self.boost,
                )
        return self._inner.prepare(pack)

    def device_eval(self, dev, params, ctx):
        return self._inner.device_eval(dev, params, ctx)


def parse_more_like_this(body, mappings) -> MoreLikeThisNode:
    fields = body.get("fields")
    if not fields:
        fields = sorted(f for f, ft in mappings.fields.items() if ft.type == "text")
    likes = body.get("like")
    if likes is None:
        raise QueryParsingError("[more_like_this] requires [like]")
    if not isinstance(likes, list):
        likes = [likes]
    texts, ids = [], []
    for like in likes:
        if isinstance(like, str):
            texts.append(like)
        elif isinstance(like, dict) and "_id" in like:
            ids.append(like["_id"])
        else:
            raise QueryParsingError(f"cannot parse [like] entry {like!r}")
    unlikes = body.get("unlike") or []
    if not isinstance(unlikes, list):
        unlikes = [unlikes]
    return MoreLikeThisNode(
        fields=list(fields), like_texts=texts, like_ids=ids,
        unlike_texts=[u for u in unlikes if isinstance(u, str)],
        mappings=mappings,
        max_query_terms=int(body.get("max_query_terms", 25)),
        min_term_freq=int(body.get("min_term_freq", 2)),
        min_doc_freq=int(body.get("min_doc_freq", 5)),
        minimum_should_match=body.get("minimum_should_match", "30%"),
        boost=float(body.get("boost", 1.0)),
    )


# ---- terms_set ------------------------------------------------------------

@dataclass
class TermsSetNode(QueryNode):
    fld: str = ""
    terms: list = dc_field(default_factory=list)
    msm_field: str = ""
    boost: float = 1.0
    _nodes: list = dc_field(default_factory=list)

    def prepare(self, pack):
        self._nodes = [TermNode(self.fld, t) for t in self.terms]
        parts = [n.prepare(pack) for n in self._nodes]
        return (
            tuple(p for p, _ in parts), np.float32(self.boost),
        ), ("terms_set", self.fld, tuple(k for _, k in parts), self.msm_field)

    def device_eval(self, dev, params, ctx):
        childs, boost = params
        n1 = ctx.num_docs + 1
        total = jnp.zeros(n1, jnp.float32)
        cnt = jnp.zeros(n1, jnp.int32)
        for node, p in zip(self._nodes, childs):
            s, m = node.device_eval(dev, p, ctx)
            total = total + jnp.where(m, s, 0.0)
            cnt = cnt + m.astype(jnp.int32)
        got = dev["dv_int"].get(self.msm_field)
        if got is None:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        msm_v, msm_h = got
        n = ctx.num_docs
        required = jnp.where(msm_h, msm_v, 2**31 - 1).astype(jnp.int32)
        ok_n = (cnt[:n] >= required) & (cnt[:n] > 0)
        match = jnp.zeros(n1, bool).at[:n].set(ok_n)
        return jnp.where(match, boost * total, 0.0), match


# ---- rank_feature ---------------------------------------------------------

@dataclass
class RankFeatureNode(QueryNode):
    fld: str = ""
    mode: str = "saturation"  # saturation | log | sigmoid | linear
    pivot: float | None = None
    exponent: float = 1.0
    scaling_factor: float = 1.0
    boost: float = 1.0

    def prepare(self, pack):
        if self.pivot is None and self.mode in ("saturation", "sigmoid"):
            # default pivot: approximate mean of the feature (the reference
            # uses a stored geometric mean; the column mean is the analog)
            col = pack.docvalues.get(self.fld)
            vals = None
            if col is not None and col.kind == "float" and col.has_value.any():
                vals = col.values[col.has_value]
            self.pivot = float(np.mean(vals)) if vals is not None else 1.0
        return (), ("rank_feature", self.fld, self.mode, self.pivot,
                    self.exponent, self.scaling_factor, self.boost)

    def device_eval(self, dev, params, ctx):
        n1 = ctx.num_docs + 1
        got = dev["dv_float"].get(self.fld)
        if got is None:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        v, h = got
        n = ctx.num_docs
        x = jnp.maximum(v[:n].astype(jnp.float32), 0.0)
        if self.mode == "saturation":
            s = x / (x + jnp.float32(self.pivot))
        elif self.mode == "log":
            s = jnp.log(jnp.float32(self.scaling_factor) + x)
        elif self.mode == "sigmoid":
            xp = x ** jnp.float32(self.exponent)
            s = xp / (xp + jnp.float32(self.pivot) ** jnp.float32(self.exponent))
        else:  # linear
            s = x
        match = jnp.zeros(n1, bool).at[:n].set(h[:n])
        score = jnp.zeros(n1, jnp.float32).at[:n].set(
            jnp.where(h[:n], self.boost * s, 0.0))
        return score, match


# ---- distance_feature -----------------------------------------------------

@dataclass
class DistanceFeatureNode(QueryNode):
    fld: str = ""
    kind: str = "numeric"  # numeric (date) | geo
    origin: float = 0.0
    origin_lat: float = 0.0
    origin_lon: float = 0.0
    pivot: float = 1.0
    boost: float = 1.0

    def prepare(self, pack):
        return (), ("distance_feature", self.fld, self.kind, self.origin,
                    self.origin_lat, self.origin_lon, self.pivot, self.boost)

    def device_eval(self, dev, params, ctx):
        n1 = ctx.num_docs + 1
        n = ctx.num_docs
        if self.kind == "geo":
            from .geo import EARTH_RADIUS_M, _geo_cols

            got = _geo_cols(dev, self.fld, ctx)
            if got is None:
                return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
            lat, h, lon = got
            la1 = jnp.deg2rad(lat[:n])
            lo1 = jnp.deg2rad(lon[:n])
            la2 = math.radians(self.origin_lat)
            lo2 = math.radians(self.origin_lon)
            a = (jnp.sin((la1 - la2) / 2) ** 2
                 + jnp.cos(la1) * math.cos(la2) * jnp.sin((lo1 - lo2) / 2) ** 2)
            dist = 2.0 * EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0, 1)))
            h = h[:n]
        else:
            got = dev["dv_int"].get(self.fld) or dev["dv_float"].get(self.fld)
            if got is None:
                return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
            v, h0 = got
            dist = jnp.abs(v[:n].astype(jnp.float32) - jnp.float32(self.origin))
            h = h0[:n]
        s = jnp.float32(self.pivot) / (jnp.float32(self.pivot) + dist)
        match = jnp.zeros(n1, bool).at[:n].set(h)
        score = jnp.zeros(n1, jnp.float32).at[:n].set(
            jnp.where(h, self.boost * s, 0.0))
        return score, match


# ---- pinned ---------------------------------------------------------------

@dataclass
class PinnedNode(QueryNode):
    ids: list = dc_field(default_factory=list)
    organic: QueryNode = None

    def prepare(self, pack):
        real = getattr(pack, "pack", pack)
        col = real.docvalues.get("_id")
        matched = []
        ranks = []
        if col is not None and col.ord_terms:
            ord_of = {t: i for i, t in enumerate(col.ord_terms)}
            id_ords = col.values
            for rank, want in enumerate(self.ids):
                o = ord_of.get(str(want))
                if o is None:
                    continue
                hits = np.flatnonzero(id_ords == o)
                for d in hits:
                    matched.append(int(d))
                    ranks.append(rank)
        width = max(1, 1 << max(0, (len(matched) - 1)).bit_length()) if matched else 1
        ids = np.full(width, -1, np.int32)
        rks = np.zeros(width, np.float32)
        ids[: len(matched)] = matched
        rks[: len(matched)] = ranks
        op, ok = self.organic.prepare(pack)
        return (ids, rks, op), ("pinned", width, ok)

    def device_eval(self, dev, params, ctx):
        ids, ranks, op = params
        n1 = ctx.num_docs + 1
        os_, om = self.organic.device_eval(dev, op, ctx)
        # pinned docs score above any organic BM25 score, ordered by list
        # position (reference behavior: PinnedQueryBuilder MAX_ORGANIC_SCORE)
        tgt = jnp.where(ids >= 0, ids, ctx.num_docs)
        # rank step must exceed the f32 ulp at the pin base (~1.4e11)
        pin_score = jnp.float32(1.7e18) - ranks * jnp.float32(1e12)
        scores = jnp.where(om, os_, 0.0)
        scores = scores.at[tgt].set(jnp.where(ids >= 0, pin_score, scores[tgt]))
        match = om.at[tgt].set((ids >= 0) | om[tgt])
        match = match.at[ctx.num_docs].set(False)
        return scores, match


# ---- parsers --------------------------------------------------------------

def parse_terms_set(body, mappings) -> TermsSetNode:
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[terms_set] expects {field: {...}}")
    (fld, spec), = body.items()
    terms = spec.get("terms")
    msm_field = spec.get("minimum_should_match_field")
    if not isinstance(terms, list) or not msm_field:
        raise QueryParsingError(
            "[terms_set] requires [terms] and [minimum_should_match_field]")
    return TermsSetNode(fld=fld, terms=[str(t) for t in terms],
                        msm_field=msm_field,
                        boost=float(spec.get("boost", 1.0)))


def parse_combined_fields(body, mappings) -> QueryNode:
    text = body.get("query")
    fields = body.get("fields")
    if text is None or not fields:
        raise QueryParsingError("[combined_fields] requires [query] and [fields]")
    from .dsl import _parse_match

    operator = body.get("operator", "or")
    children = [
        _parse_match({f.split("^")[0]: {"query": text, "operator": operator}},
                     mappings)
        for f in fields
    ]
    return BoolNode(should=children, minimum_should_match=1,
                    boost=float(body.get("boost", 1.0)))


def parse_rank_feature(body, mappings) -> RankFeatureNode:
    fld = body.get("field")
    if not fld:
        raise QueryParsingError("[rank_feature] requires [field]")
    mode = "saturation"
    pivot = None
    exponent = 1.0
    scaling = 1.0
    for m in ("saturation", "log", "sigmoid", "linear"):
        if m in body:
            mode = m
            spec = body[m] or {}
            pivot = spec.get("pivot")
            exponent = float(spec.get("exponent", 1.0))
            scaling = float(spec.get("scaling_factor", 1.0))
    return RankFeatureNode(fld=fld, mode=mode,
                           pivot=float(pivot) if pivot is not None else None,
                           exponent=exponent, scaling_factor=scaling,
                           boost=float(body.get("boost", 1.0)))


def parse_distance_feature(body, mappings) -> DistanceFeatureNode:
    fld = body.get("field")
    origin = body.get("origin")
    pivot = body.get("pivot")
    if fld is None or origin is None or pivot is None:
        raise QueryParsingError(
            "[distance_feature] requires [field], [origin] and [pivot]")
    ft = mappings.fields.get(fld)
    if ft is not None and ft.type == "geo_point":
        from ..index.pack import _parse_geo_point
        from .geo import parse_distance_meters

        lat, lon = _parse_geo_point(origin)
        return DistanceFeatureNode(
            fld=fld, kind="geo", origin_lat=lat, origin_lon=lon,
            pivot=parse_distance_meters(pivot),
            boost=float(body.get("boost", 1.0)))
    if ft is not None and ft.type == "date":
        from ..utils.durations import parse_duration_millis

        return DistanceFeatureNode(
            fld=fld, kind="numeric",
            origin=float(parse_date_to_millis(origin)),
            pivot=float(parse_duration_millis(pivot)),
            boost=float(body.get("boost", 1.0)))
    return DistanceFeatureNode(fld=fld, kind="numeric", origin=float(origin),
                               pivot=float(pivot),
                               boost=float(body.get("boost", 1.0)))


def parse_pinned(body, mappings) -> PinnedNode:
    ids = body.get("ids")
    organic = body.get("organic")
    if not isinstance(ids, list) or organic is None:
        raise QueryParsingError("[pinned] requires [ids] and [organic]")
    from .dsl import parse_query

    return PinnedNode(ids=[str(i) for i in ids],
                      organic=parse_query(organic, mappings))


def parse_wrapper(body, mappings) -> QueryNode:
    raw = body.get("query")
    if not raw:
        raise QueryParsingError("[wrapper] requires base64 [query]")
    from .dsl import parse_query

    try:
        inner = json.loads(base64.b64decode(raw))
    except Exception as ex:  # noqa: BLE001
        raise QueryParsingError(f"failed to decode wrapper query: {ex}")
    return parse_query(inner, mappings)
