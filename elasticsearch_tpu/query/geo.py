"""Geo queries: geo_bounding_box, geo_distance.

Parity targets (reference): index/query/GeoBoundingBoxQueryBuilder.java
(dateline-crossing boxes), GeoDistanceQueryBuilder.java (haversine arc
distance). geo_point columns live as paired float docvalues
(`field#lat` / `field#lon`, index/pack.py), so both queries are pure
vectorized arithmetic over two columns — ideal device shape."""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import jax.numpy as jnp

from ..index.pack import _parse_geo_point
from ..utils.errors import IllegalArgumentError, QueryParsingError
from .nodes import QueryNode

EARTH_RADIUS_M = 6371008.7714  # mean radius, matches Lucene GeoUtils

_DIST_UNITS = {
    "mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
    "in": 0.0254, "ft": 0.3048, "yd": 0.9144, "mi": 1609.344,
    "nmi": 1852.0, "nauticalmiles": 1852.0, "kilometers": 1000.0,
    "meters": 1.0, "miles": 1609.344, "feet": 0.3048, "inch": 0.0254,
}


def parse_distance_meters(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*", str(v))
    if not m:
        raise IllegalArgumentError(f"failed to parse distance [{v}]")
    unit = m.group(2).lower() or "m"
    if unit not in _DIST_UNITS:
        raise IllegalArgumentError(f"unknown distance unit [{unit}]")
    return float(m.group(1)) * _DIST_UNITS[unit]


def _geo_cols(dev, fld, ctx):
    lat = dev["dv_float"].get(f"{fld}#lat")
    lon = dev["dv_float"].get(f"{fld}#lon")
    if lat is None or lon is None:
        return None
    return lat[0], lat[1] & lon[1], lon[0]


@dataclass
class GeoBoundingBoxNode(QueryNode):
    fld: str = ""
    top: float = 90.0
    bottom: float = -90.0
    left: float = -180.0
    right: float = 180.0
    boost: float = 1.0

    def prepare(self, pack):
        return (), ("geo_bbox", self.fld, self.top, self.bottom,
                    self.left, self.right, self.boost)

    def device_eval(self, dev, params, ctx):
        n1 = ctx.num_docs + 1
        got = _geo_cols(dev, self.fld, ctx)
        if got is None:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        lat, has, lon = got
        ok = has & (lat <= self.top) & (lat >= self.bottom)
        if self.left <= self.right:
            ok = ok & (lon >= self.left) & (lon <= self.right)
        else:  # crosses the dateline
            ok = ok & ((lon >= self.left) | (lon <= self.right))
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(ok[: ctx.num_docs])
        score = jnp.where(match, jnp.float32(self.boost), 0.0)
        return score, match


@dataclass
class GeoDistanceNode(QueryNode):
    fld: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0
    boost: float = 1.0

    def prepare(self, pack):
        return (), ("geo_dist", self.fld, self.lat, self.lon,
                    self.distance_m, self.boost)

    def device_eval(self, dev, params, ctx):
        n1 = ctx.num_docs + 1
        got = _geo_cols(dev, self.fld, ctx)
        if got is None:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        lat, has, lon = got
        la1 = jnp.deg2rad(lat)
        lo1 = jnp.deg2rad(lon)
        la2 = math.radians(self.lat)
        lo2 = math.radians(self.lon)
        dphi = la1 - la2
        dlmb = lo1 - lo2
        a = jnp.sin(dphi / 2) ** 2 + jnp.cos(la1) * math.cos(la2) * jnp.sin(dlmb / 2) ** 2
        dist = 2.0 * EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
        ok = has & (dist <= self.distance_m)
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(ok[: ctx.num_docs])
        score = jnp.where(match, jnp.float32(self.boost), 0.0)
        return score, match


def parse_geo_bounding_box(body, mappings) -> GeoBoundingBoxNode:
    body = dict(body)
    boost = float(body.pop("boost", 1.0))
    body.pop("validation_method", None)
    body.pop("ignore_unmapped", None)
    if len(body) != 1:
        raise QueryParsingError("[geo_bounding_box] expects one field")
    (fld, spec), = body.items()
    if "top_left" in spec and "bottom_right" in spec:
        tl = _parse_geo_point(spec["top_left"])
        br = _parse_geo_point(spec["bottom_right"])
        top, left = tl
        bottom, right = br
    else:
        top = float(spec["top"])
        bottom = float(spec["bottom"])
        left = float(spec["left"])
        right = float(spec["right"])
    return GeoBoundingBoxNode(fld=fld, top=top, bottom=bottom,
                              left=left, right=right, boost=boost)


def parse_geo_distance(body, mappings) -> GeoDistanceNode:
    body = dict(body)
    boost = float(body.pop("boost", 1.0))
    distance = body.pop("distance", None)
    body.pop("distance_type", None)
    body.pop("validation_method", None)
    body.pop("ignore_unmapped", None)
    if distance is None:
        raise QueryParsingError("[geo_distance] requires [distance]")
    if len(body) != 1:
        raise QueryParsingError("[geo_distance] expects one origin field")
    (fld, origin), = body.items()
    lat, lon = _parse_geo_point(origin)
    return GeoDistanceNode(fld=fld, lat=lat, lon=lon,
                           distance_m=parse_distance_meters(distance),
                           boost=boost)
