"""Intervals query: proximity rules over term positions.

Parity target: index/query/IntervalQueryBuilder.java (reference behavior:
Lucene intervals — `match` with ordered/unordered + max_gaps, and
`all_of`/`any_of` combinators). Positions come from the pack's host-side
position keys (docid * POS_L + position, the same arrays the phrase kernel
uses on device); interval window evaluation runs host-side per candidate doc
at prepare time and feeds the device an explicit id set, so the clause
composes like any other. Scoring is constant boost (interval queries score
by slop in the reference — a documented simplification)."""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp
import numpy as np

from ..index.pack import POS_L
from ..utils.errors import QueryParsingError
from .nodes import QueryNode


def _term_positions_by_doc(pack, fld: str, term: str) -> dict[int, list[int]]:
    """Decode one term's (docid -> sorted positions) from the blocked keys."""
    s, nb, npos = pack.term_pos_blocks(fld, term)
    if nb == 0 or pack.pos_keys is None:
        return {}
    keys = pack.pos_keys[s: s + nb].reshape(-1)[:npos]
    out: dict[int, list[int]] = {}
    for k in keys:
        out.setdefault(int(k) // POS_L, []).append(int(k) % POS_L)
    return out


def _match_windows(pos_lists: list[list[int]], ordered: bool,
                   max_gaps: int) -> bool:
    """Does any assignment of one position per term fit in a window with at
    most max_gaps interior gaps (window width <= n + max_gaps)?"""
    n = len(pos_lists)
    if any(not p for p in pos_lists):
        return False
    if n == 1:
        return True
    width_limit = n + max_gaps if max_gaps >= 0 else 1 << 30

    if ordered:
        return any(
            _ordered_fits(pos_lists, start, width_limit)
            for start in pos_lists[0]
        )
    # unordered: sliding window over the merged positions
    events = sorted(
        (p, i) for i, plist in enumerate(pos_lists) for p in plist
    )
    from collections import Counter

    have: Counter = Counter()
    j = 0
    for i in range(len(events)):
        have[events[i][1]] += 1
        while events[i][0] - events[j][0] + 1 > width_limit:
            have[events[j][1]] -= 1
            if have[events[j][1]] == 0:
                del have[events[j][1]]
            j += 1
        if len(have) == n:
            return True
    return False


def _ordered_fits(pos_lists, start: int, width_limit: int) -> bool:
    prev = start
    for plist in pos_lists[1:]:
        nxt = None
        for p in plist:
            if p > prev:
                nxt = p
                break
        if nxt is None:
            return False
        prev = nxt
    return prev - start + 1 <= width_limit


@dataclass
class IntervalsNode(QueryNode):
    fld: str = ""
    rule: dict = dc_field(default_factory=dict)
    mappings: object = None
    boost: float = 1.0

    def _eval_rule(self, pack, rule: dict) -> set[int]:
        (kind, spec), = rule.items()
        if kind == "match":
            ft = self.mappings.fields.get(self.fld)
            analyzer = ft.get_search_analyzer() if ft else None
            terms = ([t.term for t in analyzer.analyze(str(spec.get("query", "")))]
                     if analyzer else str(spec.get("query", "")).split())
            if not terms:
                return set()
            per_term = [_term_positions_by_doc(pack, self.fld, t) for t in terms]
            docs = set(per_term[0])
            for m in per_term[1:]:
                docs &= set(m)
            ordered = bool(spec.get("ordered", False))
            max_gaps = int(spec.get("max_gaps", -1))
            return {
                d for d in docs
                if _match_windows([m[d] for m in per_term], ordered, max_gaps)
            }
        if kind == "any_of":
            out: set[int] = set()
            for sub in spec.get("intervals", []):
                out |= self._eval_rule(pack, sub)
            return out
        if kind == "all_of":
            subs = spec.get("intervals", [])
            if not subs:
                return set()
            out = self._eval_rule(pack, subs[0])
            for sub in subs[1:]:
                out &= self._eval_rule(pack, sub)
            return out
        raise QueryParsingError(f"unsupported intervals rule [{kind}]")

    def prepare(self, pack):
        real = getattr(pack, "pack", pack)
        matched = sorted(self._eval_rule(real, self.rule))
        width = 1 << max(0, (max(len(matched), 1) - 1)).bit_length()
        ids = np.full(width, -1, np.int32)
        ids[: len(matched)] = matched
        return (ids, np.float32(self.boost)), ("intervals", self.fld, width)

    def device_eval(self, dev, params, ctx):
        ids, boost = params
        n1 = ctx.num_docs + 1
        tgt = jnp.where(ids >= 0, ids, ctx.num_docs)
        match = jnp.zeros(n1, bool).at[tgt].set(ids >= 0)
        match = match.at[ctx.num_docs].set(False)
        return jnp.where(match, boost, 0.0), match


def parse_intervals(body, mappings) -> IntervalsNode:
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError("[intervals] expects {field: {rule}}")
    (fld, spec), = body.items()
    boost = 1.0
    spec = dict(spec)
    if "boost" in spec:
        boost = float(spec.pop("boost"))
    if len(spec) != 1:
        raise QueryParsingError("[intervals] expects exactly one rule")
    return IntervalsNode(fld=fld, rule=spec, mappings=mappings, boost=boost)
