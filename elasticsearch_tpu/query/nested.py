"""Nested query: per-object matching against nested documents.

Parity target: index/query/NestedQueryBuilder.java — in the reference,
nested objects are separate hidden Lucene docs joined by block-join; the
query matches a parent when ANY of its nested objects satisfies the inner
query *as a unit* (cross-field alignment within one object). Here nested
objects live inside the stored source; matching runs host-side per object
at prepare time and the matched parent ids feed the device as an explicit
id set (composable like any clause). The inner evaluator covers the
predicate subset (term/terms/match/range/exists/bool); scoring is
constant boost (score_mode=none semantics)."""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp
import numpy as np

from ..index.mappings import parse_date_to_millis
from ..utils.errors import IllegalArgumentError, QueryParsingError
from .nodes import QueryNode


def _bucket(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length() if n > 1 else 1


def _get_path(obj, path: str):
    cur = obj
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    return cur


def _values_of(obj, rel_path: str) -> list:
    v = _get_path(obj, rel_path)
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _match_predicate(q: dict, obj: dict, rel, mappings) -> bool:
    """Evaluate the inner-query subset against one nested object."""
    (kind, body), = q.items()
    if kind == "bool":
        for clause in body.get("must", []) or []:
            if not _match_predicate(clause, obj, rel, mappings):
                return False
        for clause in body.get("filter", []) or []:
            if not _match_predicate(clause, obj, rel, mappings):
                return False
        for clause in body.get("must_not", []) or []:
            if _match_predicate(clause, obj, rel, mappings):
                return False
        should = body.get("should", []) or []
        if should:
            need = int(body.get("minimum_should_match",
                                0 if (body.get("must") or body.get("filter")) else 1))
            got = sum(1 for c in should if _match_predicate(c, obj, rel, mappings))
            if got < need:
                return False
        return True
    if kind in ("term", "match"):
        (fld, spec), = body.items()
        want = spec.get("value" if kind == "term" else "query") if isinstance(spec, dict) else spec
        vals = _values_of(obj, rel(fld))
        if kind == "match":
            ft = mappings.fields.get(fld)
            if ft is not None and ft.type == "text":
                toks = {t.lower() for v in vals for t in str(v).split()}
                return any(w.lower() in toks for w in str(want).split())
        return any(v == want or str(v) == str(want) for v in vals)
    if kind == "terms":
        (fld, wants), = body.items()
        vals = _values_of(obj, rel(fld))
        return any(v in wants or str(v) in [str(w) for w in wants] for v in vals)
    if kind == "exists":
        return bool(_values_of(obj, rel(body["field"])))
    if kind == "range":
        (fld, spec), = body.items()
        ft = mappings.fields.get(fld)
        is_date = ft is not None and ft.type == "date"

        def conv(x):
            return parse_date_to_millis(x) if is_date else float(x)

        for v in _values_of(obj, rel(fld)):
            try:
                fv = conv(v)
            except Exception:  # noqa: BLE001
                continue
            ok = True
            if "gte" in spec and not fv >= conv(spec["gte"]):
                ok = False
            if "gt" in spec and not fv > conv(spec["gt"]):
                ok = False
            if "lte" in spec and not fv <= conv(spec["lte"]):
                ok = False
            if "lt" in spec and not fv < conv(spec["lt"]):
                ok = False
            if ok:
                return True
        return False
    raise QueryParsingError(
        f"query [{kind}] is not supported inside [nested] here")


@dataclass
class NestedNode(QueryNode):
    path: str = ""
    query: dict = dc_field(default_factory=dict)
    mappings: object = None
    boost: float = 1.0

    def prepare(self, pack):
        real = getattr(pack, "pack", pack)
        sources = getattr(real, "doc_sources", None)
        matched = []
        if sources is not None:
            rel = lambda f: f[len(self.path) + 1:] if f.startswith(self.path + ".") else f
            for docid, src in enumerate(sources):
                objs = _get_path(src, self.path)
                if objs is None:
                    continue
                if not isinstance(objs, list):
                    objs = [objs]
                for obj in objs:
                    if isinstance(obj, dict) and _match_predicate(
                            self.query, obj, rel, self.mappings):
                        matched.append(docid)
                        break
        width = _bucket(max(len(matched), 1))
        ids = np.full(width, -1, np.int32)
        ids[: len(matched)] = matched
        return (ids, np.float32(self.boost)), ("nested", self.path, width)

    def device_eval(self, dev, params, ctx):
        ids, boost = params
        n1 = ctx.num_docs + 1
        tgt = jnp.where(ids >= 0, ids, ctx.num_docs)
        match = jnp.zeros(n1, bool).at[tgt].set(ids >= 0)
        match = match.at[ctx.num_docs].set(False)
        score = jnp.where(match, boost, 0.0)
        return score, match


def parse_nested(body, mappings) -> NestedNode:
    if not isinstance(body, dict):
        raise QueryParsingError("[nested] expects an object")
    path = body.get("path")
    query = body.get("query")
    if not path or not isinstance(query, dict):
        raise QueryParsingError("[nested] requires [path] and [query]")
    if path not in getattr(mappings, "nested_paths", set()):
        raise QueryParsingError(
            f"[nested] failed to find nested object under path [{path}]")
    return NestedNode(path=path, query=query, mappings=mappings,
                      boost=float(body.get("boost", 1.0)))
