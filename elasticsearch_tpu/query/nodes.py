"""Query plan nodes: host-side prepare + device-side evaluation.

The reference compiles its JSON Query DSL through `QueryBuilder.toQuery()`
into Lucene `Query`/`Weight`/`Scorer` trees pulled doc-at-a-time (reference:
server/.../index/query/AbstractQueryBuilder.java, BoolQueryBuilder.java).
Here every node instead evaluates to a pair of dense device arrays

    (scores[N+1] float32, match[N+1] bool)

over the whole shard, and boolean composition is elementwise arithmetic —
the natural XLA shape: no iterators, no branches, fused by the compiler.

Protocol:
  prepare(pack)  -> (params pytree of numpy arrays, structural cache key)
     host work: term-dict lookups, idf, block-row padding to pow2 buckets.
     The cache key captures everything that changes the traced computation
     (node types, fields, bucket sizes) but NOT term values, so repeated
     queries with the same shape reuse the compiled executable.
  device_eval(dev, params, ctx) -> (scores, match)
     pure-jnp, called inside jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..index.pack import ShardPack
from ..ops.scoring import DEAD_SLOT_PAD, bm25_idf, term_score_blocks

MIN_BUCKET = 4


def _bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _pad_rows(start: int, count: int) -> np.ndarray:
    """Block-row list padded to a pow2 bucket with the reserved padding row 0."""
    b = _bucket(count)
    rows = np.zeros(b, dtype=np.int32)
    rows[:count] = np.arange(start, start + count, dtype=np.int32)
    return rows


@dataclass
class ExecContext:
    """Static per-pack info available during tracing.

    k1/b apply to the sparse CSR scoring path only; dense-tier tfn rows bake
    the BM25 defaults at pack build (index/pack.py BM25_K1/BM25_B), so
    non-default values require building the pack with the dense tier
    disabled (dense_min_df large) — enforced by the searchers."""

    num_docs: int
    avgdl: dict[str, float]
    has_norms: frozenset[str]
    k1: float = 1.2
    b: float = 0.75
    # True when per-shard partials will be merged host-side: agg nodes then
    # emit mergeable forms (bitmaps, sorted arrays) instead of final values
    sharded: bool = False


class QueryNode:
    boost: float = 1.0

    def prepare(self, pack: ShardPack) -> tuple[Any, tuple]:
        raise NotImplementedError

    def device_eval(self, dev: dict, params: Any, ctx: ExecContext):
        raise NotImplementedError


@dataclass
class TermNode(QueryNode):
    """Exact term match with BM25 scoring (reference behavior:
    index/query/TermQueryBuilder.java -> Lucene TermQuery).

    When the pack carries the impact-scored sparse tier (BM25S,
    index/pack.py) and nothing demands exact scores, evaluation is a pure
    gather+sum over quantized impact codes: idf (from the ONE bm25_idf
    implementation, effective dfs stats included) folds into a host-side
    scalar and no tf/dl/avgdl math is traced. `exact_scores` (set by
    mark_exact for explain / scripted similarity) and non-default
    ctx.k1/b fall back to the raw-postings path at trace time."""

    fld: str
    term: str
    boost: float = 1.0
    exact_scores: bool = False
    _dense: bool = False

    def prepare(self, pack):
        start, count, df = pack.term_blocks(self.fld, self.term)
        if df > 0:
            doc_count = pack.field_stats.get(self.fld, {}).get("doc_count") or pack.num_docs
            weight = np.float32(self.boost * bm25_idf(doc_count, df))
        else:
            weight = np.float32(0.0)
        dr = pack.dense_row_of(self.fld, self.term)
        self._dense = dr is not None
        # avgdl rides as a runtime param (not a trace constant) so compiled
        # plans survive stat drift as tiered refreshes add documents
        avgdl = np.float32(pack.avgdl(self.fld))
        if self._dense:
            return (np.int32(dr), weight, avgdl), ("term_dense", self.fld)
        rows = _pad_rows(start, count)
        if not self.exact_scores:
            from ..ops.scoring import impact_enabled

            isc = (pack.impact_wscale(self.fld, self.term)
                   if impact_enabled() else None)
            if isc is not None:
                # wscale = boost·idf·ubf/qmax — score = wscale · code
                return (rows, weight, avgdl, np.float32(weight * isc)), (
                    "term_imp", self.fld, len(rows))
        return (rows, weight, avgdl), ("term", self.fld, len(rows))

    def device_eval(self, dev, params, ctx):
        if self._dense:
            from ..ops.scoring import dense_term_scores

            dr, weight, _avgdl = params
            return dense_term_scores(dev["dense_tfn"][dr], weight, ctx.num_docs)
        if len(params) == 5:
            # inline postings: WAND doc-level pruning compacts survivors
            # host-side into synthetic blocks (query/wand.prune_postings)
            from ..ops.scoring import score_posting_arrays

            docids, tfs, dls, weight, avgdl = params
            return score_posting_arrays(
                docids, tfs, dls, weight, avgdl, ctx.num_docs,
                ctx.k1, ctx.b,
                has_norms=self.fld in ctx.has_norms,
            )
        if len(params) == 4:
            rows, weight, avgdl, wscale = params
            from ..index.pack import BM25_B, BM25_K1
            from ..ops.scoring import impact_term_scores

            if ("impact_codes" in dev
                    and (ctx.k1, ctx.b) == (BM25_K1, BM25_B)):
                return impact_term_scores(
                    dev["impact_codes"], dev["post_docids"], rows, wscale,
                    ctx.num_docs)
            # escalation: custom k1/b (scripted similarity contexts) or a
            # searcher without resident codes — raw-postings BM25
            params = (rows, weight, avgdl)
        rows, weight, avgdl = params
        return term_score_blocks(
            dev["post_docids"],
            dev["post_tfs"],
            dev["post_dls"],
            rows,
            weight,
            avgdl,
            ctx.num_docs,
            ctx.k1,
            ctx.b,
            has_norms=self.fld in ctx.has_norms,
        )


@dataclass
class MatchAllNode(QueryNode):
    boost: float = 1.0

    def prepare(self, pack):
        return (np.float32(self.boost),), ("match_all",)

    def device_eval(self, dev, params, ctx):
        (boost,) = params
        n1 = ctx.num_docs + 1
        return jnp.full(n1, boost, jnp.float32), jnp.ones(n1, bool)


@dataclass
class MatchNoneNode(QueryNode):
    boost: float = 1.0

    def prepare(self, pack):
        return (), ("match_none",)

    def device_eval(self, dev, params, ctx):
        n1 = ctx.num_docs + 1
        return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)


@dataclass
class RangeNode(QueryNode):
    """Range over numeric/date/keyword docvalues; constant score = boost
    (reference behavior: index/query/RangeQueryBuilder.java — point/DV range
    queries score constantly)."""

    fld: str
    lo: float | int | None
    hi: float | int | None
    include_lo: bool = True
    include_hi: bool = True
    boost: float = 1.0
    kind: str = "int"  # int | float | ord

    def prepare(self, pack):
        col = pack.docvalues.get(self.fld)
        dtype = np.int64 if self.kind in ("int", "ord") else np.float32
        info_min = np.iinfo(np.int64).min if dtype == np.int64 else -np.inf
        info_max = np.iinfo(np.int64).max if dtype == np.int64 else np.inf
        lo = info_min if self.lo is None else self.lo
        hi = info_max if self.hi is None else self.hi
        params = (
            np.asarray(lo, dtype),
            np.asarray(hi, dtype),
            np.asarray(self.include_lo),
            np.asarray(self.include_hi),
            np.float32(self.boost),
        )
        return params, ("range", self.fld, self.kind, col is None)

    def device_eval(self, dev, params, ctx):
        lo, hi, inc_lo, inc_hi, boost = params
        n1 = ctx.num_docs + 1
        kinds = {"int": "dv_int", "float": "dv_float", "ord": "dv_ord"}
        store = dev[kinds[self.kind]]
        if self.fld not in store:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        vals, has = store[self.fld]
        above = jnp.where(inc_lo, vals >= lo, vals > lo)
        below = jnp.where(inc_hi, vals <= hi, vals < hi)
        m = has & above & below
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(m)
        return boost * match.astype(jnp.float32), match


@dataclass
class TermsNode(QueryNode):
    """`terms` query: doc matches any of the values; constant score = boost
    (reference behavior: index/query/TermsQueryBuilder.java -> Lucene
    TermInSetQuery under ConstantScore)."""

    fld: str
    values: list
    boost: float = 1.0
    kind: str = "ord"  # ord | int | float

    def prepare(self, pack):
        col = pack.docvalues.get(self.fld)
        if self.kind == "ord":
            terms = col.ord_terms if col is not None else []
            ord_of = {t: i for i, t in enumerate(terms)}
            ids = [ord_of[v] for v in map(str, self.values) if v in ord_of]
            arr = np.full(_bucket(max(len(ids), 1)), -2, dtype=np.int64)
            arr[: len(ids)] = ids
        else:
            dtype = np.int64 if self.kind == "int" else np.float32
            arr = np.full(_bucket(max(len(self.values), 1)), np.iinfo(np.int64).min + 1 if dtype == np.int64 else np.nan, dtype=dtype)
            arr[: len(self.values)] = [v for v in self.values]
        return (arr, np.float32(self.boost)), ("terms", self.fld, self.kind, len(arr), col is None)

    def device_eval(self, dev, params, ctx):
        arr, boost = params
        n1 = ctx.num_docs + 1
        kinds = {"int": "dv_int", "float": "dv_float", "ord": "dv_ord"}
        store = dev[kinds[self.kind]]
        if self.fld not in store:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        vals, has = store[self.fld]
        if self.kind == "ord":
            vals = vals.astype(jnp.int64)
        m = has & (vals[:, None] == arr[None, :]).any(axis=1)
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(m)
        return boost * match.astype(jnp.float32), match


@dataclass
class ExistsNode(QueryNode):
    fld: str
    boost: float = 1.0

    def prepare(self, pack):
        has_dv = (
            self.fld in pack.docvalues
            or self.fld in pack.vectors
            or self.fld in pack.text_present
        )
        return (np.float32(self.boost),), ("exists", self.fld, has_dv)

    def device_eval(self, dev, params, ctx):
        (boost,) = params
        n1 = ctx.num_docs + 1
        m = None
        for store_key in ("dv_int", "dv_float", "dv_ord"):
            if self.fld in dev[store_key]:
                m = dev[store_key][self.fld][1]
                break
        if m is None and self.fld in dev.get("vec_has", {}):
            m = dev["vec_has"][self.fld]
        if m is None and self.fld in dev["text_has"]:
            m = dev["text_has"][self.fld]
        if m is None:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(m)
        return boost * match.astype(jnp.float32), match


@dataclass
class ConstantScoreNode(QueryNode):
    child: QueryNode = None
    boost: float = 1.0

    def prepare(self, pack):
        cp, ck = self.child.prepare(pack)
        return (cp, np.float32(self.boost)), ("const", ck)

    def device_eval(self, dev, params, ctx):
        cp, boost = params
        _, m = self.child.device_eval(dev, cp, ctx)
        return boost * m.astype(jnp.float32), m


@dataclass
class DisMaxNode(QueryNode):
    """Max over children + tie_breaker * sum(rest) (reference behavior:
    index/query/DisMaxQueryBuilder.java)."""

    children: list = dc_field(default_factory=list)
    tie_breaker: float = 0.0
    boost: float = 1.0

    def prepare(self, pack):
        parts = [c.prepare(pack) for c in self.children]
        return (
            tuple(p for p, _ in parts),
            np.float32(self.tie_breaker),
            np.float32(self.boost),
        ), ("dismax", tuple(k for _, k in parts))

    def device_eval(self, dev, params, ctx):
        child_params, tie, boost = params
        n1 = ctx.num_docs + 1
        best = jnp.zeros(n1, jnp.float32)
        total = jnp.zeros(n1, jnp.float32)
        match = jnp.zeros(n1, bool)
        for c, p in zip(self.children, child_params):
            s, m = c.device_eval(dev, p, ctx)
            best = jnp.maximum(best, s)
            total = total + s
            match = match | m
        score = boost * (best + tie * (total - best))
        return jnp.where(match, score, 0.0), match


@dataclass
class KnnNode(QueryNode):
    """Exact k-nearest-neighbor retrieval (reference behavior:
    search/vectors/KnnVectorQueryBuilder.java:54 + KnnSearchBuilder.java:44 —
    per-shard top num_candidates then global k). Here the scan is exact, so
    num_candidates only caps the per-shard match set; an optional filter is
    applied BEFORE neighbor selection (ES pre-filtering semantics)."""

    fld: str = ""
    qvec: list | None = None
    k: int = 10
    num_candidates: int | None = None
    filter_node: QueryNode | None = None
    boost: float = 1.0
    similarity_threshold: float | None = None
    # ANN controls: explicit probe count (None -> the dynamic index
    # setting / coverage heuristic); force_exact is the engine's
    # too-selective-filter escalation switch (recompiles to the scan)
    nprobe: int | None = None
    force_exact: bool = False
    _sim: str = "cosine"

    # filtered/thresholded ANN: retrieve this many times num_candidates
    # before post-filtering, so a moderately selective filter still
    # reaches k (the reference's filtered-HNSW over-probing analog)
    FILTER_OVERSAMPLE = 4

    def prepare(self, pack):
        vc = pack.vectors.get(self.fld)
        fp, fk = (None, None)
        if self.filter_node is not None:
            fp, fk = self.filter_node.prepare(pack)
        qv = np.zeros(vc.dims if vc else 1, np.float32)
        if vc is not None:
            if len(self.qvec) != vc.dims:
                from ..utils.errors import IllegalArgumentError

                raise IllegalArgumentError(
                    f"knn query vector has {len(self.qvec)} dims, field [{self.fld}] has {vc.dims}"
                )
            qv = np.asarray(self.qvec, np.float32)
        # trace-time constants consumed by device_eval; set ONLY here so the
        # struct key below always describes the plan that gets traced
        self._kk = min(self.num_candidates or self.k, max(pack.num_docs, 1))
        if vc is not None:
            self._sim = vc.similarity
        # device-resident ANN path (ann/): centroid probe + quantized
        # gather-scan + f32 rescore of survivors, all inside the compiled
        # plan. Filters/thresholds ride it with oversampled candidate
        # retrieval + post-filter; the engine re-prepares with
        # force_exact when the filtered result can't reach k.
        self._ann = None
        ann = getattr(vc, "ann", None) if vc is not None else None
        if ann is not None and not self.force_exact:
            from ..ann.search import default_nprobe

            C = int(ann["nlist"])
            L = int(ann["tile"])
            oversample = (self.FILTER_OVERSAMPLE
                          if (self.filter_node is not None
                              or self.similarity_threshold is not None)
                          else 1)
            nprobe = self.nprobe or default_nprobe(
                C, L, self._kk * oversample)
            nprobe = max(1, min(int(nprobe), C))
            if not self.nprobe:
                # PR 18: with planner.knn.target_ms set (and the scan
                # kernel's efficiency EMA warm), trade the coverage
                # heuristic for the LARGEST probe count whose predicted
                # gather-scan wall meets the latency target — recall
                # buys latency headroom instead of leaving it idle. An
                # explicit per-request nprobe is always respected.
                from ..planner import execution_planner

                nprobe = execution_planner().advise_nprobe(
                    nprobe, C, {"queries": 1, "dims": int(vc.dims),
                                "tile": L, "scan_tier": vc.ann_quant})
            kcand = min(nprobe * L, max(self._kk * oversample, self._kk))
            self._ann = (nprobe, kcand, vc.ann_quant)
            from ..telemetry import profile_event

            profile_event("tier", tier=f"ann_{vc.ann_quant}", queries=1,
                          nprobe=nprobe, kcand=kcand)
        return (qv, np.float32(self.boost), fp), (
            "knn", self.fld, vc is None, self._kk, self._sim,
            self.similarity_threshold, fk, self._ann,
        )

    def _score_threshold(self) -> float:
        """ES expresses `similarity` in the raw metric space; convert to the
        _score space the kernel compares against (reference behavior:
        VectorSimilarityQuery score translation)."""
        t = self.similarity_threshold
        if self._sim in ("cosine", "dot_product"):
            return (1.0 + t) / 2.0
        if self._sim == "l2_norm":
            return 1.0 / (1.0 + t * t)
        if self._sim == "max_inner_product":
            return 1.0 / (1.0 - t) if t < 0 else t + 1.0
        return t

    def device_eval(self, dev, params, ctx):
        from ..ops.vector import knn_scores

        qv, boost, fp = params
        n1 = ctx.num_docs + 1
        if self.fld not in dev["vec"]:
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        vecs = dev["vec"][self.fld]
        has = dev["vec_has"][self.fld]
        if self._ann is not None and self.fld in dev.get("vec_ann", {}):
            # ANN: quantized gather-scan of the probed cluster tiles
            # selects candidates; only they are rescored in f32 and
            # scattered into the dense accumulator
            from ..ann.kernels import ann_candidates_traced

            nprobe, kcand, tier = self._ann
            cand, sel_v, _tot = ann_candidates_traced(
                dev["vec_ann"][self.fld], qv, dev["live"], kcand,
                nprobe=nprobe, tier=tier, similarity=self._sim,
            )
            ok_cand = jnp.isfinite(sel_v)
            safe = jnp.where(cand >= 0, cand, 0)
            sub_scores = knn_scores(
                vecs[safe], dev["vec_sq"][self.fld][safe], qv, self._sim
            )
            tgt = jnp.where(ok_cand, cand, ctx.num_docs)
            scores_n1 = jnp.zeros(n1, jnp.float32).at[tgt].set(
                jnp.where(ok_cand, sub_scores, 0.0))
            in_cand = jnp.zeros(n1, bool).at[tgt].set(ok_cand)
            scores = scores_n1[: ctx.num_docs]
            ok = in_cand[: ctx.num_docs] & has & dev["live"]
        else:
            scores = knn_scores(vecs, dev["vec_sq"][self.fld], qv, self._sim)
            ok = has & dev["live"]
        if self.filter_node is not None:
            _, fm = self.filter_node.device_eval(dev, fp, ctx)
            ok = ok & fm[: ctx.num_docs]
        if self.similarity_threshold is not None:
            ok = ok & (scores >= self._score_threshold())
        masked = jnp.where(ok, scores, -jnp.inf)
        kth = jax.lax.top_k(masked, self._kk)[0][-1]
        match_n = ok & (masked >= kth) & jnp.isfinite(masked)
        match = jnp.zeros(n1, bool).at[: ctx.num_docs].set(match_n)
        score = jnp.zeros(n1, jnp.float32).at[: ctx.num_docs].set(
            jnp.where(match_n, boost * scores, 0.0)
        )
        return score, match


def mark_exact(node) -> "QueryNode":
    """Force exact BM25 scoring on every term in a plan tree — the
    impact-tier escalation switch for features a quantized score cannot
    serve: explain's per-clause breakdown, scripted similarity
    (script_score/function_score read the child's _score), rescore
    windows. Returns the node for chaining."""
    if isinstance(node, TermNode):
        node.exact_scores = True
    elif isinstance(node, BoolNode):
        for grp in (node.must, node.filter, node.should, node.must_not):
            for c in grp:
                mark_exact(c)
    elif isinstance(node, DisMaxNode):
        for c in node.children:
            mark_exact(c)
    elif isinstance(node, ConstantScoreNode):
        if node.child is not None:
            mark_exact(node.child)
    else:
        for attr in ("inner", "child", "filter_node"):
            c = getattr(node, attr, None)
            if isinstance(c, QueryNode):
                mark_exact(c)
    return node


MAX_CLAUSE_COUNT = 4096  # reference behavior: indices.query.bool.max_clause_count


@dataclass
class PhraseNode(QueryNode):
    """Exact phrase match (reference behavior: index/query/MatchPhraseQueryBuilder
    -> Lucene PhraseQuery, slop=0). TPU shape: positions are blocked sorted
    int64 keys (docid*POS_L + position); phrase matching is an m-way sorted-set
    intersection — the rarest term's keys probe each other term's key set via
    vectorized binary search (searchsorted), offset by the phrase positions.
    Phrase frequency (occurrence count per doc) feeds BM25 with the summed
    per-term idf, matching Lucene's PhraseQuery/BM25 scoring."""

    fld: str = ""
    terms: list = dc_field(default_factory=list)  # [(term, rel_position)]
    boost: float = 1.0
    slop: int = 0
    _no_pos: bool = False

    def prepare(self, pack):
        from ..utils.errors import IllegalArgumentError

        if self.slop != 0:
            raise IllegalArgumentError("[match_phrase] slop > 0 is not supported yet")
        stacked = getattr(pack, "stacked", None)
        pos = stacked.pos_keys if stacked is not None else getattr(pack, "pos_keys", None)
        self._no_pos = pos is None
        if self._no_pos:
            # no text tokens indexed anywhere -> nothing can match
            return (), ("phrase_empty", self.fld)
        doc_count = pack.field_stats.get(self.fld, {}).get("doc_count") or pack.num_docs
        idf_sum = 0.0
        infos = []
        for term, off in self.terms:
            ps, nb, cnt = pack.term_pos_blocks(self.fld, term)
            _s, _n, df = pack.term_blocks(self.fld, term)
            if df > 0:
                idf_sum += bm25_idf(doc_count, df)
            infos.append((ps, nb, cnt, off))
        # rarest term first: its positions become the probe set
        infos.sort(key=lambda x: x[2])
        rows = tuple(_pad_rows(ps, nb) for ps, nb, _c, _o in infos)
        offsets = np.array([o for _s, _n, _c, o in infos], np.int64)
        weight = np.float32(self.boost * idf_sum)
        return (rows, offsets, weight, np.float32(pack.avgdl(self.fld))), (
            "phrase", self.fld, tuple(len(r) for r in rows),
        )

    def device_eval(self, dev, params, ctx):
        from ..index.pack import POS_INF, POS_L

        if self._no_pos:
            n1 = ctx.num_docs + DEAD_SLOT_PAD
            return jnp.zeros(n1, jnp.float32), jnp.zeros(n1, bool)
        rows, offsets, weight, avgdl = params
        n = ctx.num_docs
        n1 = n + DEAD_SLOT_PAD
        pos_keys = dev["pos_keys"]
        probe = pos_keys[rows[0]].reshape(-1)  # sorted; POS_INF padding
        base = probe - offsets[0]
        alive = probe < POS_INF
        for i in range(1, len(rows)):
            table = pos_keys[rows[i]].reshape(-1)
            want = base + offsets[i]
            idx = jnp.searchsorted(table, want)
            hit = table[jnp.minimum(idx, table.shape[0] - 1)] == want
            alive = alive & hit
        ids = jnp.where(alive, (base // POS_L).astype(jnp.int32), n)
        phrase_tf = jnp.zeros(n1, jnp.float32).at[ids].add(
            jnp.where(alive, 1.0, 0.0), mode="drop"
        )
        tf = phrase_tf[:n]
        if self.fld in ctx.has_norms:
            dl = dev["norms"][self.fld]
            denom = tf + ctx.k1 * (1.0 - ctx.b + ctx.b * dl / avgdl)
        else:
            denom = tf + ctx.k1
        scores_n = jnp.where(tf > 0, weight * tf / denom, 0.0)
        scores = jnp.zeros(n1, jnp.float32).at[:n].set(scores_n)
        match = jnp.zeros(n1, bool).at[:n].set(tf > 0)
        return scores, match


@dataclass
class ExpandedTermsNode(QueryNode):
    """Multi-term query rewritten by host-side term-dictionary expansion
    (reference behavior: index/query/{Prefix,Wildcard,Regexp,Fuzzy}QueryBuilder
    -> Lucene MultiTermQuery; the dictionary enum runs host-side like Lucene's
    FST walk, the doc-set union runs on device).

    scored=False (prefix/wildcard/regexp): constant_score rewrite — every
    matching doc scores `boost`, like ES's default CONSTANT_SCORE rewrite.
    scored=True (fuzzy): each expanded term scores BM25 with its own idf and
    a per-term multiplier from `term_boost` (e.g. edit-distance decay).
    Divergence from Lucene's TopTermsBlendedFreq rewrite: per-term scores sum
    (bool-should semantics) instead of blending df across expanded terms.
    """

    kind: str = ""  # "prefix" | "wildcard" | "regexp" | "fuzzy" (cache tag)
    fld: str = ""
    matcher: Any = None  # host predicate: term -> False | True | weight-mult
    boost: float = 1.0
    scored: bool = False
    max_expansions: int | None = None  # cap on expanded terms (fuzzy: 50)

    def prepare(self, pack):
        from ..utils.errors import IllegalArgumentError

        expanded = []  # (term, multiplier)
        for t in pack.terms_for_field(self.fld):
            m = self.matcher(t)
            if m:
                expanded.append((t, 1.0 if m is True else float(m)))
        if self.max_expansions is not None and len(expanded) > self.max_expansions:
            # keep highest-df terms, like Lucene's top-terms rewrites
            expanded.sort(key=lambda tm: -pack.term_blocks(self.fld, tm[0])[2])
            expanded = expanded[: self.max_expansions]
        if len(expanded) > MAX_CLAUSE_COUNT:
            raise IllegalArgumentError(
                f"[{self.kind}] on [{self.fld}] expands to {len(expanded)} terms, "
                f"more than max_clause_count [{MAX_CLAUSE_COUNT}]"
            )
        doc_count = pack.field_stats.get(self.fld, {}).get("doc_count") or pack.num_docs
        rows_list, w_list = [], []
        for t, mult in expanded:
            s0, nb, df = pack.term_blocks(self.fld, t)
            if nb == 0:
                continue
            w = self.boost * mult * bm25_idf(doc_count, df) if self.scored else 1.0
            rows_list.extend(range(s0, s0 + nb))
            w_list.extend([w] * nb)
        r = max(len(rows_list), 1)
        width = 1 << (r - 1).bit_length()
        rows = np.zeros(width, np.int32)
        ws = np.zeros(width, np.float32)
        rows[: len(rows_list)] = rows_list
        ws[: len(w_list)] = w_list
        return (rows, ws, np.float32(self.boost), np.float32(pack.avgdl(self.fld))), (
            self.kind, self.fld, self.scored, width,
        )

    def device_eval(self, dev, params, ctx):
        rows, ws, boost, avgdl = params
        n1 = ctx.num_docs + DEAD_SLOT_PAD
        docids = dev["post_docids"][rows]  # [R, 128]
        tfs = dev["post_tfs"][rows]
        flat_ids = docids.reshape(-1)
        if not self.scored:
            match = jnp.zeros(n1, bool).at[flat_ids].set((tfs > 0).reshape(-1), mode="drop")
            match = match.at[ctx.num_docs].set(False)
            return jnp.where(match, boost, 0.0), match
        has_norms = self.fld in ctx.has_norms
        if has_norms:
            dls = dev["post_dls"][rows]
            denom = tfs + ctx.k1 * (1.0 - ctx.b + ctx.b * dls / avgdl)
        else:
            denom = tfs + ctx.k1
        lane_scores = ws[:, None] * tfs / denom
        scores = jnp.zeros(n1, jnp.float32).at[flat_ids].add(
            lane_scores.reshape(-1), mode="drop"
        )
        match = jnp.zeros(n1, bool).at[flat_ids].set((tfs > 0).reshape(-1), mode="drop")
        return scores, match


@dataclass
class PinnedScoresNode(QueryNode):
    """Matches a fixed (shard, docid) -> score set — the engine rewrites the
    knn section of a hybrid search to one of these holding the GLOBAL top-k
    knn hits (reference behavior: KnnSearchBuilder/KnnScoreDocQueryBuilder —
    per-shard num_candidates retrieval, then the global-k ScoreDocs become a
    query clause combined with the user query)."""

    per_shard: list = dc_field(default_factory=list)  # [(ids i32[m], scores f32[m])]

    def prepare(self, pack):
        s = getattr(pack, "shard_index", 0)
        n = pack.num_docs
        width = max((len(ids) for ids, _ in self.per_shard), default=0)
        width = max(width, 1)
        ids = np.full(width, n, np.int32)  # pad -> dead slot
        scs = np.zeros(width, np.float32)
        if self.per_shard:
            sids, sscs = self.per_shard[s]
            ids[: len(sids)] = sids
            scs[: len(sscs)] = sscs
        return (ids, scs), ("pinned", width)

    def device_eval(self, dev, params, ctx):
        ids, scs = params
        n1 = ctx.num_docs + 1
        scores = jnp.zeros(n1, jnp.float32).at[ids].set(scs, mode="drop")
        match = jnp.zeros(n1, bool).at[ids].set(True, mode="drop")
        return scores, match.at[ctx.num_docs].set(False)


@dataclass
class BoolNode(QueryNode):
    """Boolean composition (reference behavior:
    index/query/BoolQueryBuilder.java — must/filter/should/must_not with
    minimum_should_match; should is optional when must/filter present)."""

    must: list = dc_field(default_factory=list)
    filter: list = dc_field(default_factory=list)
    should: list = dc_field(default_factory=list)
    must_not: list = dc_field(default_factory=list)
    minimum_should_match: int | None = None
    boost: float = 1.0

    def _msm(self) -> int:
        if self.minimum_should_match is not None:
            return self.minimum_should_match
        if self.should and not (self.must or self.filter):
            return 1
        return 0

    def prepare(self, pack):
        groups = []
        keys = []
        for grp in (self.must, self.filter, self.should, self.must_not):
            parts = [c.prepare(pack) for c in grp]
            groups.append(tuple(p for p, _ in parts))
            keys.append(tuple(k for _, k in parts))
        return (tuple(groups), np.float32(self.boost)), (
            "bool",
            tuple(keys),
            self._msm(),
        )

    def device_eval(self, dev, params, ctx):
        groups, boost = params
        must_p, filter_p, should_p, not_p = groups
        n1 = ctx.num_docs + 1
        score = jnp.zeros(n1, jnp.float32)
        ok = jnp.ones(n1, bool)
        any_clause = bool(self.must or self.filter or self.should)
        for c, p in zip(self.must, must_p):
            s, m = c.device_eval(dev, p, ctx)
            score = score + s
            ok = ok & m
        for c, p in zip(self.filter, filter_p):
            _, m = c.device_eval(dev, p, ctx)
            ok = ok & m
        msm = self._msm()
        if self.should:
            cnt = jnp.zeros(n1, jnp.int32)
            for c, p in zip(self.should, should_p):
                s, m = c.device_eval(dev, p, ctx)
                score = score + s
                cnt = cnt + m.astype(jnp.int32)
            if msm > 0:
                ok = ok & (cnt >= msm)
        for c, p in zip(self.must_not, not_p):
            _, m = c.device_eval(dev, p, ctx)
            ok = ok & ~m
        if not any_clause and not self.must_not:
            pass  # empty bool matches everything (ok already all-true)
        score = jnp.where(ok, boost * score, 0.0)
        return score, ok
