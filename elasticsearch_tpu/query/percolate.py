"""Percolate query: reverse search over stored queries.

Parity target: modules/percolator (reference behavior:
PercolateQueryBuilder.java — stored queries in `percolator` fields are run
against an in-memory index of the candidate document(s); matching query-docs
become hits). Here each shard keeps its stored queries host-side
(pack.percolator); at percolate time the candidate documents build a tiny
pack once, every stored query runs against it, and the matching query-doc
ids feed the device as an explicit id set — so percolate composes with any
enclosing bool query like a normal clause."""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp
import numpy as np

from ..utils.errors import IllegalArgumentError
from .nodes import QueryNode


def _bucket(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length() if n > 1 else 1


@dataclass
class PercolateNode(QueryNode):
    fld: str = ""
    documents: list = dc_field(default_factory=list)
    mappings: object = None
    boost: float = 1.0
    _matcher: object = None

    def _ensure_matcher(self):
        if self._matcher is not None:
            return
        from ..index.pack import PackBuilder
        from ..query.executor import ShardSearcher

        b = PackBuilder(self.mappings, use_native=False)
        for d in self.documents:
            b.add_document(self.mappings.parse_document(d))
        pack = b.build(dense_min_df=1 << 62)
        self._matcher = ShardSearcher(pack, mappings=self.mappings)

    def _query_matches(self, qdict) -> bool:
        try:
            return self._matcher.count(qdict) > 0
        except Exception:  # noqa: BLE001 - malformed stored query never matches
            return False

    def prepare(self, pack):
        real = getattr(pack, "pack", pack)
        stored = real.percolator.get(self.fld, [])
        self._ensure_matcher()
        matched = [docid for docid, q in stored if self._query_matches(q)]
        width = _bucket(max(len(matched), 1))
        ids = np.full(width, -1, np.int32)
        ids[: len(matched)] = matched
        return (ids, np.float32(self.boost)), ("percolate", self.fld, width)

    def device_eval(self, dev, params, ctx):
        ids, boost = params
        n1 = ctx.num_docs + 1
        tgt = jnp.where(ids >= 0, ids, ctx.num_docs)  # pad -> dead slot
        match = jnp.zeros(n1, bool).at[tgt].set(ids >= 0)
        match = match.at[ctx.num_docs].set(False)
        score = jnp.where(match, boost, 0.0)
        return score, match


def parse_percolate(body, mappings) -> PercolateNode:
    if not isinstance(body, dict):
        raise IllegalArgumentError("[percolate] expects an object")
    fld = body.get("field")
    if not fld:
        raise IllegalArgumentError("[percolate] requires [field]")
    docs = body.get("documents")
    if docs is None:
        doc = body.get("document")
        if doc is None:
            raise IllegalArgumentError("[percolate] requires [document] or [documents]")
        docs = [doc]
    return PercolateNode(
        fld=fld, documents=list(docs), mappings=mappings,
        boost=float(body.get("boost", 1.0)),
    )
