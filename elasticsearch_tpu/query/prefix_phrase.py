"""match_phrase_prefix execution: phrase with an expanded last term.

The last term expands against the field's term dictionary at prepare time
(bounded by max_expansions, like the reference's MultiPhrasePrefixQuery);
the node evaluates the per-expansion phrases on device and takes the best
score per doc (dis_max semantics over complete phrases)."""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp

from .nodes import DisMaxNode, MatchNoneNode, PhraseNode, QueryNode


@dataclass
class PhrasePrefixNode(QueryNode):
    fld: str = ""
    terms: list = dc_field(default_factory=list)  # [(term, position)] head
    prefix: str = ""
    prefix_position: int = 0
    max_expansions: int = 50
    boost: float = 1.0
    _inner: QueryNode | None = None

    def prepare(self, pack):
        # expansions must be GLOBAL so every shard's traced program has the
        # same structure (stacked shard params stack leaf-wise)
        stacked = getattr(pack, "stacked", None)
        if stacked is not None:
            all_terms = sorted({
                t for p in stacked.shards for t in p.terms_for_field(self.fld)
            })
        else:
            all_terms = getattr(pack, "pack", pack).terms_for_field(self.fld)
        lo = bisect.bisect_left(all_terms, self.prefix)
        expansions = []
        for i in range(lo, len(all_terms)):
            if not all_terms[i].startswith(self.prefix):
                break
            expansions.append(all_terms[i])
            if len(expansions) >= self.max_expansions:
                break
        if not expansions:
            self._inner = MatchNoneNode()
        else:
            self._inner = DisMaxNode(children=[
                PhraseNode(self.fld, self.terms + [(t, self.prefix_position)],
                           boost=self.boost)
                for t in expansions
            ])
        params, key = self._inner.prepare(pack)
        return params, ("phrase_prefix", self.fld, key)

    def device_eval(self, dev, params, ctx):
        return self._inner.device_eval(dev, params, ctx)
