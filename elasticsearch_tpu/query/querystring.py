"""Lucene query-string syntax -> Query DSL dicts.

Parity targets: index/query/QueryStringQueryBuilder.java (full syntax,
errors on malformed input) and index/query/SimpleQueryStringBuilder.java
(forgiving operator subset, never throws). Both compile to the existing DSL
dict shapes, so everything downstream (nodes, device eval) is shared.

query_string grammar (the commonly-used subset):
    query    := clause+                      (implicit default_operator)
    clause   := [+|-] [field ':'] atom ['^' boost]
    atom     := '(' query ')' | '"' phrase '"' ['~' slop]
              | range | term ['~' fuzz] | wildcard
    range    := ('[' | '{') val TO val (']' | '}')  | ('>'|'>='|'<'|'<=') val
    special  := _exists_:field | field:* | AND | OR | NOT
"""

from __future__ import annotations

import re

from ..utils.errors import QueryParsingError

_TOKEN_RE = re.compile(
    r"""\s*(?:
      (?P<lparen>\() | (?P<rparen>\)) |
      (?P<quoted>"(?:[^"\\]|\\.)*") |
      (?P<range>(?:[A-Za-z0-9_.\-]+:)?[\[\{][^\]\}]*?\sTO\s[^\]\}]*?[\]\}]) |
      (?P<and>AND\b) | (?P<or>OR\b) | (?P<not>NOT\b) |
      (?P<plus>\+) | (?P<minus>-) |
      (?P<term>[^\s()"]+)
    )""",
    re.VERBOSE,
)


def _tokenize_qs(text: str):
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == pos:
            if text[pos:].strip() == "":
                break
            raise QueryParsingError(f"Failed to parse query [{text}]")
        pos = m.end()
        for name in ("lparen", "rparen", "quoted", "range", "and", "or",
                     "not", "plus", "minus", "term"):
            if m.group(name) is not None:
                out.append((name, m.group(name)))
                break
    return out


_RANGE_OP = re.compile(r"^(>=|<=|>|<)(.+)$")


def _strip_boost(text: str):
    m = re.match(r"^(.*)\^(\d+(?:\.\d+)?)$", text)
    if m:
        return m.group(1), float(m.group(2))
    return text, None


def _strip_fuzz(text: str):
    m = re.match(r"^(.*?)~(\d*)$", text)
    if m and not m.group(1).endswith("\\"):
        return m.group(1), (m.group(2) or "AUTO")
    return text, None


def _atom_query(fld: str, text: str, default_fields, *, lenient=False) -> dict:
    """One bare atom (no +/-/grouping) against one field or the defaults."""
    if fld is None:
        if len(default_fields) == 1:
            fld = default_fields[0]
        else:
            body, _ = _strip_boost(text)
            body2, fuzz = _strip_fuzz(body)
            if ("*" in body or "?" in body or fuzz is not None
                    or body.startswith(("[", "{", ">", "<"))):
                # non-plain atoms expand per default field under dis_max
                return {"dis_max": {"queries": [
                    _atom_query(f, text, default_fields) for f in default_fields
                ]}}
            return {
                "multi_match": {"query": text.replace("\\", ""),
                                "fields": list(default_fields)}
            }
    body, boost = _strip_boost(text)
    m = _RANGE_OP.match(body)
    if m:
        op = {">": "gt", ">=": "gte", "<": "lt", "<=": "lte"}[m.group(1)]
        rng = {op: _maybe_number(m.group(2))}
        if boost:
            rng["boost"] = boost
        return {"range": {fld: rng}}
    if body.startswith(("[", "{")) and body.endswith(("]", "}")):
        inner = body[1:-1]
        lo, hi = re.split(r"\s+TO\s+", inner, maxsplit=1)
        rng = {}
        if lo.strip() != "*":
            rng["gte" if body[0] == "[" else "gt"] = _maybe_number(lo.strip())
        if hi.strip() != "*":
            rng["lte" if body[-1] == "]" else "lt"] = _maybe_number(hi.strip())
        if boost:
            rng["boost"] = boost
        return {"range": {fld: rng}}
    if body == "*":
        q = {"exists": {"field": fld}}
        return q
    body2, fuzz = _strip_fuzz(body)
    if fuzz is not None and body2:
        q = {"fuzzy": {fld: {"value": body2, "fuzziness": fuzz}}}
        if boost:
            q["fuzzy"][fld]["boost"] = boost
        return q
    if "*" in body or "?" in body:
        q = {"wildcard": {fld: {"value": body}}}
        if boost:
            q["wildcard"][fld]["boost"] = boost
        return q
    q = {"match": {fld: {"query": body.replace("\\", "")}}}
    if boost:
        q["match"][fld]["boost"] = boost
    return q


def _maybe_number(s: str):
    try:
        f = float(s)
        return int(f) if f.is_integer() and "." not in s and "e" not in s.lower() else f
    except ValueError:
        return s


class _QSParser:
    def __init__(self, tokens, default_fields, default_operator):
        self.toks = tokens
        self.pos = 0
        self.default_fields = default_fields
        self.op = default_operator.lower()

    def peek(self):
        return self.toks[self.pos] if self.pos < len(self.toks) else (None, None)

    def parse(self, depth=0) -> dict:
        must, should, must_not = [], [], []
        pending_op = None
        while True:
            kind, text = self.peek()
            if kind is None or kind == "rparen":
                break
            self.pos += 1
            if kind == "and":
                pending_op = "and"
                continue
            if kind == "or":
                pending_op = "or"
                continue
            if kind == "not":
                q = self._clause(depth)
                must_not.append(q)
                pending_op = None
                continue
            if kind == "plus":
                must.append(self._clause(depth))
                pending_op = None
                continue
            if kind == "minus":
                must_not.append(self._clause(depth))
                pending_op = None
                continue
            self.pos -= 1
            q = self._clause(depth)
            op = pending_op or self.op
            if op == "and":
                must.append(q)
            else:
                should.append(q)
            # explicit AND binds the NEXT clause too; keep the mode sticky
            # only for the operator the user wrote (Lucene behavior is
            # left-associative; this subset treats the whole level uniformly)
            pending_op = None
        if must and should:
            # mixed: OR-connected clauses group into one should-bool
            must.append({"bool": {"should": should, "minimum_should_match": 1}})
            should = []
        body = {}
        if must:
            body["must"] = must
        if should:
            body["should"] = should
            body["minimum_should_match"] = 1
        if must_not:
            body["must_not"] = must_not
        if not body:
            return {"match_all": {}}
        if list(body.keys()) == ["must"] and len(must) == 1:
            return must[0]
        if list(body.keys()) == ["should", "minimum_should_match"] and len(should) == 1:
            return should[0]
        return {"bool": body}

    def _clause(self, depth) -> dict:
        kind, text = self.peek()
        if kind is None:
            raise QueryParsingError("unexpected end of query string")
        self.pos += 1
        if kind == "lparen":
            q = self.parse(depth + 1)
            k2, _ = self.peek()
            if k2 != "rparen":
                raise QueryParsingError("missing closing paren in query string")
            self.pos += 1
            return q
        if kind == "quoted":
            phrase = text[1:-1].replace('\\"', '"')
            fld = None
            return self._phrase(fld, phrase)
        if kind == "term":
            # field:... prefix?
            m = re.match(r"^([A-Za-z0-9_.\-]+):(.*)$", text)
            if m and m.group(2) != "":
                fld, rest = m.group(1), m.group(2)
                if fld == "_exists_":
                    return {"exists": {"field": rest}}
                k2, t2 = self.peek()
                if rest == "" and k2 == "quoted":
                    self.pos += 1
                    return self._phrase(fld, t2[1:-1])
                if k2 == "quoted" and rest == "":
                    pass
                if rest.startswith('"') and rest.endswith('"') and len(rest) > 1:
                    return self._phrase(fld, rest[1:-1])
                if k2 == "range" and rest == "":
                    self.pos += 1
                    return _atom_query(fld, t2, self.default_fields)
                return _atom_query(fld, rest, self.default_fields)
            if m and m.group(2) == "":
                fld = m.group(1)
                k2, t2 = self.peek()
                if k2 in ("quoted", "range", "term"):
                    self.pos += 1
                    if k2 == "quoted":
                        return self._phrase(fld, t2[1:-1])
                    return _atom_query(fld, t2, self.default_fields)
                raise QueryParsingError(f"missing value for field [{fld}]")
            return _atom_query(None, text, self.default_fields)
        if kind == "range":
            fld = None
            m = re.match(r"^([A-Za-z0-9_.\-]+):(.*)$", text)
            if m:
                fld, text = m.group(1), m.group(2)
            return _atom_query(fld, text, self.default_fields)
        raise QueryParsingError(f"unexpected token [{text}] in query string")

    def _phrase(self, fld, phrase) -> dict:
        if fld is None:
            if len(self.default_fields) == 1:
                fld = self.default_fields[0]
            else:
                return {"multi_match": {"query": phrase,
                                        "fields": list(self.default_fields),
                                        "type": "phrase"}}
        return {"match_phrase": {fld: {"query": phrase}}}


def parse_query_string(body: dict, mappings) -> dict:
    """query_string body -> DSL dict (strict: malformed input raises)."""
    query = body.get("query")
    if not isinstance(query, str):
        raise QueryParsingError("[query_string] requires a [query] string")
    fields = body.get("fields") or (
        [body["default_field"]] if body.get("default_field") else None
    )
    if fields is None:
        fields = sorted(
            f for f, ft in mappings.fields.items() if ft.type == "text"
        ) or ["*"]
    if fields == ["*"]:
        fields = sorted(
            f for f, ft in mappings.fields.items() if ft.type == "text"
        )
    default_operator = body.get("default_operator", "or")
    toks = _tokenize_qs(query)
    parser = _QSParser(toks, fields, default_operator)
    out = parser.parse()
    if parser.pos != len(toks):
        raise QueryParsingError(f"Failed to parse query [{query}]")
    if body.get("boost"):
        out = {"bool": {"must": [out], "boost": body["boost"]}}
    return out


_SQS_SPECIAL = set('+|-"*()')


def parse_simple_query_string(body: dict, mappings) -> dict:
    """simple_query_string: forgiving subset — never raises on bad syntax
    (reference behavior: SimpleQueryStringBuilder lenient parsing)."""
    query = body.get("query")
    if not isinstance(query, str):
        raise QueryParsingError("[simple_query_string] requires a [query] string")
    fields = body.get("fields")
    if not fields or fields == ["*"]:
        fields = sorted(
            f for f, ft in mappings.fields.items() if ft.type == "text"
        )
    default_operator = body.get("default_operator", "or").lower()

    def atom(text, negate=False):
        if text.startswith('"') and text.endswith('"') and len(text) > 1:
            inner = text[1:-1]
            if len(fields) == 1:
                return {"match_phrase": {fields[0]: {"query": inner}}}
            return {"multi_match": {"query": inner, "fields": list(fields),
                                    "type": "phrase"}}
        if text.endswith("*") and len(text) > 1 and "*" not in text[:-1]:
            sub = {"bool": {"should": [
                {"prefix": {f: {"value": text[:-1].lower()}}} for f in fields
            ], "minimum_should_match": 1}} if len(fields) > 1 else {
                "prefix": {fields[0]: {"value": text[:-1].lower()}}}
            return sub
        if len(fields) == 1:
            return {"match": {fields[0]: {"query": text}}}
        return {"multi_match": {"query": text, "fields": list(fields)}}

    # split respecting quotes
    parts = re.findall(r'"[^"]*"|\S+', query)
    must, should, must_not = [], [], []
    or_next = False
    for raw in parts:
        if raw == "|":
            or_next = True
            continue
        neg = raw.startswith("-") and len(raw) > 1
        plus = raw.startswith("+") and len(raw) > 1
        body_txt = raw[1:] if (neg or plus) else raw
        body_txt = body_txt.strip("()") or body_txt
        if not body_txt or body_txt in ("|",):
            continue
        q = atom(body_txt)
        if neg:
            must_not.append(q)
        elif plus:
            must.append(q)
        elif or_next or default_operator == "or":
            should.append(q)
        else:
            must.append(q)
        or_next = False
    b = {}
    if must:
        b["must"] = must
    if should:
        b["should"] = should
        b["minimum_should_match"] = 1
    if must_not:
        b["must_not"] = must_not
    if not b:
        return {"match_all": {}}
    if list(b.keys()) == ["must"] and len(must) == 1:
        return must[0]
    if list(b.keys()) == ["should", "minimum_should_match"] and len(should) == 1:
        return should[0]
    return {"bool": b}
