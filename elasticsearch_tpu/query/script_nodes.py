"""Script-driven scoring queries: script_score, function_score, script filter.

The reference evaluates scripts per document inside the scoring loop
(reference behavior: index/query/functionscore/FunctionScoreQueryBuilder.java,
ScriptScoreQueryBuilder.java, ScriptQueryBuilder.java; functions in
common/lucene/search/function/*). Here a compiled expression becomes part of
the traced XLA program, so "per-doc script" costs one fused vector pass over
the docvalues columns — no interpreter on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..script.expression import CompiledScript, ScriptError, compile_script
from ..utils.errors import IllegalArgumentError
from .nodes import ExecContext, QueryNode


def script_env(dev: dict, fields, ctx: ExecContext, fill_missing: float = 0.0):
    """{field: float32[n]} doc-value env for a compiled script; missing
    values read as 0 (lang-expression semantics)."""
    env = {}
    n = ctx.num_docs
    for f in fields:
        if f in dev["dv_float"]:
            vals, has = dev["dv_float"][f]
        elif f in dev["dv_int"]:
            vals, has = dev["dv_int"][f]
        else:
            raise ScriptError(
                f"field [{f}] has no numeric doc values for scripting"
            )
        env[f] = jnp.where(has, vals.astype(jnp.float32), jnp.float32(fill_missing))[:n]
    return env


@dataclass
class ScriptScoreNode(QueryNode):
    """script_score: replaces the inner query's score with the script value
    (ScriptScoreQueryBuilder; negative scores are an error in the reference —
    clamped-checked here host-side is impossible, so clamp at 0)."""

    inner: QueryNode
    script: CompiledScript
    min_score: float | None = None
    boost: float = 1.0

    def prepare(self, pack):
        p, k = self.inner.prepare(pack)
        return (p,), ("script_score", self.script.source, self.min_score, k)

    def device_eval(self, dev, params, ctx):
        (p,) = params
        scores, match = self.inner.device_eval(dev, p, ctx)
        n = ctx.num_docs
        env = script_env(dev, self.script.fields, ctx)
        val = self.script.evaluate(env, score=scores[:n])
        val = jnp.maximum(val.astype(jnp.float32), 0.0) * jnp.float32(self.boost)
        out = jnp.zeros(n + 1, jnp.float32).at[:n].set(val)
        out = jnp.where(match, out, 0.0)
        if self.min_score is not None:
            match = match & (out >= self.min_score)
        return out, match


@dataclass
class ScriptFilterNode(QueryNode):
    """`script` query: filter context, matches where the expression != 0
    (ScriptQueryBuilder)."""

    script: CompiledScript
    boost: float = 1.0

    def prepare(self, pack):
        return (), ("script_filter", self.script.source)

    def device_eval(self, dev, params, ctx):
        n = ctx.num_docs
        env = script_env(dev, self.script.fields, ctx)
        ok = self.script.evaluate(env, score=None) != 0
        match = jnp.zeros(n + 1, bool).at[:n].set(ok)
        return jnp.float32(self.boost) * match.astype(jnp.float32), match


# ---------------------------------------------------------------------------
# function_score
# ---------------------------------------------------------------------------

_MODIFIERS = {
    "none": lambda x: x,
    "log": jnp.log10,
    "log1p": lambda x: jnp.log10(x + 1.0),
    "log2p": lambda x: jnp.log10(x + 2.0),
    "ln": jnp.log,
    "ln1p": jnp.log1p,
    "ln2p": lambda x: jnp.log(x + 2.0),
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "reciprocal": lambda x: 1.0 / x,
}


@dataclass
class ScoreFunction:
    kind: str  # weight | field_value_factor | script_score | random_score | decay
    filter: QueryNode | None = None
    weight: float | None = None
    # field_value_factor
    fvf_field: str | None = None
    fvf_factor: float = 1.0
    fvf_modifier: str = "none"
    fvf_missing: float | None = None
    # script_score
    script: CompiledScript | None = None
    # random_score
    seed: int = 0
    # decay
    decay_kind: str = "gauss"  # gauss | exp | linear
    decay_field: str | None = None
    origin: float = 0.0
    scale: float = 1.0
    offset: float = 0.0
    decay: float = 0.5

    def key(self):
        return (
            self.kind, self.weight, self.fvf_field, self.fvf_factor,
            self.fvf_modifier, self.fvf_missing,
            self.script.source if self.script else None,
            self.seed, self.decay_kind, self.decay_field,
            self.origin, self.scale, self.offset, self.decay,
        )

    def value(self, dev, ctx: ExecContext, scores_n):
        n = ctx.num_docs
        if self.kind == "weight":
            v = jnp.full(n, 1.0, jnp.float32)
        elif self.kind == "field_value_factor":
            f = self.fvf_field
            if f in dev["dv_float"]:
                vals, has = dev["dv_float"][f]
            elif f in dev["dv_int"]:
                vals, has = dev["dv_int"][f]
            else:
                raise IllegalArgumentError(
                    f"unable to find a field mapper for field [{f}]"
                )
            x = vals.astype(jnp.float32)[:n]
            has = has[:n]
            if self.fvf_missing is not None:
                x = jnp.where(has, x, jnp.float32(self.fvf_missing))
            # the reference errors on missing without `missing`; on device we
            # treat missing as 0 after factor/modifier (documented divergence)
            v = _MODIFIERS[self.fvf_modifier](x * jnp.float32(self.fvf_factor))
            v = jnp.where(jnp.isfinite(v), v, 0.0)
        elif self.kind == "script_score":
            env = script_env(dev, self.script.fields, ctx)
            v = self.script.evaluate(env, score=scores_n).astype(jnp.float32)
        elif self.kind == "random_score":
            # deterministic per-doc hash -> [0, 1) (RandomScoreFunction uses
            # a hash of seed+doc identity for consistent scores)
            idx = jnp.arange(n, dtype=jnp.uint32)
            h = (idx ^ jnp.uint32(self.seed * 2654435761 & 0xFFFFFFFF)) * jnp.uint32(2246822519)
            h = (h ^ (h >> 13)) * jnp.uint32(3266489917)
            h = h ^ (h >> 16)
            v = h.astype(jnp.float32) / jnp.float32(2**32)
        elif self.kind == "decay":
            f = self.decay_field
            if f in dev["dv_float"]:
                vals, has = dev["dv_float"][f]
            elif f in dev["dv_int"]:
                vals, has = dev["dv_int"][f]
            else:
                raise IllegalArgumentError(f"unknown decay field [{f}]")
            x = vals.astype(jnp.float32)[:n]
            dist = jnp.maximum(jnp.abs(x - jnp.float32(self.origin)) - jnp.float32(self.offset), 0.0)
            scale = jnp.float32(self.scale)
            decay = jnp.float32(self.decay)
            if self.decay_kind == "gauss":
                sigma2 = -(scale**2) / (2.0 * jnp.log(decay))
                v = jnp.exp(-(dist**2) / (2.0 * sigma2))
            elif self.decay_kind == "exp":
                lam = jnp.log(decay) / scale
                v = jnp.exp(lam * dist)
            else:  # linear
                s = scale / (1.0 - decay)
                v = jnp.maximum((s - dist) / s, 0.0)
            v = jnp.where(has[:n], v, 1.0)
        else:
            raise IllegalArgumentError(f"unknown score function [{self.kind}]")
        if self.weight is not None:
            v = v * jnp.float32(self.weight)
        return v


@dataclass
class FunctionScoreNode(QueryNode):
    """function_score (FunctionScoreQueryBuilder): per-function filters,
    score_mode combination across functions, boost_mode combination with the
    query score, max_boost cap, min_score cut."""

    inner: QueryNode
    functions: list[ScoreFunction] = field(default_factory=list)
    score_mode: str = "multiply"
    boost_mode: str = "multiply"
    max_boost: float = float("inf")
    min_score: float | None = None
    boost: float = 1.0

    def prepare(self, pack):
        p, k = self.inner.prepare(pack)
        fparams = []
        fkeys = []
        for fn in self.functions:
            if fn.filter is not None:
                fp, fk = fn.filter.prepare(pack)
            else:
                fp, fk = (), None
            fparams.append(fp)
            fkeys.append((fn.key(), fk))
        return (p, tuple(fparams)), (
            "function_score", k, tuple(fkeys), self.score_mode,
            self.boost_mode, self.max_boost, self.min_score,
        )

    def device_eval(self, dev, params, ctx):
        p, fparams = params
        scores, match = self.inner.device_eval(dev, p, ctx)
        n = ctx.num_docs
        scores_n = scores[:n]
        if not self.functions:
            factor = jnp.ones(n, jnp.float32)
            applied_any = jnp.zeros(n, bool)
        else:
            applies_list = []
            values_list = []
            for fn, fp in zip(self.functions, fparams):
                if fn.filter is not None:
                    _fs, fmatch = fn.filter.device_eval(dev, fp, ctx)
                    applies = fmatch[:n]
                else:
                    applies = jnp.ones(n, bool)
                applies_list.append(applies)
                values_list.append(fn.value(dev, ctx, scores_n))
            A = jnp.stack(applies_list)  # [F, n]
            V = jnp.stack(values_list)
            applied_any = A.any(axis=0)
            if self.score_mode == "multiply":
                factor = jnp.where(A, V, 1.0).prod(axis=0)
            elif self.score_mode == "sum":
                factor = jnp.where(A, V, 0.0).sum(axis=0)
            elif self.score_mode == "avg":
                cnt = A.sum(axis=0)
                factor = jnp.where(
                    cnt > 0, jnp.where(A, V, 0.0).sum(axis=0) / jnp.maximum(cnt, 1), 1.0
                )
            elif self.score_mode == "max":
                factor = jnp.where(A, V, -jnp.inf).max(axis=0)
            elif self.score_mode == "min":
                factor = jnp.where(A, V, jnp.inf).min(axis=0)
            elif self.score_mode == "first":
                first_idx = jnp.argmax(A, axis=0)
                factor = jnp.take_along_axis(V, first_idx[None], axis=0)[0]
            else:
                raise IllegalArgumentError(f"bad score_mode [{self.score_mode}]")
            factor = jnp.where(applied_any, factor, 1.0)
        factor = jnp.minimum(factor, jnp.float32(self.max_boost))

        bm = self.boost_mode
        if bm == "multiply":
            out_n = scores_n * factor
        elif bm == "replace":
            out_n = jnp.where(applied_any | (len(self.functions) == 0), factor, scores_n)
        elif bm == "sum":
            out_n = scores_n + factor
        elif bm == "avg":
            out_n = (scores_n + factor) / 2.0
        elif bm == "max":
            out_n = jnp.maximum(scores_n, factor)
        elif bm == "min":
            out_n = jnp.minimum(scores_n, factor)
        else:
            raise IllegalArgumentError(f"bad boost_mode [{bm}]")
        out_n = out_n * jnp.float32(self.boost)
        out = jnp.zeros(n + 1, jnp.float32).at[:n].set(out_n)
        out = jnp.where(match, out, 0.0)
        if self.min_score is not None:
            match = match & (out >= self.min_score)
        return out, match


# ---------------------------------------------------------------------------
# DSL parsing (wired from dsl.py)
# ---------------------------------------------------------------------------


def parse_script_score(body: dict, mappings, parse_query):
    from ..utils.errors import QueryParsingError

    if "query" not in body:
        raise QueryParsingError("[script_score] requires a [query]")
    from .nodes import mark_exact

    # scripted similarity reads the child's _score: escalate the child
    # off the quantized impact tier (index/pack.py escalation contract)
    inner = mark_exact(parse_query(body["query"], mappings))
    script = compile_script(body.get("script") or {})
    return ScriptScoreNode(
        inner, script,
        min_score=body.get("min_score"),
        boost=float(body.get("boost", 1.0)),
    )


def parse_script_filter(body: dict, mappings, parse_query):
    return ScriptFilterNode(
        compile_script(body.get("script") or {}),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_one_function(spec: dict, mappings, parse_query) -> ScoreFunction:
    from ..utils.errors import QueryParsingError

    filt = None
    if "filter" in spec:
        filt = parse_query(spec["filter"], mappings)
    weight = spec.get("weight")
    kinds = [k for k in spec if k not in ("filter", "weight")]
    if not kinds:
        return ScoreFunction("weight", filter=filt, weight=float(weight if weight is not None else 1.0))
    if len(kinds) > 1:
        raise QueryParsingError(f"more than one function in clause: {kinds}")
    kind = kinds[0]
    body = spec[kind]
    w = float(weight) if weight is not None else None
    if kind == "field_value_factor":
        return ScoreFunction(
            "field_value_factor", filter=filt, weight=w,
            fvf_field=body["field"], fvf_factor=float(body.get("factor", 1.0)),
            fvf_modifier=body.get("modifier", "none"),
            fvf_missing=body.get("missing"),
        )
    if kind == "script_score":
        return ScoreFunction(
            "script_score", filter=filt, weight=w,
            script=compile_script(body.get("script") or {}),
        )
    if kind == "random_score":
        return ScoreFunction(
            "random_score", filter=filt, weight=w, seed=int(body.get("seed", 0))
        )
    if kind in ("gauss", "exp", "linear"):
        (fld, conf), = [(k, v) for k, v in body.items() if k != "multi_value_mode"]
        from ..index.mappings import parse_date_to_millis
        from ..utils.durations import parse_duration_seconds

        ft = mappings.fields.get(fld)
        is_date = ft is not None and ft.type == "date"

        def conv(v, default=None):
            if v is None:
                return default
            if is_date:
                if isinstance(v, str):
                    try:
                        # durations like "10d" (scale/offset)
                        return float(parse_duration_seconds(v, None) * 1000.0)
                    except Exception:
                        return float(parse_date_to_millis(v))
                return float(v)
            if isinstance(v, str):
                return float(v)
            return float(v)

        if "scale" not in conf:
            raise QueryParsingError(f"[{kind}] requires [scale]")
        return ScoreFunction(
            "decay", filter=filt, weight=w, decay_kind=kind, decay_field=fld,
            origin=conv(conf.get("origin"), 0.0),
            scale=conv(conf["scale"]),
            offset=conv(conf.get("offset"), 0.0),
            decay=float(conf.get("decay", 0.5)),
        )
    raise QueryParsingError(f"unknown score function [{kind}]")


def parse_function_score(body: dict, mappings, parse_query):
    from ..utils.errors import QueryParsingError

    inner = parse_query(body.get("query"), mappings) if body.get("query") else None
    if inner is None:
        from .nodes import MatchAllNode

        inner = MatchAllNode()
    else:
        from .nodes import mark_exact

        # boost_mode multiply/avg etc. transform the child's _score —
        # keep it exact BM25, off the quantized impact tier
        mark_exact(inner)
    specs = body.get("functions")
    if specs is None:
        # single-function shorthand at top level
        specs = [{k: v for k, v in body.items()
                  if k in ("field_value_factor", "script_score", "random_score",
                           "gauss", "exp", "linear", "weight", "filter")}]
        if not any(k for k in specs[0] if k not in ("weight", "filter")) and "weight" not in specs[0]:
            specs = []
    functions = [_parse_one_function(s, mappings, parse_query) for s in specs]
    return FunctionScoreNode(
        inner,
        functions,
        score_mode=body.get("score_mode", "multiply"),
        boost_mode=body.get("boost_mode", "multiply"),
        max_boost=float(body.get("max_boost", float("inf"))),
        min_score=body.get("min_score"),
        boost=float(body.get("boost", 1.0)),
    )
