"""Field sorting + search_after (reference behavior: search/sort/
FieldSortBuilder.java -> Lucene SortField over DocValues, merged at the
coordinator by SearchPhaseController with (key..., shard, doc) order).

TPU shape: every sort key becomes an ascending-sortable device array
("transformed key space"): descending numerics negate, keyword ordinals
double (2*ord) so absent search_after values land between ordinals as odd
integers, missing values take +/- sentinels (_last/_first). The per-shard
top-k is a lax.sort over (key_1, ..., key_m, docid); the cross-shard merge
is a host-side lexsort over S*k candidates — tiny, and exactly the
coordinator-side TopFieldDocs.merge of the reference."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils.errors import IllegalArgumentError, QueryParsingError

F64_SENTINEL = np.float64(np.finfo(np.float64).max)
I64_SENTINEL = np.int64(2**62)


@dataclass
class SortField:
    field: str  # field name, or "_score" / "_doc"
    order: str = "asc"
    missing: object = "_last"

    @property
    def desc(self) -> bool:
        return self.order == "desc"


def parse_sort(spec) -> list[SortField]:
    """["f", {"f": "desc"}, {"f": {"order": "desc", "missing": "_first"}},
    "_score", ...] -> [SortField]."""
    if spec is None:
        return []
    if not isinstance(spec, list):
        spec = [spec]
    out = []
    for s in spec:
        if isinstance(s, str):
            order = "desc" if s == "_score" else "asc"
            out.append(SortField(s, order))
        elif isinstance(s, dict) and len(s) == 1:
            (fld, body), = s.items()
            if isinstance(body, str):
                out.append(SortField(fld, body))
            elif isinstance(body, dict):
                out.append(
                    SortField(
                        fld,
                        body.get("order", "desc" if fld == "_score" else "asc"),
                        body.get("missing", "_last"),
                    )
                )
            else:
                raise QueryParsingError(f"malformed sort clause for [{fld}]")
        else:
            raise QueryParsingError(f"malformed sort clause {s!r}")
    for sf in out:
        if sf.order not in ("asc", "desc"):
            raise QueryParsingError(f"unknown sort order [{sf.order}]")
    return out


def is_score_only(sort: list[SortField]) -> bool:
    return not sort or (len(sort) == 1 and sort[0].field == "_score" and sort[0].desc)


class SortPlan:
    """Host-side plan: per sort field, how to build the transformed device
    key, convert search_after values in, and convert hit keys back out."""

    def __init__(self, sort: list[SortField], pack, mappings):
        self.sort = sort
        self.fields = []  # (SortField, kind, col) kind: score|doc|int|float|ord
        self.needs_scores = False
        for sf in sort:
            if sf.field == "_score":
                self.fields.append((sf, "score", None))
                self.needs_scores = True
                continue
            if sf.field == "_doc":
                self.fields.append((sf, "doc", None))
                continue
            ft = mappings.fields.get(sf.field) if mappings else None
            if ft is not None and ft.type in ("text",):
                raise IllegalArgumentError(
                    f"Text fields are not optimised for operations that require "
                    f"per-document field data like sorting: [{sf.field}]"
                )
            col = pack.docvalues.get(sf.field)
            if col is None:
                # unmapped/absent column: every doc "missing"
                self.fields.append((sf, "absent", None))
                continue
            self.fields.append((sf, col.kind, col))

    def struct_key(self):
        return tuple(
            (sf.field, sf.order, str(sf.missing), kind)
            for sf, kind, _ in self.fields
        )

    # ---- transformed key space ------------------------------------------

    def _sentinels(self, sf, kind):
        sent = F64_SENTINEL if kind in ("float", "absent") else I64_SENTINEL
        lo = -sent
        # missing sorts last by default regardless of order (ES default)
        if sf.missing == "_last":
            return sent
        if sf.missing == "_first":
            return lo
        # concrete missing value: transform like a real value
        v = sf.missing
        if kind == "ord":
            raise IllegalArgumentError("custom missing on keyword sort not supported")
        v = float(v) if kind in ("float", "absent") else int(v)
        return -v if sf.desc else v

    def device_keys(self, dev, scores, num_docs):
        """-> tuple of [N] ascending-sortable key arrays (traced)."""
        import jax.numpy as jnp

        keys = []
        for sf, kind, col in self.fields:
            if kind == "score":
                k = -scores[:num_docs] if sf.desc else scores[:num_docs]
                keys.append(k.astype(jnp.float64))
                continue
            if kind == "doc":
                d = jnp.arange(num_docs, dtype=jnp.int64)
                keys.append(-d if sf.desc else d)
                continue
            if kind == "absent":
                keys.append(
                    jnp.full(num_docs, self._sentinels(sf, kind), jnp.float64)
                )
                continue
            if kind == "ord":
                vals, has = dev["dv_ord"][sf.field]
                k = vals.astype(jnp.int64) * 2
            elif kind == "float":
                vals, has = dev["dv_float"][sf.field]
                k = vals.astype(jnp.float64)
            else:
                vals, has = dev["dv_int"][sf.field]
                k = vals.astype(jnp.int64)
            if sf.desc:
                k = -k
            k = jnp.where(has, k, self._sentinels(sf, kind))
            keys.append(k)
        return tuple(keys)

    # ---- search_after conversion ----------------------------------------

    def after_keys(self, after_values, pack) -> tuple:
        """Original-space search_after values -> transformed key scalars."""
        if len(after_values) != len(self.fields):
            raise IllegalArgumentError(
                f"search_after has {len(after_values)} values, sort has "
                f"{len(self.fields)}"
            )
        out = []
        for v, (sf, kind, col) in zip(after_values, self.fields):
            if kind == "score":
                k = np.float64(v)
                out.append(-k if sf.desc else k)
            elif kind == "doc":
                k = np.int64(v)
                out.append(-k if sf.desc else k)
            elif kind == "absent":
                out.append(np.float64(self._sentinels(sf, kind)))
            elif kind == "ord":
                terms = col.ord_terms or []
                i = int(np.searchsorted(terms, str(v)))
                exact = i < len(terms) and terms[i] == str(v)
                k = np.int64(2 * i if exact else 2 * i - 1)
                out.append(-k if sf.desc else k)
            elif kind == "float":
                out.append(np.float64(-float(v) if sf.desc else float(v)))
            else:
                out.append(np.int64(-int(v) if sf.desc else int(v)))
        return tuple(out)

    # ---- hit values back to original space ------------------------------

    def hit_values(self, key_arrays, positions):
        """Transformed keys at hit positions -> response `sort` arrays.
        Sentinel keys (missing values) come back as None."""
        out = []
        for pos in positions:
            row = []
            for (sf, kind, col), karr in zip(self.fields, key_arrays):
                k = karr[pos]
                if kind in ("float", "absent", "score"):
                    kv = float(k)
                    if abs(kv) >= float(F64_SENTINEL):
                        row.append(None)
                        continue
                    row.append(-kv if sf.desc else kv)
                    continue
                ki = int(k)
                if abs(ki) >= int(I64_SENTINEL):
                    row.append(None)
                    continue
                ki = -ki if sf.desc else ki
                if kind == "ord":
                    terms = col.ord_terms or []
                    row.append(terms[ki // 2] if 0 <= ki // 2 < len(terms) else None)
                else:
                    row.append(ki)
            out.append(row)
        return out
