"""Block-max WAND planning for pure term disjunctions.

TPU-shaped analog of Lucene's block-max WAND early termination (reference
behavior: Lucene WANDScorer + hit-count thresholds wired through
search/query/QueryPhaseCollectorManager.java:416). Branchy doc-at-a-time
skipping becomes a two-launch plan:

  pass 1: score only each term's best few blocks (by per-block upper-bound
          score) -> the k-th partial score is a LOWER bound θ on the true
          k-th score (every doc's partial sum <= its true sum);
  pass 2: keep only blocks whose upper bound could still matter
          (ub_t(block) + Σ_{t'≠t} max-ub(t') >= θ) and rescore exactly.

Soundness: a true top-k doc d has score(d) >= θ; for any block b∋d of term
t, ub_t(b) + Σ_{t'≠t} max-ub(t') >= score(d) >= θ, so every block carrying a
top-k doc survives — pass 2's top-k equals the exhaustive top-k (scores AND
docids; ties keep on the >= comparison). Pruned blocks only remove score
mass from docs provably outside the top-k, so the pass-2 hit count is a
LOWER bound: callers must report hits.total with relation "gte" (exactly the
reference's track_total_hits threshold contract).

Per-block upper bound (BM25 is monotone ↑ in tf and ↓ in dl):

  ub(block) = weight * max_tf / (max_tf + k1*(1 - b + b*min_dl/avgdl))

computed from the pack's block_max_tf / block_min_len metadata with the
EXECUTION avgdl (the global dfs stats — not the shard-build avgdl, which
would be unsound when shards skew).
"""

from __future__ import annotations

import numpy as np

from .nodes import BoolNode, TermNode, _bucket


def should_terms(node) -> list[TermNode] | None:
    """The term list of a pure scoring disjunction, else None.

    Pure = bool with only `should` clauses (>= 2), minimum_should_match <= 1,
    every clause a TermNode, positive boost. (`match` on text parses to
    exactly this shape — query/dsl.py.)
    """
    if not isinstance(node, BoolNode):
        return None
    if node.must or node.filter or node.must_not:
        return None
    if len(node.should) < 2:
        return None
    if node._msm() > 1:
        return None
    if not node.boost > 0.0:
        return None
    if not all(type(c) is TermNode for c in node.should):
        return None
    if not all(c.boost >= 0.0 for c in node.should):
        return None
    return list(node.should)


def term_row_ubf(
    pack, start: int, count: int,
    avgdl: float, has_norms: bool, k1: float, b: float,
) -> tuple[np.ndarray, np.ndarray]:
    """-> (rows sorted by tf-saturation upper bound desc, ubf in that order).

    ubf is the WEIGHT-FREE bound (max_tf saturation with the block's most
    favorable doc length); a term's block score bound = weight * ubf, so one
    cached (rows, ubf) pair serves every query/boost of the term."""
    rows = np.arange(start, start + count, dtype=np.int32)
    mtf = pack.block_max_tf[rows]
    if has_norms:
        K = k1 * (1.0 - b + b * pack.block_min_len[rows] / max(avgdl, 1e-9))
    else:
        K = np.float32(k1)
    ubf = mtf / np.maximum(mtf + K, 1e-9)
    order = np.argsort(-ubf, kind="stable")
    return rows[order], ubf[order].astype(np.float32)


def pad_rows_to(rows: np.ndarray, width: int) -> np.ndarray:
    """Pad a row list with the reserved all-padding row 0 to `width`."""
    out = np.zeros(width, np.int32)
    out[: len(rows)] = rows
    return out


def bucket_width(n: int) -> int:
    return _bucket(max(n, 1))


# number of fixed doc-id windows per shard used to localize the other-terms
# bound (the analog of Lucene's per-docid-range block maxes: a rare term
# contributes nothing to ranges it has no postings in)
WINDOWS = 64


def _posting_windows(pack, rows: np.ndarray, num_docs: int):
    """Per-lane window ids + validity for the given block rows."""
    docids = pack.post_docids[rows]  # [B, 128]
    valid = pack.post_tfs[rows] > 0
    w_of = (docids.astype(np.int64) * WINDOWS // max(num_docs, 1)).clip(
        0, WINDOWS - 1)
    return w_of, valid


def window_ub_csr(pack, rows, ubs, num_docs: int) -> np.ndarray:
    """[WINDOWS] per-window max upper-bound score of a CSR term — exact
    posting coverage: a window only carries a bound where the term actually
    has postings (a rare term bounds ~0 over most of doc space)."""
    out = np.zeros(WINDOWS, np.float32)
    if len(rows) == 0 or num_docs == 0:
        return out
    w_of, valid = _posting_windows(pack, rows, num_docs)
    ub_lanes = np.broadcast_to(np.asarray(ubs)[:, None], w_of.shape)
    np.maximum.at(out, w_of[valid], ub_lanes[valid])
    return out


def window_tfn_dense(tfn_row: np.ndarray, num_docs: int) -> np.ndarray:
    """[WINDOWS] per-window max tfn of a dense-tier term's row (weight-free;
    a term's window score bound = weight * this)."""
    out = np.zeros(WINDOWS, np.float32)
    if num_docs == 0:
        return out
    # ceil edges: window w covers exactly {d : d*WINDOWS//num_docs == w},
    # matching _posting_windows' assignment (floor edges would exclude up
    # to one boundary doc per window and under-bound it)
    edges = (np.arange(WINDOWS + 1) * num_docs + WINDOWS - 1) // WINDOWS
    for w in range(WINDOWS):
        a, b_ = edges[w], edges[w + 1]
        if b_ > a:
            out[w] = float(tfn_row[a:b_].max())
    return out


def prune_blocks(
    pack,
    num_docs: int,
    rows: np.ndarray,
    ubs: np.ndarray,
    other_window_ub: np.ndarray,  # [WINDOWS] Σ of OTHER terms' window maxes
    theta: float,
) -> np.ndarray:
    """Surviving block rows of one term: keep block b iff
    ub(b) + max over b's postings' windows of Σ-other-terms' window bound
    >= theta (any doc d in b scores <= ub(b) + other_window_ub[window(d)])."""
    if len(rows) == 0:
        return rows
    if not np.isfinite(theta):
        return rows if theta < 0 else rows[:0]
    w_of, valid = _posting_windows(pack, rows, num_docs)
    vals = np.where(valid, other_window_ub[w_of], -np.inf)
    local = vals.max(axis=1)
    keep = np.asarray(ubs) + local >= theta
    return rows[keep]
