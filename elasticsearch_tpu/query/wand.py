"""Block-max WAND planning for pure term disjunctions — EXPERIMENTAL.

Demoted from the production searchers in PR 8 (ES_TPU_WAND=1 re-enables):
six rounds of measurement never found a regime where the two-pass pruned
plan beats batched exhaustive execution on this hardware by the >1.5x the
ROADMAP demanded — r05's crossover sweep engaged nowhere at 1M docs, and
the r08 rerun against the eager impact tier (BM25S gather+sum, whose
bytes/query is a strict subset of WAND pass-2's) only widened the gap:
pruning saves a fraction of a bandwidth-bound scan that batched kernels
already amortize, while paying an extra device round trip plus host-side
posting compaction per query. The planner below stays import-clean and
flag-gated (search_wand* / search_pruned_batch in parallel/sharded.py,
exercised by tests/test_wand.py) so the verdict remains re-measurable on
future hardware; `hits.total` relation semantics are unchanged when it
engages.

TPU-shaped analog of Lucene's block-max WAND early termination (reference
behavior: Lucene WANDScorer + hit-count thresholds wired through
search/query/QueryPhaseCollectorManager.java:416). Branchy doc-at-a-time
skipping becomes a two-launch plan:

  pass 1: score only each term's best few blocks (by per-block upper-bound
          score) -> the k-th partial score is a LOWER bound θ on the true
          k-th score (every doc's partial sum <= its true sum);
  pass 2: keep only blocks whose upper bound could still matter
          (ub_t(block) + Σ_{t'≠t} max-ub(t') >= θ) and rescore exactly.

Soundness: a true top-k doc d has score(d) >= θ; for any block b∋d of term
t, ub_t(b) + Σ_{t'≠t} max-ub(t') >= score(d) >= θ, so every block carrying a
top-k doc survives — pass 2's top-k equals the exhaustive top-k (scores AND
docids; ties keep on the >= comparison). Pruned blocks only remove score
mass from docs provably outside the top-k, so the pass-2 hit count is a
LOWER bound: callers must report hits.total with relation "gte" (exactly the
reference's track_total_hits threshold contract).

Per-block upper bound (BM25 is monotone ↑ in tf and ↓ in dl):

  ub(block) = weight * max_tf / (max_tf + k1*(1 - b + b*min_dl/avgdl))

computed from the pack's block_max_tf / block_min_len metadata with the
EXECUTION avgdl (the global dfs stats — not the shard-build avgdl, which
would be unsound when shards skew).
"""

from __future__ import annotations

import os

import numpy as np

from .nodes import BoolNode, TermNode, _bucket


def wand_enabled() -> bool:
    """ES_TPU_WAND (default off): the experimental flag gating block-max
    WAND in the production searchers. The direct entry points
    (StackedSearcher.search_wand / search_pruned_batch) ignore the flag —
    they ARE the experimental path — only the `prune_floor` routing in
    `search` / the serving waves consults it."""
    return os.environ.get("ES_TPU_WAND", "0") not in ("0", "")


def should_terms(node) -> list[TermNode] | None:
    """The term list of a pure scoring disjunction, else None.

    Pure = bool with only `should` clauses (>= 2), minimum_should_match <= 1,
    every clause a TermNode, positive boost. (`match` on text parses to
    exactly this shape — query/dsl.py.)
    """
    if not isinstance(node, BoolNode):
        return None
    if node.must or node.filter or node.must_not:
        return None
    if len(node.should) < 2:
        return None
    if node._msm() > 1:
        return None
    if not node.boost > 0.0:
        return None
    if not all(type(c) is TermNode for c in node.should):
        return None
    if not all(c.boost >= 0.0 for c in node.should):
        return None
    return list(node.should)


def term_row_ubf(
    pack, start: int, count: int,
    avgdl: float, has_norms: bool, k1: float, b: float,
) -> tuple[np.ndarray, np.ndarray]:
    """-> (rows sorted by tf-saturation upper bound desc, ubf in that order).

    ubf is the WEIGHT-FREE bound (max_tf saturation with the block's most
    favorable doc length); a term's block score bound = weight * ubf, so one
    cached (rows, ubf) pair serves every query/boost of the term."""
    rows = np.arange(start, start + count, dtype=np.int32)
    mtf = pack.block_max_tf[rows]
    if has_norms:
        K = k1 * (1.0 - b + b * pack.block_min_len[rows] / max(avgdl, 1e-9))
    else:
        K = np.float32(k1)
    ubf = mtf / np.maximum(mtf + K, 1e-9)
    order = np.argsort(-ubf, kind="stable")
    return rows[order], ubf[order].astype(np.float32)


def pad_rows_to(rows: np.ndarray, width: int) -> np.ndarray:
    """Pad a row list with the reserved all-padding row 0 to `width`."""
    out = np.zeros(width, np.int32)
    out[: len(rows)] = rows
    return out


def bucket_width(n: int) -> int:
    return _bucket(max(n, 1))


# legacy default window count; real plans use windows_for(num_docs) —
# fine windows are what make the other-terms bound local enough to prune
# (the analog of Lucene's per-docid-range block maxes: a rare term
# contributes nothing to ranges it has no postings in)
WINDOWS = 64


def windows_for(num_docs: int) -> int:
    """Window count for a shard: ~32 docs per window, pow2-clamped.

    Granularity drives pruning yield. A posting of term t survives doc-level
    pruning iff its own exact score + the OTHER terms' bound in its window
    reaches θ; with W windows a window is other-term-free with probability
    ~exp(-Σ df_other / W), so W must be of order Σ df_other (i.e. ~N/32 at
    Zipf loads) before most windows bound to zero. The round-2 fixed 64
    windows made every window carry every mid-frequency term's bound —
    measured zero pruning at 1M docs (VERDICT round 2, weak #4)."""
    w = max(64, min(num_docs // 32, 1 << 15))
    return 1 << (w - 1).bit_length()


def _posting_windows(pack, rows: np.ndarray, num_docs: int, windows: int):
    """Per-lane window ids + validity for the given block rows."""
    docids = pack.post_docids[rows]  # [B, 128]
    valid = pack.post_tfs[rows] > 0
    w_of = (docids.astype(np.int64) * windows // max(num_docs, 1)).clip(
        0, windows - 1)
    return w_of, valid


def window_ub_csr(pack, rows, ubs, num_docs: int, windows: int) -> np.ndarray:
    """[windows] per-window max upper-bound score of a CSR term — exact
    posting coverage: a window only carries a bound where the term actually
    has postings (a rare term bounds ~0 over most of doc space)."""
    out = np.zeros(windows, np.float32)
    if len(rows) == 0 or num_docs == 0:
        return out
    w_of, valid = _posting_windows(pack, rows, num_docs, windows)
    ub_lanes = np.broadcast_to(np.asarray(ubs)[:, None], w_of.shape)
    np.maximum.at(out, w_of[valid], ub_lanes[valid])
    return out


def window_tfn_dense(tfn_row: np.ndarray, num_docs: int, windows: int) -> np.ndarray:
    """[windows] per-window max tfn of a dense-tier term's row (weight-free;
    a term's window score bound = weight * this)."""
    out = np.zeros(windows, np.float32)
    if num_docs == 0:
        return out
    # ceil edges: window w covers exactly {d : d*windows//num_docs == w},
    # matching _posting_windows' assignment (floor edges would exclude up
    # to one boundary doc per window and under-bound it)
    edges = (np.arange(windows + 1) * num_docs + windows - 1) // windows
    nonempty = edges[1:] > edges[:-1]
    segmax = np.maximum.reduceat(tfn_row, edges[:-1].clip(0, num_docs - 1))
    out[nonempty] = segmax[nonempty]
    return out


def prune_blocks(
    pack,
    num_docs: int,
    rows: np.ndarray,
    ubs: np.ndarray,
    other_window_ub: np.ndarray,  # [windows] Σ of OTHER terms' window maxes
    theta: float,
    windows: int,
) -> np.ndarray:
    """Surviving block rows of one term: keep block b iff
    ub(b) + max over b's postings' windows of Σ-other-terms' window bound
    >= theta (any doc d in b scores <= ub(b) + other_window_ub[window(d)])."""
    if len(rows) == 0:
        return rows
    if not np.isfinite(theta):
        return rows if theta < 0 else rows[:0]
    w_of, valid = _posting_windows(pack, rows, num_docs, windows)
    vals = np.where(valid, other_window_ub[w_of], -np.inf)
    local = vals.max(axis=1)
    keep = np.asarray(ubs) + local >= theta
    return rows[keep]


def prune_postings(
    pack,
    num_docs: int,
    rows: np.ndarray,  # this term's block rows (unsorted order fine)
    weight: float,
    avgdl: float,
    has_norms: bool,
    k1: float,
    b: float,
    other_window_ub: np.ndarray,  # [windows] Σ of OTHER terms' window maxes
    theta: float,
    windows: int,
):
    """DOC-level pruning: keep posting p iff its EXACT self score plus the
    other-terms' bound of p's window reaches θ; compact survivors into
    synthetic posting blocks.

    This is the TPU analog of Lucene WANDScorer advancing doc-at-a-time past
    non-competitive docs: block-level pruning cannot help mid-frequency
    disjunctions (every 128-posting block's docid span overlaps other terms'
    postings somewhere), but per-posting tests against fine windows prune
    exactly the docs a DAAT scorer would skip. Soundness: score(d) =
    self(d) + Σ_other contrib(d) <= self(d) + other_window_ub[window(d)],
    so a dropped posting's doc is provably below θ *for its contribution
    via this term*; since every term applies the same test, a true top-k
    doc keeps all its postings (its full score >= θ implies the test holds
    for each of its terms with the EXACT self part included).

    -> (docids [B',128] i32, tfs [B',128] f32, dls [B',128] f32,
        kept_postings, total_postings)
    """
    docids = pack.post_docids[rows]
    tfs = pack.post_tfs[rows]
    dls = pack.post_dls[rows]
    valid = tfs > 0
    total = int(valid.sum())
    if not np.isfinite(theta):
        if theta < 0:
            return docids, tfs, dls, total, total
        return (np.full((1, docids.shape[1]), num_docs, np.int32),
                np.zeros((1, docids.shape[1]), np.float32),
                np.ones((1, docids.shape[1]), np.float32), 0, total)
    if has_norms:
        K = k1 * (1.0 - b + b * dls / max(avgdl, 1e-9))
    else:
        K = k1
    self_score = weight * tfs / np.maximum(tfs + K, 1e-9)
    w_of = (docids.astype(np.int64) * windows // max(num_docs, 1)).clip(
        0, windows - 1)
    keep = valid & (self_score + other_window_ub[w_of] >= theta)
    kept = int(keep.sum())
    BLOCK = docids.shape[1]
    nb = max(1, (kept + BLOCK - 1) // BLOCK)
    out_d = np.full((nb, BLOCK), num_docs, np.int32)
    out_t = np.zeros((nb, BLOCK), np.float32)
    out_l = np.ones((nb, BLOCK), np.float32)
    if kept:
        sel = keep.reshape(-1)
        out_d.reshape(-1)[:kept] = docids.reshape(-1)[sel]
        out_t.reshape(-1)[:kept] = tfs.reshape(-1)[sel]
        out_l.reshape(-1)[:kept] = dls.reshape(-1)[sel]
    return out_d, out_t, out_l, kept, total
