from .app import make_app

__all__ = ["make_app"]
